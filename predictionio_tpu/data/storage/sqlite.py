"""SQLite storage backend — the persistent embedded default.

Plays the role of the reference's JDBC backend
(data/src/main/scala/io/prediction/data/storage/jdbc/): one database file
holds the metadata tables and per-app/channel event tables named
``events_<app>[_<channel>]`` (the reference's table-per-app/channel scheme,
JDBCUtils/HBEventsUtil). Event rows carry a millisecond timestamp column for
ordered range scans (the role of the HBase row-key time component,
hbase/HBEventsUtil.scala:82-130).

Write-path scale-out (the role of the reference's HBase region servers):

- **Group commit.** Single-event inserts do not commit their own
  transaction. REST worker threads enqueue rows onto a bounded per-shard
  queue; a committer thread per shard coalesces queued rows into ONE
  multi-row transaction (flush at ``GROUP_COMMIT_EVENTS`` rows or
  ``GROUP_COMMIT_MS`` after the batch opened, whichever first — a solo
  row with an idle queue flushes immediately). The caller's ``insert``
  returns only after its batch's COMMIT, so the 201 ack still means
  durable-to-WAL; what changes is that N concurrent inserts now cost one
  commit instead of N.

- **Hash sharding.** With ``PIO_STORAGE_SOURCES_<NAME>_SHARDS = K`` (>1),
  single-event rows split across K independent sqlite files
  (``<path>.shard<k>``) by a stable hash of the entity id. Each shard has
  its own connection, lock, WAL write slot, and committer — concurrent
  writers stop serializing on one lock. The main file keeps the metadata
  tables, the columnar page store, and the (possibly pre-sharding) row
  table, which participates in every scan as shard "-1"; turning shards
  on for an existing database is therefore seamless. Events of one
  entity always land in one shard, so per-entity order is preserved and
  the streaming scan's counting-sort merge reproduces the single-file
  wire byte-for-byte (``ops/streaming.py``).
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import json
import logging
import os
import queue as _queue
import time as _time
import zlib

from predictionio_tpu.utils.fs import fs_basedir
import sqlite3
import threading
from typing import Dict, Iterator, List, Optional, Sequence

from predictionio_tpu.data.event import (
    DataMap,
    Event,
    format_iso8601,
    new_event_id,
    parse_iso8601,
)
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import (
    UNSET,
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EngineManifest,
    EvaluationInstance,
    Model,
    OptFilter,
    PartialBatchError,
    StorageError,
)


logger = logging.getLogger(__name__)


def _ms(t: _dt.datetime) -> int:
    if t.tzinfo is None:
        t = t.replace(tzinfo=_dt.timezone.utc)
    return int(t.timestamp() * 1000)


def _utc_iso(t: _dt.datetime) -> str:
    """UTC-normalized fixed-width ISO8601, so lexicographic TEXT ordering is
    chronological (used for instance start/end times in ORDER BY)."""
    if t.tzinfo is None:
        t = t.replace(tzinfo=_dt.timezone.utc)
    return format_iso8601(t.astimezone(_dt.timezone.utc))


class _LockedCursor:
    """Runs a statement under the client lock and materializes results, so
    concurrent REST worker threads never interleave cursor state on the
    shared connection."""

    __slots__ = ("_rows", "rowcount", "lastrowid")

    def __init__(self, client: "StorageClient", sql: str, params=()):
        with client.lock:
            cur = client.conn.execute(sql, params)
            self._rows = cur.fetchall() if cur.description is not None else []
            self.rowcount = cur.rowcount
            self.lastrowid = cur.lastrowid

    def fetchone(self):
        return self._rows[0] if self._rows else None

    def fetchall(self):
        return self._rows


def _open_wal_conn(path: str) -> sqlite3.Connection:
    """Open a writer connection in the mode every concurrent path here
    assumes: WAL (readers on other connections see a consistent snapshot
    while one writer proceeds), busy_timeout for multi-process writers
    (gateway + CLI) briefly contending for the single WAL write slot, and
    synchronous=NORMAL — WAL's standard production pairing: commits
    append to the WAL without an fsync each (integrity is preserved on
    crash; only the tail of very recent commits may be lost on power
    failure). Per-event REST ingest is commit-bound — FULL measured ~380
    events/s vs ~thousands with NORMAL on the same rig."""
    conn = sqlite3.connect(path, check_same_thread=False)
    conn.execute("PRAGMA journal_mode=WAL")
    conn.execute("PRAGMA busy_timeout=5000")
    conn.execute("PRAGMA synchronous=NORMAL")
    return conn


class _InsertUnit:
    """One atomic slice of committer work: a statement plus the rows to
    executemany it with. All rows of a unit commit together or not at
    all — a unit is one REST insert (1 row) or one ``insert_batch`` slice
    (the ``/batch/events.json`` group), so a reader can never observe a
    torn unit."""

    __slots__ = ("sql", "rows", "error", "done", "trace")

    def __init__(self, sql: str, rows: list):
        self.sql = sql
        self.rows = rows
        self.error: Optional[BaseException] = None
        self.done = threading.Event()
        # the caller's ambient trace (if any), captured HERE because
        # submit() runs on the caller's thread — the committer thread
        # records its flush span into each unit's trace
        from predictionio_tpu.utils import tracing as _tracing

        self.trace = _tracing.current()

    # generous: a unit is at most one committer flush (~512 rows), but
    # it may queue behind a full backlog on a slow disk — this bound
    # exists to surface a wedged committer, not to deadline healthy I/O
    WAIT_S = 600.0

    def wait(self, timeout: Optional[float] = None) -> None:
        if not self.done.wait(self.WAIT_S if timeout is None else timeout):
            # the unit is NOT cancelled — it may still commit after this
            # raises, so the outcome is unknown, not "failed": a caller
            # that blind-retries could duplicate the event
            raise StorageError(
                "group-commit writer did not resolve within "
                f"{self.WAIT_S if timeout is None else timeout}s; "
                "outcome UNKNOWN (the batch may still commit) — "
                "investigate the committer before retrying"
            )
        if self.error is not None:
            raise self.error


class _GroupCommitter:
    """Per-shard group-commit thread: worker threads enqueue
    :class:`_InsertUnit`s on a bounded queue; this thread coalesces them
    into one multi-row transaction. Flush policy: at ``max_rows`` rows or
    ``max_delay_s`` after the batch opened, whichever first; a solo unit
    with an idle queue flushes immediately, so sequential callers pay no
    accumulation latency — batching kicks in exactly when concurrency
    exists. Callers block on ``unit.wait()``, so their ack still means
    the rows are committed (durable to the WAL)."""

    _STOP = object()

    # watchdog deadline for one flush: a healthy multi-row COMMIT is
    # milliseconds; a flush silent past this long while mid-batch flips
    # every in-process server's /readyz to 503 (utils/health.py). Class
    # attribute so tests (and operators with slow disks) can tune it
    # before opening storage.
    HEARTBEAT_DEADLINE_S = 30.0

    # admission control: at most this many queued units, and a submit
    # blocked longer than the admission window is REFUSED with
    # StorageSaturatedError instead of parking the caller's handler
    # thread behind a wedged committer (frontends answer it as 503 +
    # Retry-After). Class attributes so tests can shrink them before
    # opening storage.
    QUEUE_MAX_UNITS = 4096
    ADMIT_WAIT_S = 0.25

    def __init__(self, shard: "_ShardState", max_rows: int, max_delay_s: float):
        from predictionio_tpu.utils import health as _health
        from predictionio_tpu.utils import metrics as _metrics

        self._shard = shard
        self._max_rows = max(1, int(max_rows))
        self._max_delay_s = max(0.0, float(max_delay_s))
        self._q: "_queue.Queue[_InsertUnit]" = _queue.Queue(
            maxsize=self.QUEUE_MAX_UNITS
        )
        self._thread: Optional[threading.Thread] = None
        self._start_lock = threading.Lock()
        # per-shard flush accounting in the process-global registry
        # (labels carry the shard file name, so a K-sharded store shows
        # K series): flush count, rows per flush, commit latency
        reg = _metrics.get_registry()
        shard_name = os.path.basename(shard.path) or shard.path
        self._m_flushes = reg.counter(
            "pio_group_commit_flushes_total",
            "Group-commit flushes (one multi-row COMMIT each)",
            labels=("shard",),
        ).labels(shard=shard_name)
        self._m_flush_rows = reg.histogram(
            "pio_group_commit_flush_rows",
            "Rows coalesced into one group-commit flush",
            labels=("shard",),
            buckets=_metrics.ROW_COUNT_BUCKETS,
        ).labels(shard=shard_name)
        self._m_flush_seconds = reg.histogram(
            "pio_group_commit_flush_seconds",
            "Wall clock of one group-commit flush (execute + COMMIT)",
            labels=("shard",),
            buckets=_metrics.LATENCY_BUCKETS_S,
        ).labels(shard=shard_name)
        # daemon watchdog: busy exactly for the span of one flush, so a
        # wedged COMMIT (locked file, dead disk) reads as a stall while
        # an idle committer stays healthy. Keyed by shard file name like
        # the flush metrics — committers of one process that share a
        # basename share the verdict, which is what readiness wants.
        self._hb = _health.heartbeat(
            f"sqlite-committer:{shard_name}",
            deadline_s=self.HEARTBEAT_DEADLINE_S,
        )
        # a same-named heartbeat may predate this committer (an earlier
        # store in this process); the CURRENT class deadline wins
        self._hb.deadline_s = float(self.HEARTBEAT_DEADLINE_S)

    def close(self, timeout: float = 10.0) -> None:
        """Drain-and-stop: queued units ahead of the sentinel still
        commit, then the thread exits. Idempotent; a never-started
        committer has nothing to stop."""
        t = self._thread
        if t is None or not t.is_alive():
            return
        self._q.put(self._STOP)
        t.join(timeout)

    def submit(self, sql: str, rows: list) -> _InsertUnit:
        unit = _InsertUnit(sql, rows)
        if self._thread is None:
            with self._start_lock:
                if self._thread is None:
                    t = threading.Thread(
                        target=self._run, daemon=True,
                        name="sqlite-group-commit",
                    )
                    t.start()
                    self._thread = t
        try:
            # bounded admission: refuse (typed) rather than park the
            # caller unboundedly when the queue is saturated — REST
            # frontends turn the refusal into 503 + Retry-After
            self._q.put(unit, timeout=self.ADMIT_WAIT_S)
        except _queue.Full:
            from predictionio_tpu.utils import metrics as _metrics

            _metrics.get_registry().counter(
                "pio_group_commit_saturated_total",
                "Write submissions refused because the group-commit "
                "queue stayed full past the admission window "
                "(surfaced to clients as 503 + Retry-After)",
                labels=("shard",),
            ).labels(
                shard=os.path.basename(self._shard.path) or self._shard.path
            ).inc()
            raise base.StorageSaturatedError(
                f"group-commit queue for {self._shard.path!r} is "
                f"saturated ({self.QUEUE_MAX_UNITS} queued units); "
                "the write was NOT accepted — retry after backoff",
                retry_after_s=1.0,
            )
        return unit

    def _run(self) -> None:
        while True:
            try:
                if not self._drain_one_batch():
                    return  # close() sentinel
            except BaseException:  # the loop must survive anything —
                # but never silently: an exception here (outside
                # _commit_batch's own handling) means some units may
                # never resolve and their callers will time out
                logger.exception(
                    "group-commit loop error; queued units may be lost"
                )
                continue

    def _drain_one_batch(self) -> bool:
        unit = self._q.get()
        if unit is self._STOP:
            return False
        batch = [unit]
        n = len(unit.rows)
        deadline = _time.monotonic() + self._max_delay_s
        while n < self._max_rows:
            try:
                nxt = self._q.get_nowait()
            except _queue.Empty:
                if len(batch) == 1:
                    break  # solo unit, idle queue: zero added latency
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=remaining)
                except _queue.Empty:
                    break
            if nxt is self._STOP:
                self._q.put(nxt)  # commit this batch, stop next round
                break
            batch.append(nxt)
            n += len(nxt.rows)
        self._commit_batch(batch)
        return True

    def _commit_batch(self, batch: list) -> None:
        from predictionio_tpu.utils import tracing as _tracing
        from predictionio_tpu.utils.compilation_cache import compile_site

        t0 = _time.perf_counter()
        t0_wall = _time.time()
        shard = self._shard
        # the flush is a latency-critical site: an executable compile
        # in here (nothing should compile during an ingest flush, which
        # is exactly why one must be loudly attributable) counts in
        # pio_cold_compiles_total{site="ingest"}
        with self._hb.busy(), compile_site("ingest"), shard.lock:
            try:
                for u in batch:
                    shard.conn.executemany(u.sql, u.rows)
                fault = shard.commit_fault  # test-only crash injection
                if fault is not None:
                    fault()
                shard.conn.commit()
            except BaseException as e:
                try:
                    shard.conn.rollback()
                except sqlite3.Error:
                    pass
                if len(batch) == 1:
                    batch[0].error = e
                else:
                    # poison isolation: replay each unit as its own
                    # transaction so one bad unit cannot fail its
                    # coalesced neighbors; each replay stays unit-atomic
                    # and consults the fault hook too, so crash tests
                    # can abort coalesced batches, not just solo units
                    for u in batch:
                        try:
                            shard.conn.executemany(u.sql, u.rows)
                            fault = shard.commit_fault
                            if fault is not None:
                                fault()
                            shard.conn.commit()
                        except BaseException as ue:
                            try:
                                shard.conn.rollback()
                            except sqlite3.Error:
                                pass
                            u.error = ue
            finally:
                # bookkeeping BEFORE done.set(): a caller unblocked by
                # its unit must observe the flush span/counters of the
                # COMMIT that acked it (and never block on a recording
                # failure)
                try:
                    elapsed = _time.perf_counter() - t0
                    n_rows = sum(len(u.rows) for u in batch)
                    self._m_flushes.inc()
                    self._m_flush_rows.observe(n_rows)
                    self._m_flush_seconds.observe(elapsed)
                    for u in batch:
                        if u.trace is not None:
                            _tracing.record_span(
                                "group-commit-flush", u.trace.trace_id,
                                parent_id=u.trace.span_id, start_s=t0_wall,
                                duration_s=elapsed,
                                attrs={"rows": n_rows, "units": len(batch)},
                            )
                except Exception:
                    logger.exception("group-commit flush bookkeeping failed")
                for u in batch:
                    u.done.set()


class _ShardState:
    """One event-row write slot: a sqlite connection, its lock, its
    thread-local WAL snapshot read connections, and its group committer.
    The main database file is wrapped in one of these (sharing the
    client's connection and lock); with ``SHARDS`` > 1, each shard file
    gets an independent one — an independent WAL write slot."""

    def __init__(
        self,
        path: str,
        conn: sqlite3.Connection,
        lock,
        gc_rows: int,
        gc_delay_s: float,
    ):
        self.path = path
        self.conn = conn
        self.lock = lock
        self._read_local = threading.local()
        # memoized POSITIVE table-existence results (see _exists_memo)
        self.known_tables: set = set()
        # test-only fault injection: called between the batch's last
        # execute and its COMMIT (crash-consistency tests)
        self.commit_fault = None
        self.committer = _GroupCommitter(self, gc_rows, gc_delay_s)

    @staticmethod
    def open(path: str, gc_rows: int, gc_delay_s: float) -> "_ShardState":
        return _ShardState(
            path, _open_wal_conn(path), threading.RLock(), gc_rows,
            gc_delay_s,
        )

    def execute(self, sql: str, params=()) -> _LockedCursor:
        return _LockedCursor(self, sql, params)

    def commit(self) -> None:
        with self.lock:
            self.conn.commit()

    def read_execute(self, sql: str, params=()):
        """Run a read-only statement on a thread-local WAL connection —
        no writer lock held, so long scans and concurrent writes overlap.
        Returns a live cursor (fetchone/fetchall). :memory: databases are
        not shareable across connections and fall back to the locked
        shared connection.

        Because the existence check and the read no longer share one lock
        scope, a concurrent table drop (app delete) can surface here as
        sqlite's raw OperationalError — it is re-raised as StorageError so
        read paths keep their documented error contract."""
        if self.path == ":memory:":
            return self.execute(sql, params)
        conn = getattr(self._read_local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self.path)
            conn.execute("PRAGMA busy_timeout=5000")
            conn.execute("PRAGMA query_only=ON")
            self._read_local.conn = conn
        try:
            return conn.execute(sql, params)
        except sqlite3.OperationalError as e:
            if "no such table" in str(e):
                raise StorageError(str(e)) from e
            raise

    def read_snapshot(self, stmts):
        """Run several read statements inside ONE read transaction, so
        they observe a single WAL snapshot — the segment-tier scans need
        the compaction watermark and the segment manifest to be a
        consistent pair (a compaction commits both in one transaction;
        two autocommit reads could straddle it and double- or
        zero-count the sealed rows). Returns a list of fetchall lists.
        :memory: databases fall back to the shared locked connection
        (writes serialize on the same lock, so the pair is consistent
        there too)."""
        if self.path == ":memory:":
            with self.lock:
                return [
                    self.conn.execute(sql, params).fetchall()
                    for sql, params in stmts
                ]
        conn = self.read_execute("SELECT 1").connection
        out = []
        conn.execute("BEGIN")
        try:
            for sql, params in stmts:
                try:
                    out.append(conn.execute(sql, params).fetchall())
                except sqlite3.OperationalError as e:
                    if "no such table" in str(e):
                        raise StorageError(str(e)) from e
                    raise
        finally:
            conn.execute("COMMIT")
        return out

    def has_table(self, table: str) -> bool:
        """Memoized (positive results only) existence probe against THIS
        shard's file; a table created later must be seen, so negatives
        re-probe."""
        if table in self.known_tables:
            return True
        row = self.execute(
            "SELECT name FROM sqlite_master WHERE type='table' AND name=?",
            (table,),
        ).fetchone()
        if row is not None:
            self.known_tables.add(table)
            return True
        return False

    def submit_rows(self, sql: str, rows: list) -> _InsertUnit:
        """Hand rows to the group committer; returns the unit to wait
        on. The caller sees the commit (or the unit's error) via
        ``unit.wait()``."""
        return self.committer.submit(sql, rows)


class StorageClient(base.DAOCacheMixin):
    """Shared sqlite connection per source (reference caches clients per
    source name, Storage.scala:202-208). ``check_same_thread=False`` plus a
    lock serializes WRITE access from REST worker threads; bulk reads run
    on per-thread WAL snapshot connections (``read_execute``), so a
    training scan never blocks ingest and ingest never stalls a scan —
    the concurrency role of the reference's HBase client pool +
    region-parallel reads (hbase/StorageClient.scala:40,
    HBPEvents.scala:84-90).

    Source properties (``PIO_STORAGE_SOURCES_<NAME>_<KEY>``):

    - ``PATH``: database file (default ``<fs_basedir>/storage.db``)
    - ``SHARDS``: event-row shard count K (default 1). K > 1 opens K
      extra files ``<PATH>.shard<k>``, each an independent WAL write
      slot with its own group committer; single-event inserts hash to a
      shard by entity id (module docstring).
    - ``GROUP_COMMIT_EVENTS`` / ``GROUP_COMMIT_MS``: committer flush
      thresholds — rows per transaction (default 512) and max
      accumulation window in ms once a batch has ≥ 2 units (default 2).
    """

    def __init__(self, config=None):
        self.config = config
        props = getattr(config, "properties", {}) or {}
        path = props.get("PATH") or os.path.join(
            fs_basedir(),
            "storage.db",
        )
        if path != ":memory:":
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self.path = path
        self.conn = _open_wal_conn(path)
        self.lock = threading.RLock()
        self._init_dao_cache(self.lock)
        self.shard_count = self._pin_shard_count(
            max(1, int(props.get("SHARDS", 1) or 1))
        )
        gc_rows = int(props.get("GROUP_COMMIT_EVENTS", 512) or 512)
        gc_delay_s = float(props.get("GROUP_COMMIT_MS", 2.0) or 0.0) / 1e3
        # unit-atomicity granularity: batches up to this many rows per
        # shard commit as ONE unit; larger slices (bulk imports through
        # write()) split into chunks so no single unit can outgrow a
        # committer flush (see SQLiteLEvents.insert_batch)
        self.gc_rows = max(1, gc_rows)
        # the main file as a write slot (shares this conn + lock): the
        # K==1 write target, and always scanned as the legacy/residual
        # row store
        self.main_store = _ShardState(
            self.path, self.conn, self.lock, gc_rows, gc_delay_s
        )
        if self.shard_count <= 1:
            self.event_shards = [self.main_store]
        else:
            self.event_shards = [
                _ShardState.open(
                    ":memory:" if path == ":memory:"
                    else f"{path}.shard{k}",
                    gc_rows, gc_delay_s,
                )
                for k in range(self.shard_count)
            ]

    def _pin_shard_count(self, configured: int) -> int:
        """The shard count is part of the DATA layout (crc32 % K routes
        every entity), so it is pinned in the main file at first use and
        validated on every open: reopening a K-sharded database with a
        different K (or none) would silently hide the shard files' rows
        from every scan, or re-route entities away from their history.
        Changing K requires export + re-import. Read-only files (and
        pre-pin single-file databases) skip the pin and keep K=1
        semantics."""
        try:
            with self.lock:
                self.conn.execute(
                    "CREATE TABLE IF NOT EXISTS pio_shard_meta ("
                    "key TEXT PRIMARY KEY, value TEXT)"
                )
                # OR IGNORE: multi-process workers (SO_REUSEPORT) race
                # this first-open write; losers read the winner's pin
                self.conn.execute(
                    "INSERT OR IGNORE INTO pio_shard_meta VALUES "
                    "('shard_count', ?)",
                    (str(configured),),
                )
                self.conn.commit()
                row = self.conn.execute(
                    "SELECT value FROM pio_shard_meta WHERE key='shard_count'"
                ).fetchone()
        except sqlite3.OperationalError:
            # e.g. a read-only database file: honor the configuration
            # (reads of a sharded db still need the right K to fan out)
            return configured
        pinned = int(row[0])
        if pinned == configured:
            return pinned
        if pinned == 1:
            # 1 -> K is the safe upgrade: every existing row is in the
            # main file, which is always scanned first, and no entity
            # has shard-file history to be re-routed away from
            with self.lock:
                self.conn.execute(
                    "UPDATE pio_shard_meta SET value=? "
                    "WHERE key='shard_count'",
                    (str(configured),),
                )
                self.conn.commit()
            return configured
        raise StorageError(
            f"database {self.path!r} was sharded with SHARDS={pinned} "
            f"but is being opened with SHARDS={configured}; the shard "
            "count routes entities to files and cannot change in place "
            "once rows exist in shard files — reopen with "
            f"SHARDS={pinned}, or export and re-import to re-shard"
        )

    def close(self) -> None:
        """Stop every shard's committer (draining queued units) and
        close the shard + main connections. For embedders that own a
        Storage universe's lifecycle; the module-default client lives
        for the process."""
        for shard in self.event_shards:
            shard.committer.close()
        if self.main_store not in self.event_shards:
            self.main_store.committer.close()
        for shard in self.event_shards:
            if shard is not self.main_store:
                with shard.lock:
                    shard.conn.close()
        with self.lock:
            self.conn.close()

    def shard_index_for(self, entity_id) -> int:
        """Stable entity→shard hash (crc32, not ``hash()`` — per-process
        salting would scatter one entity across files between runs)."""
        if self.shard_count <= 1:
            return 0
        return zlib.crc32(str(entity_id).encode("utf-8")) % self.shard_count

    def shard_for(self, entity_id) -> _ShardState:
        return self.event_shards[self.shard_index_for(entity_id)]

    def row_stores(self) -> List[_ShardState]:
        """Every store holding event ROWS, scan order: the main file
        first (legacy/pre-sharding rows), then the hash shards."""
        if self.shard_count <= 1:
            return [self.main_store]
        return [self.main_store] + self.event_shards

    def execute(self, sql: str, params=()) -> _LockedCursor:
        return _LockedCursor(self, sql, params)

    def read_execute(self, sql: str, params=()):
        """Snapshot read against the MAIN file (see
        :meth:`_ShardState.read_execute`)."""
        return self.main_store.read_execute(sql, params)

    def commit(self) -> None:
        with self.lock:
            self.conn.commit()

_GEN_SCHEMA = (
    "CREATE TABLE IF NOT EXISTS pio_table_gen "
    "(tbl TEXT PRIMARY KEY, gen INTEGER NOT NULL)"
)


def _table_name(namespace: str, suffix: str) -> str:
    ns = "".join(c if c.isalnum() else "_" for c in (namespace or "pio"))
    return f"{ns}_{suffix}"


class _StaleWatermark(Exception):
    """Another compactor advanced this store's watermark first; the
    round's files are abandoned (optimistic concurrency)."""


class SQLiteLEvents(base.LEvents):
    def __init__(self, client: StorageClient, config=None, namespace: str = ""):
        self._c = client
        self._ns = namespace or "pio"
        self._pages_schema_ok: set = set()
        self._seg_schema_ok: set = set()
        # path -> SegmentData, LRU (see _open_segment); segment files
        # are immutable, so entries never go stale (remove()/app delete
        # clears them)
        from collections import OrderedDict

        self._seg_cache: "OrderedDict[str, object]" = OrderedDict()
        # test-only crash injection: called between segment-file write
        # and the manifest commit (compaction crash-consistency tests)
        self.compact_fault = None

    def _ensure_pages_schema(self, t: str) -> None:
        """Migrate page tables from older layouts (memoized per table):
        databases whose events table predates the page store get the
        _pages/_dict tables created here (init() never re-runs for an
        existing app), and page tables created before a column existed
        are ALTERed (additive-only)."""
        if t in self._pages_schema_ok:
            return
        with self._c.lock:
            if not self._exists(t):
                # app never init()ed — read paths must stay read-only and
                # must not plant orphan page tables (do not memoize: the
                # app may be init()ed later)
                return
            try:
                # IF NOT EXISTS both statements: a no-op on an up-to-date
                # database, and self-heals one where only part of the
                # page schema was ever committed
                self._create_page_tables(t)
                self._c.commit()
            except sqlite3.OperationalError:
                # e.g. a read-only database file: reads proceed
                # (page-path callers guard on table existence);
                # writes surface sqlite's own error at INSERT time
                return
            cols = {
                row[1]
                for row in self._c.execute(
                    f"PRAGMA table_info({t}_pages)"
                ).fetchall()
            }
            if "dead" not in cols:
                self._c.execute(f"ALTER TABLE {t}_pages ADD COLUMN dead BLOB")
                self._c.commit()
            self._pages_schema_ok.add(t)

    def _events_table(self, app_id: int, channel_id: Optional[int]) -> str:
        name = _table_name(self._ns, f"events_{int(app_id)}")
        if channel_id is not None:
            name += f"_{int(channel_id)}"
        return name

    @staticmethod
    def _create_row_table(store, t: str) -> None:
        """Event-row DDL, identical in the main file and every shard
        file. Caller holds the store's lock.

        ``rid INTEGER PRIMARY KEY AUTOINCREMENT`` makes rowids strictly
        monotonic for the table's whole lifetime (sqlite_sequence keeps
        the high-water mark across deletes): the compaction tier's
        per-store watermark — "rowids <= W are sealed into segments" —
        stays sound even after every row below it is physically
        deleted, because no future insert can ever be assigned a rowid
        under W. Tables created before this schema (plain implicit
        rowid) are migrated on their first compaction
        (:meth:`_ensure_monotonic_rowids`)."""
        store.conn.execute(
            f"""CREATE TABLE IF NOT EXISTS {t} (
                rid INTEGER PRIMARY KEY AUTOINCREMENT,
                id TEXT UNIQUE NOT NULL,
                event TEXT NOT NULL,
                entity_type TEXT NOT NULL,
                entity_id TEXT NOT NULL,
                target_entity_type TEXT,
                target_entity_id TEXT,
                properties TEXT,
                event_time TEXT NOT NULL,
                event_time_ms INTEGER NOT NULL,
                tags TEXT,
                pr_id TEXT,
                creation_time TEXT NOT NULL
            )"""
        )
        store.conn.execute(
            f"CREATE INDEX IF NOT EXISTS {t}_time ON {t} (event_time_ms)"
        )
        store.conn.execute(
            f"CREATE INDEX IF NOT EXISTS {t}_entity ON {t} "
            f"(entity_type, entity_id, event_time_ms)"
        )

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            self._create_row_table(self._c.main_store, t)
            self._create_page_tables(t)
            self._c.commit()
        for shard in self._c.event_shards:
            if shard is self._c.main_store:
                continue
            with shard.lock:
                self._create_row_table(shard, t)
                shard.conn.commit()
        return True

    def _create_page_tables(self, t: str) -> None:
        """Columnar page store DDL (see data/storage/columnar.py): bulk
        imports land here as dictionary-encoded numpy blobs — the role of
        the reference's HBase regions feeding partitioned columnar scans
        (hbase/HBPEvents.scala:84-90). Single-event inserts keep using
        the row table; scans merge both. Caller holds the lock."""
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {t}_pages (
                page INTEGER PRIMARY KEY AUTOINCREMENT,
                event TEXT NOT NULL,
                entity_type TEXT NOT NULL,
                target_entity_type TEXT NOT NULL,
                prop TEXT NOT NULL,
                n INTEGER NOT NULL,
                min_ms INTEGER NOT NULL,
                max_ms INTEGER NOT NULL,
                entities BLOB NOT NULL,
                targets BLOB NOT NULL,
                vals BLOB NOT NULL,
                times BLOB NOT NULL,
                dead BLOB
            )"""
        )
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {t}_dict (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                name TEXT UNIQUE NOT NULL
            )"""
        )

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        t = self._events_table(app_id, channel_id)
        # collect segment file paths before the manifest drops
        seg_paths: List[str] = []
        if self._c.main_store.has_table(f"{t}_segments"):
            try:
                seg_paths = [
                    r[0]
                    for r in self._c.execute(
                        f"SELECT path FROM {t}_segments"
                    ).fetchall()
                ]
            except StorageError:
                pass
        with self._c.lock:
            self._c.execute(f"DROP TABLE IF EXISTS {t}")
            self._c.execute(f"DROP TABLE IF EXISTS {t}_pages")
            self._c.execute(f"DROP TABLE IF EXISTS {t}_dict")
            self._c.execute(f"DROP TABLE IF EXISTS {t}_segments")
            self._c.execute(f"DROP TABLE IF EXISTS {t}_compaction")
            # bump the table GENERATION: DROP resets the AUTOINCREMENT
            # sequence, so without this a delta cursor taken before a
            # wipe-and-reimport of a same-sized dataset could validate
            # against the recreated table and serve the stale wire
            self._c.execute(_GEN_SCHEMA)
            self._c.execute(
                "INSERT INTO pio_table_gen (tbl, gen) VALUES (?, 2) "
                "ON CONFLICT(tbl) DO UPDATE SET gen = gen + 1",
                (t,),
            )
            self._c.commit()
            self._c.main_store.known_tables.discard(t)
            self._c.main_store.known_tables.discard(f"{t}_segments")
            self._seg_schema_ok.discard(t)
        for path in seg_paths:
            self._seg_cache.pop(path, None)
            try:
                os.remove(path)
            except OSError:
                pass
        for shard in self._c.event_shards:
            if shard is self._c.main_store:
                continue
            with shard.lock:
                shard.conn.execute(f"DROP TABLE IF EXISTS {t}")
                shard.conn.commit()
                shard.known_tables.discard(t)
        return True

    def close(self) -> None:
        pass

    def _exists(self, table: str) -> bool:
        cur = self._c.execute(
            "SELECT name FROM sqlite_master WHERE type='table' AND name=?", (table,)
        )
        return cur.fetchone() is not None

    def _exists_memo(self, table: str) -> bool:
        """_exists with positive-result memoization for hot write paths:
        the per-event sqlite_master probe was a measurable share of REST
        ingest. Only positive results memoize (a table created later must
        be seen); remove() invalidates. A table dropped by ANOTHER
        process after memoization surfaces as StorageError from the
        statement itself rather than this probe."""
        return self._c.main_store.has_table(table)

    def _ensure_shard_table(self, shard: _ShardState, t: str) -> None:
        """Shard files are populated lazily: a database init()ed before
        sharding was enabled (or before this app existed) gets the row
        table created in the shard on first write to it. The MAIN file's
        table is the authority on whether the app is initialized — this
        is only reached after that check passed."""
        if shard is self._c.main_store or shard.has_table(t):
            return
        with shard.lock:
            self._create_row_table(shard, t)
            shard.conn.commit()
            shard.known_tables.add(t)

    # event-row column list (no rid): names both the insert slots and
    # every row SELECT, so the schema can carry the rid column without
    # positional drift between old and migrated tables
    _ROW_COLS = (
        "id, event, entity_type, entity_id, target_entity_type, "
        "target_entity_id, properties, event_time, event_time_ms, tags, "
        "pr_id, creation_time"
    )
    _INSERT_SQL = (
        "INSERT OR REPLACE INTO {t} ("
        "id, event, entity_type, entity_id, target_entity_type, "
        "target_entity_id, properties, event_time, event_time_ms, tags, "
        "pr_id, creation_time) VALUES (?,?,?,?,?,?,?,?,?,?,?,?)"
    )

    @staticmethod
    def _event_row(event: Event, eid: str) -> tuple:
        return (
            eid,
            event.event,
            event.entity_type,
            event.entity_id,
            event.target_entity_type,
            event.target_entity_id,
            json.dumps(event.properties.to_json()),
            format_iso8601(event.event_time),
            _ms(event.event_time),
            json.dumps(list(event.tags)),
            event.pr_id,
            format_iso8601(event.creation_time),
        )

    def _scrub_duplicate_ids(self, t: str, spares) -> None:
        """INSERT OR REPLACE only replaces within ONE file — a client
        re-posting an EXPLICIT event id whose old row lives in another
        row store (pre-sharding main rows, or the same id re-posted with
        a different entity) would otherwise leave a stale duplicate that
        get() keeps returning. ``spares`` is ``[(event_id, keep_store)]``;
        each id is deleted from every OTHER row store in one batched
        transaction per store. Called AFTER the replacement row's commit:
        a failed insert then never loses the old row (the reverse order
        could drop the event entirely), at the price that a crash in the
        narrow window between commit and scrub leaves a duplicate of an
        explicitly re-posted id — duplicates over data loss. Explicit ids
        are the rare path (imports, updates); server-generated ids never
        pay this probe."""
        if not spares:
            return
        for store in self._c.row_stores():
            ids = [eid for eid, keep in spares if keep is not store]
            if not ids or not store.has_table(t):
                continue
            with store.lock:
                deleted = False
                for s in range(0, len(ids), 500):  # bound-param headroom
                    part = ids[s : s + 500]
                    cur = store.conn.execute(
                        f"DELETE FROM {t} WHERE id IN "
                        f"({','.join('?' * len(part))})",
                        part,
                    )
                    deleted = deleted or cur.rowcount > 0
                if deleted:
                    store.conn.commit()
                else:
                    store.conn.rollback()
        # a compacted copy of a re-posted id lives in an immutable
        # segment, out of DELETE's reach — tombstone it in the manifest
        # (explicit ids are the rare path; server-generated ids never
        # reach here)
        self._tombstone_segment_ids(t, [eid for eid, _ in spares])

    def insert(self, event: Event, app_id: int, channel_id: Optional[int] = None) -> str:
        """Single-event insert through the per-shard GROUP COMMITTER: the
        row is enqueued, the shard's committer coalesces it with whatever
        else is in flight into one transaction, and this call returns
        after that transaction's COMMIT — the returned id is durable (to
        the WAL) exactly as before, but N concurrent inserts now pay one
        commit, not N."""
        t = self._events_table(app_id, channel_id)
        eid = event.event_id or new_event_id()
        if not self._exists_memo(t):
            raise StorageError(f"events table {t} not initialized")
        shard = self._c.shard_for(event.entity_id)
        self._ensure_shard_table(shard, t)
        shard.submit_rows(
            self._INSERT_SQL.format(t=t), [self._event_row(event, eid)]
        ).wait()
        if event.event_id:
            self._scrub_duplicate_ids(t, [(eid, shard)])
        return eid

    def insert_batch(
        self,
        events: Sequence[Event],
        app_id: int,
        channel_id: Optional[int] = None,
    ) -> List[str]:
        """Batch insert (the ``/batch/events.json`` path): the batch is
        split by shard and each shard's slice rides the group committer
        as an atomic unit — a reader can never observe part of a unit.
        Slices larger than ``GROUP_COMMIT_EVENTS`` rows (bulk imports
        through ``write()``) split into chunked units of that size, so
        no unit can outgrow a committer flush; the <=50-event REST batch
        is always one unit per shard. With K > 1 a batch spanning shards
        is atomic PER SHARD, not globally — a failure after some shards
        committed raises :class:`PartialBatchError` naming exactly which
        event ids did NOT land, so the REST route reports per-event
        outcomes. Shard slices commit in parallel; this returns after
        every slice resolves."""
        events = list(events)
        if not events:
            return []
        t = self._events_table(app_id, channel_id)
        if not self._exists_memo(t):
            raise StorageError(f"events table {t} not initialized")
        eids = [e.event_id or new_event_id() for e in events]
        # duplicate EXPLICIT ids within one batch are last-wins, exactly
        # like single-file INSERT OR REPLACE: earlier occurrences never
        # reach a shard, so the post-commit scrub can't delete the
        # survivor from its own store
        last_slot: Dict[str, int] = {
            eid: j
            for j, (event, eid) in enumerate(zip(events, eids))
            if event.event_id
        }
        by_shard: Dict[int, list] = {}  # shard idx -> [(row, eid)]
        explicit: list = []  # (eid, keep_store) to scrub post-commit
        for j, (event, eid) in enumerate(zip(events, eids)):
            if event.event_id and last_slot[eid] != j:
                continue  # superseded later in this same batch
            k = self._c.shard_index_for(event.entity_id)
            if event.event_id:
                explicit.append((eid, self._c.event_shards[k]))
            by_shard.setdefault(k, []).append((self._event_row(event, eid), eid))
        sql = self._INSERT_SQL.format(t=t)
        chunk = self._c.gc_rows
        units: list = []  # (unit, [eids])
        # bounded admission can refuse a LATER unit after earlier units
        # of this same batch were enqueued (and will commit). A bare
        # StorageSaturatedError here would tell the caller "nothing was
        # admitted — retry the whole batch", and a retry of auto-id
        # events would re-insert the committed slices under fresh ids.
        # So the refusal is only propagated as-is when NO unit made it
        # into a queue; otherwise the refused/unsubmitted slices join
        # the PartialBatchError's failed set (marked retryable-after-
        # backoff) after the enqueued units resolve.
        unsubmitted: list = []  # eids of slices never enqueued
        admit_error: Optional[base.StorageSaturatedError] = None
        for k, pairs in by_shard.items():
            shard = self._c.event_shards[k]
            self._ensure_shard_table(shard, t)
            for s in range(0, len(pairs), chunk):
                part = pairs[s : s + chunk]
                if admit_error is not None:
                    unsubmitted.extend(eid for _, eid in part)
                    continue
                try:
                    units.append(
                        (
                            shard.submit_rows(
                                sql, [row for row, _ in part]
                            ),
                            [eid for _, eid in part],
                        )
                    )
                except base.StorageSaturatedError as e:
                    admit_error = e
                    unsubmitted.extend(eid for _, eid in part)
        if admit_error is not None and not units:
            raise admit_error  # truly nothing admitted: batch-retry safe
        failed: list = []
        first_error: Optional[BaseException] = None
        for unit, unit_eids in units:
            try:
                unit.wait()
            except BaseException as e:
                failed.extend(unit_eids)
                if first_error is None:
                    first_error = e
        failed.extend(unsubmitted)
        # scrub explicit ids only where the REPLACEMENT actually landed
        # (a failed unit must keep the old copy — see _scrub_duplicate_ids)
        failed_set = set(failed)
        self._scrub_duplicate_ids(
            t, [(eid, keep) for eid, keep in explicit if eid not in failed_set]
        )
        if first_error is not None or admit_error is not None:
            err = first_error if first_error is not None else admit_error
            if len(failed) == len(eids):
                raise err  # nothing landed: plain error
            raise PartialBatchError(
                f"{len(failed)}/{len(eids)} batch events failed to "
                f"commit: {err}",
                event_ids=eids,
                failed_ids=failed,
                # the backoff hint marks EVERY failed slot as a
                # capacity refusal, so it is only attached when no
                # unit failed hard — a mixed batch must not label
                # commit failures as 503-retryable saturation
                retry_after_s=(
                    admit_error.retry_after_s
                    if admit_error is not None and first_error is None
                    else None
                ),
            ) from err
        return eids

    @staticmethod
    def _row_to_event(row) -> Event:
        return Event(
            event_id=row[0],
            event=row[1],
            entity_type=row[2],
            entity_id=row[3],
            target_entity_type=row[4],
            target_entity_id=row[5],
            properties=DataMap(json.loads(row[6]) if row[6] else {}),
            event_time=parse_iso8601(row[7]),
            tags=tuple(json.loads(row[9]) if row[9] else ()),
            pr_id=row[10],
            creation_time=parse_iso8601(row[11]),
        )

    @staticmethod
    def _parse_page_id(event_id: str):
        """Bulk-imported events carry synthetic ids ``pg-<page>-<idx>``."""
        if not event_id.startswith("pg-"):
            return None
        try:
            _, page, idx = event_id.split("-", 2)
            return int(page), int(idx)
        except ValueError:
            return None

    def _get_page_event(
        self, t: str, page: int, idx: int
    ) -> Optional[Event]:
        import numpy as np

        self._ensure_pages_schema(t)
        with self._c.lock:
            if not self._exists(f"{t}_pages"):
                return None
            row = self._c.execute(
                f"SELECT event, entity_type, target_entity_type, prop, n, "
                f"entities, targets, vals, times, dead "
                f"FROM {t}_pages WHERE page=?",
                (page,),
            ).fetchone()
        if row is None or idx >= row[4]:
            return None
        ev, et, tet, prop, n, eb, gb, vb, tb, db = row
        if db is not None and np.frombuffer(db, np.uint8)[idx]:
            return None  # tombstoned
        names = self._dict_names(t)
        when = _dt.datetime.fromtimestamp(
            int(np.frombuffer(tb, np.int64)[idx]) / 1000.0, _dt.timezone.utc
        )
        return Event(
            event_id=f"pg-{page}-{idx}",
            event=ev,
            entity_type=et,
            entity_id=names[np.frombuffer(eb, np.int32)[idx]],
            target_entity_type=tet,
            target_entity_id=names[np.frombuffer(gb, np.int32)[idx]],
            properties=DataMap(
                {prop: float(np.frombuffer(vb, np.float32)[idx])}
            ),
            event_time=when,
            creation_time=when,
        )

    def get(
        self, event_id: str, app_id: int, channel_id: Optional[int] = None
    ) -> Optional[Event]:
        t = self._events_table(app_id, channel_id)
        pg = self._parse_page_id(event_id)
        if pg is not None:
            return self._get_page_event(t, *pg)
        with self._c.lock:
            if not self._exists(t):
                raise StorageError(f"events table {t} not initialized")
        # event ids don't encode their shard (the entity hash needs the
        # entity id), so probe each row store; K is small and the id
        # column is the primary key
        for store in self._c.row_stores():
            if not store.has_table(t):
                continue
            row = store.execute(
                f"SELECT {self._ROW_COLS} FROM {t} WHERE id=?", (event_id,)
            ).fetchone()
            if row:
                return self._row_to_event(row)
        # compacted events keep their original ids inside segment files
        return self._get_segment_event(t, event_id)

    def _delete_page_event(self, t: str, page: int, idx: int) -> bool:
        """Delete one row of a page by marking its tombstone bit. The
        page is never compacted, so the positional event ids
        (``pg-<page>-<idx>``) of the surviving rows stay STABLE — a
        compaction would silently re-address later rows, making a second
        delete remove the wrong event. A fully-dead page is dropped."""
        import numpy as np

        self._ensure_pages_schema(t)
        with self._c.lock:
            if not self._exists(f"{t}_pages"):
                return False
            row = self._c.execute(
                f"SELECT n, dead FROM {t}_pages WHERE page=?", (page,)
            ).fetchone()
            if row is None or idx >= row[0]:
                return False
            n, dead_blob = row
            dead = (
                np.frombuffer(dead_blob, np.uint8).copy()
                if dead_blob is not None
                else np.zeros(n, np.uint8)
            )
            if dead[idx]:
                return False  # already deleted
            dead[idx] = 1
            if int(dead.sum()) == n:
                self._c.conn.execute(
                    f"DELETE FROM {t}_pages WHERE page=?", (page,)
                )
            else:
                self._c.conn.execute(
                    f"UPDATE {t}_pages SET dead=? WHERE page=?",
                    (dead.tobytes(), page),
                )
            self._c.conn.commit()
            return True

    def delete(
        self, event_id: str, app_id: int, channel_id: Optional[int] = None
    ) -> bool:
        t = self._events_table(app_id, channel_id)
        pg = self._parse_page_id(event_id)
        if pg is not None:
            return self._delete_page_event(t, *pg)
        with self._c.lock:
            if not self._exists(t):
                raise StorageError(f"events table {t} not initialized")
        # deletes are rare: a direct per-store transaction, not the
        # group committer (same shard probe rationale as get()). A
        # sealed copy may ALSO exist in the segment tier (always, after
        # compaction; plus a grace-window row copy) — tombstone it too,
        # or the event would resurface on the next scan.
        deleted = False
        for store in self._c.row_stores():
            if not store.has_table(t):
                continue
            with store.lock:
                cur = store.conn.execute(
                    f"DELETE FROM {t} WHERE id=?", (event_id,)
                )
                store.conn.commit()
            if cur.rowcount > 0:
                deleted = True
                break
        return self._tombstone_segment_ids(t, [event_id]) or deleted

    @staticmethod
    def _find_clauses(
        start_time, until_time, entity_type, entity_id, event_names,
        target_entity_type, target_entity_id,
    ):
        clauses: List[str] = []
        params: list = []
        if start_time is not None:
            clauses.append("event_time_ms >= ?")
            params.append(_ms(start_time))
        if until_time is not None:
            clauses.append("event_time_ms < ?")
            params.append(_ms(until_time))
        if entity_type is not None:
            clauses.append("entity_type = ?")
            params.append(entity_type)
        if entity_id is not None:
            clauses.append("entity_id = ?")
            params.append(entity_id)
        if event_names is not None:
            if event_names:
                clauses.append(
                    "event IN (" + ",".join("?" * len(event_names)) + ")"
                )
                params.extend(event_names)
            else:
                clauses.append("1=0")  # empty allow-list matches nothing
        if target_entity_type is not UNSET:
            if target_entity_type is None:
                clauses.append("target_entity_type IS NULL")
            else:
                clauses.append("target_entity_type = ?")
                params.append(target_entity_type)
        if target_entity_id is not UNSET:
            if target_entity_id is None:
                clauses.append("target_entity_id IS NULL")
            else:
                clauses.append("target_entity_id = ?")
                params.append(target_entity_id)
        return clauses, params

    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: OptFilter = UNSET,
        target_entity_id: OptFilter = UNSET,
        limit: Optional[int] = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        t = self._events_table(app_id, channel_id)
        clauses, params = self._find_clauses(
            start_time, until_time, entity_type, entity_id, event_names,
            target_entity_type, target_entity_id,
        )
        with self._c.lock:
            if not self._exists(t):
                raise StorageError(f"events table {t} not initialized")
        marks, segs = self._segment_state(t)
        # the potentially-large scans run on snapshot connections, so
        # concurrent ingest proceeds while these fetches stream; sharded
        # stores fan out per shard and merge (stable sort: ties keep
        # main-store-then-shard, insertion order). An entity_id filter
        # pins the events to ONE shard (the insert hash), so the serving
        # find-by-entity path scans main + that shard, not all K.
        all_stores = self._c.row_stores()
        keys = list(range(len(all_stores)))
        if entity_id is not None and self._c.shard_count > 1:
            keys = [0, all_stores.index(self._c.shard_for(entity_id))]
        row_events: List[Event] = []
        n_stores = 0
        for key in keys:
            store = all_stores[key]
            if not store.has_table(t):
                continue
            n_stores += 1
            sql = f"SELECT {self._ROW_COLS} FROM {t}"
            store_clauses = list(clauses)
            store_params = list(params)
            pred = self._residual_clause(marks, key)
            if pred is not None:  # sealed prefix lives in segments now
                store_clauses.append(pred[0])
                store_params.extend(pred[1])
            if store_clauses:
                sql += " WHERE " + " AND ".join(store_clauses)
            sql += f" ORDER BY event_time_ms {'DESC' if reversed else 'ASC'}"
            if limit is not None and limit >= 0:
                sql += f" LIMIT {int(limit)}"  # per-store bound; re-cut below
            row_events.extend(
                self._row_to_event(r)
                for r in store.read_execute(sql, store_params).fetchall()
            )
        # merge compacted segment events and bulk-imported page events
        # (rare on this legacy path — the training scan is
        # find_columns_native; here both decode into Event objects so
        # find() stays a complete view of the store)
        seg_events = self._segment_events(
            t, segs, start_time, until_time, entity_type, entity_id,
            event_names, target_entity_type, target_entity_id,
            store_keys=set(keys), limit=limit, reversed=reversed,
        )
        page_events = self._page_events(
            t, start_time, until_time, entity_type, entity_id, event_names,
            target_entity_type, target_entity_id,
        )
        if not page_events and not seg_events and n_stores <= 1:
            return iter(row_events)
        # stable sort: segment events (the sealed, older prefix) sort
        # before the residual rows they precede on equal timestamps
        merged = seg_events + row_events + page_events
        merged.sort(key=lambda e: _ms(e.event_time), reverse=reversed)
        if limit is not None and limit >= 0:
            merged = merged[: int(limit)]
        return iter(merged)

    # --- columnar page store (see data/storage/columnar.py) ---

    _PAGE_ROWS = 1 << 20

    def _dict_encode(self, t: str, names) -> "np.ndarray":
        """Distinct id strings -> global dictionary codes (insert-if-new)."""
        import numpy as np

        strs = [str(n) for n in names]
        with self._c.lock:
            self._c.conn.executemany(
                f"INSERT OR IGNORE INTO {t}_dict (name) VALUES (?)",
                ((s,) for s in strs),
            )
            mapping: Dict[str, int] = {}
            chunk = 900  # sqlite bound-parameter limit headroom
            for s in range(0, len(strs), chunk):
                part = strs[s : s + chunk]
                rows = self._c.conn.execute(
                    f"SELECT name, id FROM {t}_dict WHERE name IN "
                    f"({','.join('?' * len(part))})",
                    part,
                ).fetchall()
                mapping.update(rows)
            self._c.conn.commit()
        return np.array([mapping[s] for s in strs], np.int32)

    def _dict_names(self, t: str) -> "np.ndarray":
        """Global dictionary as an id-indexed name array."""
        import numpy as np

        rows = self._c.read_execute(
            f"SELECT id, name FROM {t}_dict"
        ).fetchall()
        size = (max(r[0] for r in rows) + 1) if rows else 0
        arr = np.empty(size, object)
        for i, name in rows:
            arr[i] = name
        return arr

    def insert_columns(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        event: str,
        entity_type: str,
        target_entity_type: str,
        entity_ids,
        target_ids,
        values,
        value_property: str = "rating",
        event_time: Optional[_dt.datetime] = None,
        event_times_ms=None,
    ) -> int:
        from predictionio_tpu.data.storage.columnar import encode_strings

        e_names, e_codes = encode_strings(entity_ids)
        g_names, g_codes = encode_strings(target_ids)
        return self.insert_columns_encoded(
            app_id,
            channel_id,
            event=event,
            entity_type=entity_type,
            target_entity_type=target_entity_type,
            entity_names=e_names,
            entity_codes=e_codes,
            target_names=g_names,
            target_codes=g_codes,
            values=values,
            value_property=value_property,
            event_time=event_time,
            event_times_ms=event_times_ms,
        )

    def insert_columns_encoded(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        event: str,
        entity_type: str,
        target_entity_type: str,
        entity_names,
        entity_codes,
        target_names,
        target_codes,
        values,
        value_property: str = "rating",
        event_time: Optional[_dt.datetime] = None,
        event_times_ms=None,
    ) -> int:
        """Vectorized bulk append: dictionary-encode the (pre-factorized)
        id columns and store numpy blobs as pages — 20M events import in
        seconds where the row path takes minutes (the role of the
        reference's HBase bulk region writes). ``event_times_ms`` keeps
        per-row timestamps (import round-trips); otherwise every row gets
        ``event_time`` (default now)."""
        import numpy as np

        if event.startswith("$"):
            raise StorageError(
                f"insert_columns cannot write special event {event!r}"
            )
        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            if not self._exists(t):
                raise StorageError(f"events table {t} not initialized")
        # pre-page-store databases lack the _pages/_dict tables entirely
        self._ensure_pages_schema(t)
        vals = np.asarray(values, np.float32)
        e_codes = np.asarray(entity_codes, np.int32)
        g_codes = np.asarray(target_codes, np.int32)
        n = len(vals)
        if n != len(e_codes) or n != len(g_codes):
            raise ValueError("entity/target/values column lengths differ")
        if n == 0:
            return 0
        e_glob = self._dict_encode(t, entity_names)[e_codes]
        g_glob = self._dict_encode(t, target_names)[g_codes]
        if event_times_ms is not None:
            times = np.asarray(event_times_ms, np.int64)
            if len(times) != n:
                raise ValueError("event_times_ms length differs")
        else:
            tms = _ms(event_time or _dt.datetime.now(_dt.timezone.utc))
            times = np.full(n, tms, np.int64)
        with self._c.lock:
            for s in range(0, n, self._PAGE_ROWS):
                e = slice(s, min(s + self._PAGE_ROWS, n))
                cnt = e.stop - e.start
                ts = times[e]
                self._c.conn.execute(
                    f"INSERT INTO {t}_pages (event, entity_type, "
                    "target_entity_type, prop, n, min_ms, max_ms, "
                    "entities, targets, vals, times) "
                    "VALUES (?,?,?,?,?,?,?,?,?,?,?)",
                    (
                        event, entity_type, target_entity_type,
                        value_property, cnt, int(ts.min()), int(ts.max()),
                        e_glob[e].tobytes(), g_glob[e].tobytes(),
                        vals[e].tobytes(), ts.tobytes(),
                    ),
                )
            self._c.conn.commit()
        return n

    @staticmethod
    def _page_filter(
        start_time, until_time, entity_type, event_names,
        target_entity_type,
    ):
        """Page-level WHERE ``(clauses, params)`` shared by every page
        scan (monolithic, streaming, legacy find view), or None when no
        page can match. Pages only hold target-carrying events, so an
        explicit target_entity_type IS NULL filter matches none."""
        if target_entity_type is None:  # explicit "no target" filter
            return None
        clauses, params = [], []
        if event_names is not None:
            if not event_names:
                return None
            clauses.append(
                "event IN (" + ",".join("?" * len(event_names)) + ")"
            )
            params.extend(event_names)
        if entity_type is not None:
            clauses.append("entity_type = ?")
            params.append(entity_type)
        if target_entity_type is not UNSET:
            clauses.append("target_entity_type = ?")
            params.append(target_entity_type)
        if start_time is not None:
            clauses.append("max_ms >= ?")
            params.append(_ms(start_time))
        if until_time is not None:
            clauses.append("min_ms < ?")
            params.append(_ms(until_time))
        return clauses, params

    def _page_rows(
        self, t, start_time, until_time, entity_type, event_names,
        target_entity_type,
    ):
        """Pages matching the coarse (page-level) filters."""
        filt = self._page_filter(
            start_time, until_time, entity_type, event_names,
            target_entity_type,
        )
        if filt is None:
            return []
        self._ensure_pages_schema(t)
        clauses, params = filt
        sql = (
            f"SELECT page, event, entity_type, target_entity_type, prop, "
            f"n, min_ms, max_ms, entities, targets, vals, times, dead "
            f"FROM {t}_pages"
        )
        if clauses:
            sql += " WHERE " + " AND ".join(clauses)
        with self._c.lock:
            if not self._exists(f"{t}_pages"):
                return []
        return self._c.read_execute(sql, params).fetchall()

    def _page_events(
        self, t, start_time, until_time, entity_type, entity_id,
        event_names, target_entity_type, target_entity_id,
    ) -> List[Event]:
        """Decode page rows into Event objects (legacy find() view)."""
        import numpy as np

        pages = self._page_rows(
            t, start_time, until_time, entity_type, event_names,
            target_entity_type,
        )
        if not pages or target_entity_id is None:
            return []

        def code_of(name: str):
            row = self._c.execute(
                f"SELECT id FROM {t}_dict WHERE name=?", (name,)
            ).fetchone()
            return row[0] if row else None

        # entity filters compare int32 dict CODES, not strings: a
        # serving-path find_by_entity over a 20M-row bulk import must
        # stay vectorized (object-array string equality would burn the
        # serving deadline)
        e_code = g_code = None
        if entity_id is not None:
            e_code = code_of(entity_id)
            if e_code is None:
                return []
        if target_entity_id is not UNSET:
            g_code = code_of(target_entity_id)
            if g_code is None:
                return []
        names = self._dict_names(t)
        out: List[Event] = []
        lo = _ms(start_time) if start_time is not None else None
        hi = _ms(until_time) if until_time is not None else None
        for (
            page, ev, et, tet, prop, n, min_ms, max_ms, eb, gb, vb, tb, db
        ) in pages:
            e = np.frombuffer(eb, np.int32)
            g = np.frombuffer(gb, np.int32)
            v = np.frombuffer(vb, np.float32)
            ts = np.frombuffer(tb, np.int64)
            keep = (
                np.frombuffer(db, np.uint8) == 0
                if db is not None
                else np.ones(n, bool)
            )
            if lo is not None:
                keep = keep & (ts >= lo)
            if hi is not None:
                keep = keep & (ts < hi)
            if e_code is not None:
                keep = keep & (e == e_code)
            if g_code is not None:
                keep = keep & (g == g_code)
            for j in np.nonzero(keep)[0]:
                when = _dt.datetime.fromtimestamp(
                    ts[j] / 1000.0, _dt.timezone.utc
                )
                out.append(
                    Event(
                        event_id=f"pg-{page}-{int(j)}",
                        event=ev,
                        entity_type=et,
                        entity_id=names[e[j]],
                        target_entity_type=tet,
                        target_entity_id=names[g[j]],
                        properties=DataMap({prop: float(v[j])}),
                        event_time=when,
                        creation_time=when,
                    )
                )
        return out

    def iter_row_events(
        self, app_id: int, channel_id: Optional[int] = None
    ) -> Iterator[Event]:
        """Row-store events ONLY (no page merge) — the export path pairs
        this with iter_export_pages so neither side is double-counted.
        Sharded stores merge every shard's rows back into one
        time-ordered view."""
        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            if not self._exists(t):
                raise StorageError(f"events table {t} not initialized")
        marks, _ = self._segment_state(t)
        queries: list = []  # (store, sql, params)
        for key, store in enumerate(self._c.row_stores()):
            if not store.has_table(t):
                continue
            sql = f"SELECT {self._ROW_COLS} FROM {t}"
            pred = self._residual_clause(marks, key)
            params: list = []
            if pred is not None:  # sealed rows export via segments
                sql += f" WHERE {pred[0]}"
                params = pred[1]
            sql += " ORDER BY event_time_ms ASC"
            queries.append((store, sql, params))
        if len(queries) <= 1:
            # single store: Event objects materialize one at a time as
            # the consumer (e.g. the parquet export writer) iterates —
            # a 20M-row export must not hold 20M Events at once
            rows = (
                queries[0][0].read_execute(
                    queries[0][1], queries[0][2]
                ).fetchall()
                if queries
                else []
            )
            return (self._row_to_event(r) for r in rows)
        events = [
            self._row_to_event(r)
            for store, sql, params in queries
            for r in store.read_execute(sql, params).fetchall()
        ]
        events.sort(key=lambda e: _ms(e.event_time))
        return iter(events)

    def iter_export_pages(
        self, app_id: int, channel_id: Optional[int] = None
    ) -> Iterator[dict]:
        """Bulk-export view of the page store: one dict of decoded numpy
        columns per page (live rows only), for vectorized writers —
        exporting 20M events must not build 20M Event objects any more
        than importing them does. Keys: event, entity_type,
        target_entity_type, prop, event_ids, entity_ids, target_ids,
        values, times_ms."""
        import numpy as np

        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            if not self._exists(t):
                raise StorageError(f"events table {t} not initialized")
        self._ensure_pages_schema(t)
        with self._c.lock:
            if not self._exists(f"{t}_pages"):
                return
        page_ids = [
            r[0]
            for r in self._c.read_execute(
                f"SELECT page FROM {t}_pages ORDER BY page"
            ).fetchall()
        ]
        if not page_ids:
            return
        names = self._dict_names(t)
        for page_id in page_ids:
            # one page's blobs at a time: peak memory stays one page, and
            # the snapshot connection never touches the writer lock
            row = self._c.read_execute(
                f"SELECT page, event, entity_type, target_entity_type, "
                f"prop, n, entities, targets, vals, times, dead "
                f"FROM {t}_pages WHERE page=?",
                (page_id,),
            ).fetchone()
            if row is None:
                continue  # deleted since listing
            (page, ev, et, tet, prop, n, eb, gb, vb, tb, db) = row
            alive = (
                np.nonzero(np.frombuffer(db, np.uint8) == 0)[0]
                if db is not None
                else np.arange(n)
            )
            if not len(alive):
                continue
            # positional ids stay stable across tombstones: the index in
            # the id is the ORIGINAL slot, not the live rank
            event_ids = np.char.add(
                f"pg-{page}-", alive.astype("U10")
            ).astype(object)
            yield {
                "event": ev,
                "entity_type": et,
                "target_entity_type": tet,
                "prop": prop,
                "event_ids": event_ids,
                "entity_ids": names[np.frombuffer(eb, np.int32)[alive]],
                "target_ids": names[np.frombuffer(gb, np.int32)[alive]],
                "values": np.frombuffer(vb, np.float32)[alive],
                "times_ms": np.frombuffer(tb, np.int64)[alive],
            }

    # --- compacted columnar segment tier (data/storage/segments.py) ---
    #
    # Immutable segment files hold sealed cold prefixes of each row
    # store; a manifest + per-store watermark in the MAIN database makes
    # them atomically visible and excludes the sealed rowid ranges from
    # every residual row scan. Scans fan out over
    # pages -> per store: [segments, residual rows] — exactly the event
    # order of an uncompacted store, so the counting-sort merge's wire
    # stays byte-identical (segments module docstring).

    def _seg_dir(self) -> str:
        return f"{self._c.path}.segments"

    def _ensure_segment_schema(self, t: str) -> None:
        """Create the manifest + compaction-state tables (main db)."""
        if t in self._seg_schema_ok:
            return
        with self._c.lock:
            self._c.execute(
                f"""CREATE TABLE IF NOT EXISTS {t}_segments (
                    segment INTEGER PRIMARY KEY AUTOINCREMENT,
                    store INTEGER NOT NULL,
                    n INTEGER NOT NULL,
                    min_rowid INTEGER NOT NULL,
                    max_rowid INTEGER NOT NULL,
                    min_ms INTEGER NOT NULL,
                    max_ms INTEGER NOT NULL,
                    events TEXT NOT NULL,
                    entity_types TEXT NOT NULL,
                    target_entity_types TEXT NOT NULL,
                    path TEXT NOT NULL,
                    checksum INTEGER NOT NULL,
                    created_ms INTEGER NOT NULL,
                    dead BLOB
                )"""
            )
            self._c.execute(
                f"""CREATE TABLE IF NOT EXISTS {t}_compaction (
                    store INTEGER PRIMARY KEY,
                    watermark INTEGER NOT NULL,
                    cleaned INTEGER NOT NULL,
                    holdouts BLOB,
                    last_ms INTEGER NOT NULL
                )"""
            )
            self._c.commit()
            self._seg_schema_ok.add(t)

    def _segment_state(self, t: str):
        """One consistent snapshot of (compaction marks, live segment
        manifest): ``marks`` is ``{store_key: (watermark, holdout rowid
        tuple, cleaned, last_ms)}``, ``segs`` a list of manifest dicts
        ordered by (store, segment id) — which IS rowid order, because
        each store's watermark only advances. Store keys index
        ``row_stores()`` (0 = main file, then hash shards); the pair is
        read in ONE read transaction so a racing compaction commit can
        never double- or zero-count sealed rows."""
        if not self._c.main_store.has_table(f"{t}_segments"):
            return {}, []
        import numpy as np

        rows_marks, rows_segs = self._c.main_store.read_snapshot(
            [
                (
                    f"SELECT store, watermark, cleaned, holdouts, last_ms "
                    f"FROM {t}_compaction",
                    (),
                ),
                (
                    f"SELECT segment, store, n, min_rowid, max_rowid, "
                    f"min_ms, max_ms, events, entity_types, "
                    f"target_entity_types, path, checksum, created_ms, "
                    f"dead FROM {t}_segments ORDER BY store, segment",
                    (),
                ),
            ]
        )
        marks = {
            int(r[0]): (
                int(r[1]),
                tuple(
                    int(x) for x in np.frombuffer(r[3], np.int64)
                )
                if r[3]
                else (),
                int(r[2]),
                int(r[4]),
            )
            for r in rows_marks
        }
        segs = [
            {
                "segment": r[0], "store": r[1], "n": r[2],
                "min_rowid": r[3], "max_rowid": r[4], "min_ms": r[5],
                "max_ms": r[6], "events": json.loads(r[7]),
                "entity_types": json.loads(r[8]),
                "target_entity_types": json.loads(r[9]), "path": r[10],
                "checksum": r[11], "created_ms": r[12], "dead": r[13],
            }
            for r in rows_segs
        ]
        return marks, segs

    # open-segment LRU bound: entries are mmap-backed (resident pages
    # are OS page cache, evictable), so the cap limits mappings/handles,
    # not data bytes
    _SEG_CACHE_MAX = 128

    def _open_segment(self, path: str):
        """Open (and cache) one immutable segment file. The cache is
        instance-scoped, LRU-bounded, and keyed by path; files never
        change in place (writes go through temp + rename under a fresh
        name), so entries can't go stale — only cold."""
        from predictionio_tpu.data.storage import segments as _seg

        data = self._seg_cache.get(path)
        if data is None:
            try:
                data = _seg.SegmentData(path)
            except (OSError, _seg.SegmentReadError) as e:
                raise StorageError(f"segment unreadable: {e}") from e
            self._seg_cache[path] = data
            while len(self._seg_cache) > self._SEG_CACHE_MAX:
                self._seg_cache.pop(next(iter(self._seg_cache)))
        else:
            self._seg_cache.move_to_end(path)
        return data

    @staticmethod
    def _and_extras(*extras):
        """AND-combine optional pre-bound ``(clause, params)`` predicates
        (None entries skipped; None when nothing remains)."""
        parts = [e for e in extras if e is not None]
        if not parts:
            return None
        return (
            " AND ".join(f"({c})" for c, _ in parts),
            [p for _, ps in parts for p in ps],
        )

    @staticmethod
    def _residual_clause(marks, store_key: int):
        """SQL predicate excluding the compacted prefix of one row
        store (``None`` when nothing is compacted): rows above the
        watermark, plus the bounded holdout set the compactor could not
        columnarize."""
        mark = marks.get(store_key) if marks else None
        if not mark or mark[0] <= 0:
            return None
        wm, holdouts = mark[0], mark[1]
        if holdouts:
            # holdout rowids inline as integer literals, not bound
            # parameters: max_holdouts (4096) exceeds older sqlite's
            # 999-variable limit, and these are int64s from our own
            # manifest — nothing to escape
            inlist = ",".join(str(int(h)) for h in holdouts)
            return f"(rowid > ? OR rowid IN ({inlist}))", [wm]
        return "rowid > ?", [wm]

    @staticmethod
    def _segs_match(
        seg: dict, event_names, entity_type, target_entity_type, lo, hi
    ) -> bool:
        """Coarse manifest-level pruning, mirroring ``_page_filter``."""
        if target_entity_type is None:  # explicit "no target" filter
            return False
        if event_names is not None and not (
            set(event_names) & set(seg["events"])
        ):
            return False
        if entity_type is not None and entity_type not in seg["entity_types"]:
            return False
        if (
            target_entity_type is not UNSET
            and target_entity_type not in seg["target_entity_types"]
        ):
            return False
        if lo is not None and seg["max_ms"] < lo:
            return False
        if hi is not None and seg["min_ms"] >= hi:
            return False
        return True

    def _seg_dead(self, seg: dict):
        import numpy as np

        if seg["dead"] is None:
            return None
        return np.frombuffer(seg["dead"], np.uint8)

    def _segment_events(
        self, t, segs, start_time, until_time, entity_type, entity_id,
        event_names, target_entity_type, target_entity_id,
        store_keys=None, limit=None, reversed=False,
    ) -> List[Event]:
        """Decode matching segment rows into Event objects (the legacy
        ``find()`` view), original ids and creation times preserved.
        With ``limit``, only the per-segment top-``limit`` rows by event
        time decode (the global top-limit is a subset of the union of
        per-segment top-limits), so a bounded serving query never pays a
        full-dataset decode."""
        import numpy as np

        if not segs or target_entity_id is None:
            return []
        lo = _ms(start_time) if start_time is not None else None
        hi = _ms(until_time) if until_time is not None else None
        wanted = [
            s
            for s in segs
            if (store_keys is None or s["store"] in store_keys)
            and self._segs_match(
                s, event_names, entity_type, target_entity_type, lo, hi
            )
        ]
        if not wanted:
            return []
        e_code = g_code = None
        if entity_id is not None or target_entity_id is not UNSET:
            def code_of(name: str):
                row = self._c.execute(
                    f"SELECT id FROM {t}_dict WHERE name=?", (name,)
                ).fetchone()
                return row[0] if row else None

            if entity_id is not None:
                e_code = code_of(entity_id)
                if e_code is None:
                    return []
            if target_entity_id is not UNSET:
                g_code = code_of(target_entity_id)
                if g_code is None:
                    return []
        names = self._dict_names(t)
        out: List[Event] = []
        for seg in wanted:
            data = self._open_segment(seg["path"])
            keep = data.keep_mask(
                lo_ms=lo, hi_ms=hi, entity_type=entity_type,
                target_entity_type=(
                    None if target_entity_type is None else target_entity_type
                ),
                target_entity_type_set=target_entity_type is not UNSET,
                event_names=event_names, dead=self._seg_dead(seg),
            )
            e = data.column("entities")
            if e_code is not None:
                m = e == e_code
                keep = m if keep is None else (keep & m)
            if g_code is not None:
                m = data.column("targets") == g_code
                keep = m if keep is None else (keep & m)
            idx = np.nonzero(keep)[0] if keep is not None else np.arange(data.n)
            if not len(idx):
                continue
            if limit is not None and 0 <= limit < len(idx):
                t_of = data.column("times_ms")[idx]
                order = np.argsort(
                    -t_of if reversed else t_of, kind="stable"
                )[:limit]
                idx = idx[np.sort(order)]  # keep row order among chosen
            g = data.column("targets")
            v = data.column("values")
            ts = data.column("times_ms")
            cts = data.column("ctimes_ms")
            ev = data.column("evcodes")
            pr = data.column("propcodes")
            et = data.column("etcodes")
            tet = data.column("tetcodes")
            ids = data.column("ids")
            for j in idx:
                prop = data.props[pr[j]]
                when = _dt.datetime.fromtimestamp(
                    ts[j] / 1000.0, _dt.timezone.utc
                )
                out.append(
                    Event(
                        event_id=ids[j].decode("utf-8"),
                        event=data.event_names[ev[j]],
                        entity_type=data.entity_types[et[j]],
                        entity_id=names[e[j]],
                        target_entity_type=data.target_entity_types[tet[j]],
                        target_entity_id=names[g[j]],
                        properties=DataMap(
                            {prop: float(v[j])} if prop else {}
                        ),
                        event_time=when,
                        creation_time=_dt.datetime.fromtimestamp(
                            cts[j] / 1000.0, _dt.timezone.utc
                        ),
                    )
                )
        return out

    def _get_segment_event(self, t: str, event_id: str) -> Optional[Event]:
        """Probe the segment tier for one event by its ORIGINAL id."""
        import numpy as np

        _, segs = self._segment_state(t)
        if not segs:
            return None
        needle = event_id.encode("utf-8")
        names = None
        for seg in segs:
            data = self._open_segment(seg["path"])
            ids = data.column("ids")
            if len(needle) > ids.dtype.itemsize:
                continue
            hit = data.id_rows([needle])
            if not len(hit):
                continue
            j = int(hit[0])
            dead = self._seg_dead(seg)
            if dead is not None and dead[j]:
                continue
            if names is None:
                names = self._dict_names(t)
            prop = data.props[data.column("propcodes")[j]]
            when = _dt.datetime.fromtimestamp(
                data.column("times_ms")[j] / 1000.0, _dt.timezone.utc
            )
            return Event(
                event_id=event_id,
                event=data.event_names[data.column("evcodes")[j]],
                entity_type=data.entity_types[data.column("etcodes")[j]],
                entity_id=names[data.column("entities")[j]],
                target_entity_type=data.target_entity_types[
                    data.column("tetcodes")[j]
                ],
                target_entity_id=names[data.column("targets")[j]],
                properties=DataMap(
                    {prop: float(data.column("values")[j])} if prop else {}
                ),
                event_time=when,
                creation_time=_dt.datetime.fromtimestamp(
                    data.column("ctimes_ms")[j] / 1000.0, _dt.timezone.utc
                ),
            )
        return None

    def _tombstone_segment_ids(self, t: str, ids: Sequence[str]) -> bool:
        """Set the manifest dead bit for any segment rows carrying these
        ids (delete of a compacted event; explicit-id re-post scrub).
        Segments stay immutable — liveness lives in the manifest."""
        import numpy as np

        if not ids:
            return False
        _, segs = self._segment_state(t)
        if not segs:
            return False
        needles = [i.encode("utf-8") for i in ids]
        changed = False
        for seg in segs:
            data = self._open_segment(seg["path"])
            col = data.column("ids")
            fit = [b for b in needles if len(b) <= col.dtype.itemsize]
            if not fit:
                continue
            hits = data.id_rows(fit)
            if not len(hits):
                continue
            with self._c.lock:
                row = self._c.execute(
                    f"SELECT dead FROM {t}_segments WHERE segment=?",
                    (seg["segment"],),
                ).fetchone()
                if row is None:
                    continue
                dead = (
                    np.frombuffer(row[0], np.uint8).copy()
                    if row[0] is not None
                    else np.zeros(data.n, np.uint8)
                )
                if dead[hits].all():
                    continue
                dead[hits] = 1
                self._c.execute(
                    f"UPDATE {t}_segments SET dead=? WHERE segment=?",
                    (dead.tobytes(), seg["segment"]),
                )
                self._c.commit()
                changed = True
        return changed

    def _ensure_monotonic_rowids(self, store, t: str) -> None:
        """Migrate a pre-segment-tier row table (implicit rowid) to the
        AUTOINCREMENT schema, preserving every rowid. Without this, a
        compaction that empties the table would let sqlite re-issue
        rowids UNDER the watermark — silently invisible events. One
        full-table rewrite, once per store file."""
        ok = getattr(store, "rid_ok", None)
        if ok is None:
            ok = store.rid_ok = set()
        if t in ok:
            return
        with store.lock:
            row = store.conn.execute(
                "SELECT sql FROM sqlite_master WHERE type='table' AND name=?",
                (t,),
            ).fetchone()
            if row is None:
                return
            if "AUTOINCREMENT" in (row[0] or ""):
                ok.add(t)
                return
            mig = f"{t}__rid_mig"
            store.conn.execute(f"DROP TABLE IF EXISTS {mig}")
            self._create_row_table(store, mig)
            # _create_row_table names indexes after its table argument;
            # drop the migration-name indexes and let the final CREATE
            # below rebuild them under the real name
            store.conn.execute(f"DROP INDEX IF EXISTS {mig}_time")
            store.conn.execute(f"DROP INDEX IF EXISTS {mig}_entity")
            store.conn.execute(
                f"INSERT INTO {mig} (rid, {self._ROW_COLS}) "
                f"SELECT rowid, {self._ROW_COLS} FROM {t} ORDER BY rowid"
            )
            store.conn.execute(f"DROP TABLE {t}")
            store.conn.execute(f"ALTER TABLE {mig} RENAME TO {t}")
            store.conn.execute(
                f"CREATE INDEX IF NOT EXISTS {t}_time ON {t} (event_time_ms)"
            )
            store.conn.execute(
                f"CREATE INDEX IF NOT EXISTS {t}_entity ON {t} "
                f"(entity_type, entity_id, event_time_ms)"
            )
            store.conn.commit()
            ok.add(t)

    def _sweep_orphan_segments(self, t: str, live_paths, now_ms: int) -> None:
        """Delete segment files this table owns that no manifest row
        references (a crash between file write and manifest commit, or
        a lost optimistic-concurrency race). Age-gated so a concurrent
        compactor's just-written, not-yet-committed files survive."""
        seg_dir = self._seg_dir()
        if not os.path.isdir(seg_dir):
            return
        prefix = f"{t}."
        cutoff_s = (now_ms / 1000.0) - 3600.0
        for name in os.listdir(seg_dir):
            if not name.startswith(prefix):
                continue
            path = os.path.join(seg_dir, name)
            if path in live_paths:
                continue
            try:
                if os.path.getmtime(path) < cutoff_s:
                    os.remove(path)
                    logger.info("swept orphan segment %s", path)
            except OSError:
                pass

    def compact_app(
        self, app_id: int, channel_id: Optional[int] = None, *, policy=None,
        now_ms: Optional[int] = None,
    ) -> dict:
        """One compaction round for one app/channel: per row store, seal
        the cold qualified prefix above the watermark into immutable
        segment file(s), register them + the advanced watermark in ONE
        main-db transaction, then (grace period permitting) physically
        delete sealed rows. Returns counters for observability. Safe to
        run concurrently with writers, scans, and other compactors (the
        manifest commit re-validates the watermark it started from and
        aborts if another compactor advanced it first)."""
        import time as _t

        from predictionio_tpu.data.storage import segments as _seg

        if self._c.path == ":memory:":
            return {"skipped": "memory database has no segment tier"}
        policy = policy or _seg.CompactionPolicy()
        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            if not self._exists(t):
                return {"skipped": "not initialized"}
        now = int(now_ms if now_ms is not None else _t.time() * 1000)
        self._ensure_segment_schema(t)
        os.makedirs(self._seg_dir(), exist_ok=True)
        cutoff = now - int(policy.cold_s * 1000)
        result = {
            "sealed_events": 0, "segments": 0, "holdouts_added": 0,
            "rows_deleted": 0,
        }
        marks, segs = self._segment_state(t)
        for key, store in enumerate(self._c.row_stores()):
            if not store.has_table(t):
                continue
            sealed = self._compact_store(
                t, key, store, marks, policy, cutoff, now
            )
            for k, v in sealed.items():
                result[k] = result.get(k, 0) + v
        # physical cleanup + orphan sweep run AFTER sealing so a fresh
        # manifest state is observed; both are idempotent
        marks, segs = self._segment_state(t)
        deleted = self._cleanup_sealed_rows(t, marks, segs, policy, now)
        result["rows_deleted"] += deleted
        self._sweep_orphan_segments(
            t, {s["path"] for s in segs}, now
        )
        self._record_compaction_metrics(t, result, marks)
        if result["segments"]:
            logger.info(
                "compacted app %s%s: %d events into %d segment(s)",
                app_id, f"/{channel_id}" if channel_id else "",
                result["sealed_events"], result["segments"],
            )
        return result

    def _record_compaction_metrics(self, t: str, result: dict, marks) -> None:
        """Registry bookkeeping for one compaction round: lifetime
        totals (rounds, sealed events/segments, holdouts, physical
        deletes) plus the per-store rowid watermark as a gauge — the
        numbers ``CachedCompactionStatus`` recomputes with COUNT(*)
        scans, available here for free as monotone counters."""
        from predictionio_tpu.utils import metrics as _metrics

        reg = _metrics.get_registry()
        reg.counter(
            "pio_compaction_rounds_total",
            "Completed compaction rounds (per events table)",
            labels=("table",),
        ).labels(table=t).inc()
        totals = reg.counter(
            "pio_compaction_total",
            "Lifetime compaction work by kind (sealed_events, segments, "
            "holdouts_added, rows_deleted)",
            labels=("table", "kind"),
        )
        for kind in (
            "sealed_events", "segments", "holdouts_added", "rows_deleted"
        ):
            v = result.get(kind, 0)
            if v:
                totals.labels(table=t, kind=kind).inc(v)
        wm = reg.gauge(
            "pio_compaction_watermark",
            "Per-store sealed-rowid watermark (rows at or below are "
            "segment-resident)",
            labels=("table", "store"),
        )
        for store_key, mark in (marks or {}).items():
            watermark = mark[0] if isinstance(mark, tuple) else mark
            wm.labels(table=t, store=str(store_key)).set(float(watermark))

    def _compact_store(
        self, t, key, store, marks, policy, cutoff, now
    ) -> dict:
        import numpy as np

        from predictionio_tpu.data.storage import segments as _seg

        mark = marks.get(key, (0, (), 0, 0))
        wm, holdouts = mark[0], list(mark[1])
        if len(holdouts) >= policy.max_holdouts:
            return {}
        self._ensure_monotonic_rowids(store, t)
        rows = store.read_execute(
            f"SELECT rowid, {self._ROW_COLS} FROM {t} WHERE rowid > ? "
            f"ORDER BY rowid LIMIT ?",
            (wm, int(policy.max_rows)),
        ).fetchall()
        if not rows:
            return {}
        qual = _seg.RowQualifier()
        new_holdouts: list = []
        hi = wm
        day_ms = 86_400_000
        for row in rows:
            if row[9] > cutoff:  # event_time_ms
                if row[9] <= now + day_ms:
                    # genuinely recent (will cool): the cold prefix
                    # ends here
                    break
                # far-future-dated junk never cools — a break here
                # would stall the watermark for the whole store
                # forever; bounded holdout instead
                if len(holdouts) + len(new_holdouts) >= policy.max_holdouts:
                    break
                new_holdouts.append(row[0])
                hi = row[0]
                continue
            if qual.offer(row):
                hi = row[0]
            else:
                if len(holdouts) + len(new_holdouts) >= policy.max_holdouts:
                    break
                new_holdouts.append(row[0])
                hi = row[0]
        if qual.n < max(1, int(policy.min_events)):
            return {}
        # table-global dict codes for the id columns (the page store's
        # code space, so segment batches merge without re-encoding)
        e_uniq, e_inv = np.unique(
            np.asarray(qual.entity_ids, object), return_inverse=True
        )
        g_uniq, g_inv = np.unique(
            np.asarray(qual.target_ids, object), return_inverse=True
        )
        e_codes = self._dict_encode(t, e_uniq)[e_inv]
        g_codes = self._dict_encode(t, g_uniq)[g_inv]
        cols = qual.finish(e_codes, g_codes)
        files: list = []  # (path, footer)
        try:
            for s in range(0, cols.n, int(policy.rows_per_segment)):
                part = cols.slice(s, min(s + int(policy.rows_per_segment), cols.n))
                path = os.path.join(
                    self._seg_dir(),
                    f"{t}.k{key}.{int(part.rids[0])}-{int(part.rids[-1])}"
                    f".{now}-{s}.seg",
                )
                footer = _seg.write_segment_file(path, part)
                files.append((path, footer))
            fault = self.compact_fault
            if fault is not None:
                fault()
            with self._c.lock:
                # BEGIN IMMEDIATE takes the write lock BEFORE the
                # watermark re-read, so the check and the commit are one
                # atomic unit ACROSS PROCESSES too (a deferred
                # transaction would upgrade at the first INSERT — after
                # the check — letting two compactor processes both pass
                # it and register overlapping segment sets)
                self._c.conn.commit()  # close any implicit txn first
                self._c.conn.execute("BEGIN IMMEDIATE")
                try:
                    cur = self._c.conn.execute(
                        f"SELECT watermark FROM {t}_compaction "
                        f"WHERE store=?",
                        (key,),
                    ).fetchone()
                    if cur is not None and int(cur[0]) != wm:
                        # another compactor advanced this store first:
                        # our range overlaps its segments — abandon ours
                        raise _StaleWatermark()
                    for path, footer in files:
                        self._c.conn.execute(
                            f"INSERT INTO {t}_segments (store, n, "
                            f"min_rowid, max_rowid, min_ms, max_ms, "
                            f"events, entity_types, target_entity_types, "
                            f"path, checksum, created_ms, dead) "
                            f"VALUES (?,?,?,?,?,?,?,?,?,?,?,?,NULL)",
                            (
                                key, footer["n"], footer["min_rowid"],
                                footer["max_rowid"], footer["min_ms"],
                                footer["max_ms"],
                                json.dumps(footer["event_names"]),
                                json.dumps(footer["entity_types"]),
                                json.dumps(footer["target_entity_types"]),
                                path, footer["checksum"], now,
                            ),
                        )
                    all_holdouts = np.asarray(
                        holdouts + new_holdouts, np.int64
                    )
                    self._c.conn.execute(
                        f"INSERT OR REPLACE INTO {t}_compaction "
                        f"(store, watermark, cleaned, holdouts, last_ms) "
                        f"VALUES (?,?,?,?,?)",
                        (
                            key, int(hi), int(mark[2]),
                            all_holdouts.tobytes()
                            if len(all_holdouts)
                            else None,
                            now,
                        ),
                    )
                    self._c.commit()
                except BaseException:
                    # NEVER leave the IMMEDIATE transaction open with
                    # partial manifest rows: an unrelated later commit
                    # on this shared connection would persist segments
                    # WITHOUT the watermark advance — every sealed row
                    # then scans twice, forever
                    try:
                        self._c.conn.rollback()
                    except sqlite3.Error:
                        pass
                    raise
            # TOCTOU reconciliation: a delete() (or an explicit-id
            # re-post's REPLACE) that removed a sealed row AFTER our
            # snapshot but BEFORE the manifest commit found no segment
            # to tombstone — re-check the sealed range and tombstone
            # whatever vanished from the row store (deletes after the
            # commit see the manifest and tombstone themselves)
            self._reconcile_sealed_rows(t, store, files, wm, hi)
        except _StaleWatermark:
            for path, _ in files:
                try:
                    os.remove(path)
                except OSError:
                    pass
            return {}
        except BaseException:
            # crash path (incl. injected faults): files may remain as
            # orphans but the manifest never saw them — rows stay
            # authoritative, the sweep reclaims the files later
            raise
        return {
            "sealed_events": int(cols.n),
            "segments": len(files),
            "holdouts_added": len(new_holdouts),
        }

    def _reconcile_sealed_rows(self, t, store, files, wm, hi) -> None:
        """Post-commit sweep of the sealed range: any rowid the segment
        carries that is no longer in the row store was deleted (or
        REPLACE-moved by an explicit-id re-post) during the compaction
        window — tombstone it in the manifest so it cannot resurrect.
        Idempotent; races with concurrent deletes only double-set the
        same dead bits."""
        import numpy as np

        present = np.fromiter(
            (
                r[0]
                for r in store.read_execute(
                    f"SELECT rowid FROM {t} WHERE rowid > ? AND rowid <= ?",
                    (wm, hi),
                ).fetchall()
            ),
            np.int64,
        )
        present.sort()
        for path, footer in files:
            data = self._open_segment(path)
            rids = data.column("rids")
            if len(present):
                pos = np.clip(
                    np.searchsorted(present, rids), 0, len(present) - 1
                )
                found = present[pos] == rids
            else:
                found = np.zeros(len(rids), bool)
            missing = np.nonzero(~found)[0]
            if not len(missing):
                continue
            with self._c.lock:
                row = self._c.execute(
                    f"SELECT segment, dead FROM {t}_segments WHERE path=?",
                    (path,),
                ).fetchone()
                if row is None:
                    continue
                dead = (
                    np.frombuffer(row[1], np.uint8).copy()
                    if row[1] is not None
                    else np.zeros(data.n, np.uint8)
                )
                dead[missing] = 1
                self._c.execute(
                    f"UPDATE {t}_segments SET dead=? WHERE segment=?",
                    (dead.tobytes(), row[0]),
                )
                self._c.commit()
            logger.info(
                "compaction reconciliation: %d row(s) deleted during the "
                "seal window tombstoned in %s", len(missing), path,
            )

    def _cleanup_sealed_rows(self, t, marks, segs, policy, now) -> int:
        """Physically delete sealed rows once their segments are older
        than the grace period (scans snapshot the manifest at start, so
        rows must outlive any scan that began before the seal).
        Idempotent: a crash between the delete and the ``cleaned`` mark
        just re-deletes nothing next round."""
        deleted = 0
        grace_ms = int(policy.grace_s * 1000)
        for key, store in enumerate(self._c.row_stores()):
            mark = marks.get(key)
            if mark is None:
                continue
            wm, holdouts, cleaned = mark[0], mark[1], mark[2]
            eligible = [
                s["max_rowid"]
                for s in segs
                if s["store"] == key
                and s["max_rowid"] > cleaned
                and s["created_ms"] + grace_ms <= now
            ]
            if not eligible:
                continue
            upto = max(eligible)
            if not store.has_table(t):
                continue
            # delete (cleaned, upto] minus holdouts as open intervals
            # between consecutive holdout rowids — bounded statements
            bounds = sorted(
                h for h in holdouts if cleaned < h <= upto
            )
            spans = []
            lo = cleaned
            for h in bounds:
                if h - 1 > lo:
                    spans.append((lo, h - 1))
                lo = h
            if upto > lo:
                spans.append((lo, upto))
            with store.lock:
                for lo_ex, hi_in in spans:
                    cur = store.conn.execute(
                        f"DELETE FROM {t} WHERE rowid > ? AND rowid <= ?",
                        (lo_ex, hi_in),
                    )
                    deleted += max(0, cur.rowcount)
                store.conn.commit()
            with self._c.lock:
                self._c.execute(
                    f"UPDATE {t}_compaction SET cleaned=? WHERE store=?",
                    (int(upto), key),
                )
                self._c.commit()
        return deleted

    def compaction_stats(
        self, app_id: int, channel_id: Optional[int] = None
    ) -> Optional[dict]:
        """Observability summary for status.json / the admin listing:
        segment count, live compacted events, residual row events, the
        compacted fraction of the scannable store, and the last
        compaction timestamp."""
        import numpy as np

        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            if not self._exists(t):
                return None
        marks, segs = self._segment_state(t)
        seg_events = 0
        for s in segs:
            dead = self._seg_dead(s)
            seg_events += int(s["n"]) - (
                int(dead.sum()) if dead is not None else 0
            )
        row_events = 0
        for key, store in enumerate(self._c.row_stores()):
            if not store.has_table(t):
                continue
            pred = self._residual_clause(marks, key)
            sql = f"SELECT COUNT(*) FROM {t}"
            params: list = []
            if pred is not None:
                sql += f" WHERE {pred[0]}"
                params = pred[1]
            row_events += int(store.read_execute(sql, params).fetchone()[0])
        page_events = 0
        self._ensure_pages_schema(t)
        with self._c.lock:
            have_pages = self._exists(f"{t}_pages")
        if have_pages:
            page_events = int(
                self._c.read_execute(
                    f"SELECT COALESCE(TOTAL(n), 0) FROM {t}_pages"
                ).fetchone()[0]
            )
            for (db,) in self._c.read_execute(
                f"SELECT dead FROM {t}_pages WHERE dead IS NOT NULL"
            ).fetchall():
                page_events -= int(np.frombuffer(db, np.uint8).sum())
        total = seg_events + row_events + page_events
        return {
            "segments": len(segs),
            "segmentEvents": seg_events,
            "rowEvents": row_events,
            "pageEvents": page_events,
            "compactedFraction": (seg_events / total) if total else 0.0,
            "lastCompactionMs": max(
                (m[3] for m in marks.values()), default=0
            ),
        }

    def iter_export_segments(
        self, app_id: int, channel_id: Optional[int] = None
    ) -> Iterator[dict]:
        """Bulk-export view of the segment tier: decoded numpy column
        groups, one per homogeneous (event, types, prop) run of each
        segment, live rows only — the near-zero-copy half of segment
        exchange (``tools/export_import.py``). Keys match
        ``iter_export_pages`` plus ``creation_times_ms``; ``event_ids``
        are the ORIGINAL ids, preserved end to end."""
        import numpy as np

        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            if not self._exists(t):
                raise StorageError(f"events table {t} not initialized")
        _, segs = self._segment_state(t)
        if not segs:
            return
        names = self._dict_names(t)
        for seg in segs:
            data = self._open_segment(seg["path"])
            dead = self._seg_dead(seg)
            alive = (
                np.nonzero(dead == 0)[0]
                if dead is not None
                else np.arange(data.n)
            )
            if not len(alive):
                continue
            # group key per row: (event, prop, etype, tetype) — emit
            # maximal CONSECUTIVE runs so row order survives the
            # round trip
            gk = (
                data.column("evcodes").astype(np.int64) * (1 << 48)
                + data.column("propcodes").astype(np.int64) * (1 << 32)
                + data.column("etcodes").astype(np.int64) * (1 << 16)
                + data.column("tetcodes").astype(np.int64)
            )[alive]
            ids = data.ids_str()
            starts = np.concatenate(
                [[0], np.nonzero(gk[1:] != gk[:-1])[0] + 1, [len(alive)]]
            )
            for a, b in zip(starts[:-1], starts[1:]):
                rows = alive[a:b]
                j0 = rows[0]
                yield {
                    "event": data.event_names[data.column("evcodes")[j0]],
                    "entity_type": data.entity_types[
                        data.column("etcodes")[j0]
                    ],
                    "target_entity_type": data.target_entity_types[
                        data.column("tetcodes")[j0]
                    ],
                    "prop": data.props[data.column("propcodes")[j0]],
                    "event_ids": ids[rows],
                    "entity_ids": names[data.column("entities")[rows]],
                    "target_ids": names[data.column("targets")[rows]],
                    "values": data.column("values")[rows],
                    "times_ms": data.column("times_ms")[rows],
                    "creation_times_ms": data.column("ctimes_ms")[rows],
                }

    def insert_segment_encoded(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        event: str,
        entity_type: str,
        target_entity_type: str,
        entity_names,
        entity_codes,
        target_names,
        target_codes,
        values,
        event_ids,
        value_property: str = "rating",
        event_times_ms=None,
        creation_times_ms=None,
    ) -> int:
        """Import a homogeneous column group DIRECTLY as a sealed
        segment, preserving the original event ids — the receiving half
        of near-zero-copy segment exchange. Append-only: the caller
        (``tools/export_import.py``) falls back to the keyed generic
        path when any sampled id already exists in this store."""
        import time as _t

        import numpy as np

        from predictionio_tpu.data.storage import segments as _seg

        if self._c.path == ":memory:":
            raise StorageError("memory database has no segment tier")
        if event.startswith("$"):
            raise StorageError(
                f"insert_segment cannot write special event {event!r}"
            )
        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            if not self._exists(t):
                raise StorageError(f"events table {t} not initialized")
        vals = np.asarray(values, np.float32)
        n = len(vals)
        if n == 0:
            return 0
        times = np.asarray(event_times_ms, np.int64)
        ctimes = (
            np.asarray(creation_times_ms, np.int64)
            if creation_times_ms is not None
            else times
        )
        ids_b = [str(i).encode("utf-8") for i in event_ids]
        width = max(len(b) for b in ids_b)
        if width > _seg.MAX_ID_BYTES:
            raise StorageError("event id exceeds segment id width")
        e_glob = self._dict_encode(t, np.asarray(entity_names, object))[
            np.asarray(entity_codes, np.int64)
        ]
        g_glob = self._dict_encode(t, np.asarray(target_names, object))[
            np.asarray(target_codes, np.int64)
        ]
        cols = _seg.SegmentColumns(
            rids=np.zeros(n, np.int64),  # no source rows: outside every
            ids=np.array(ids_b, dtype=f"S{width}"),  # cleanup range
            entities=e_glob.astype(np.int32),
            targets=g_glob.astype(np.int32),
            values=vals,
            times_ms=times,
            ctimes_ms=ctimes,
            evcodes=np.zeros(n, np.uint16),
            propcodes=np.zeros(n, np.uint16),
            etcodes=np.zeros(n, np.uint16),
            tetcodes=np.zeros(n, np.uint16),
            event_names=[event],
            props=[value_property],
            entity_types=[entity_type],
            target_entity_types=[target_entity_type],
        )
        now = int(_t.time() * 1000)
        self._ensure_segment_schema(t)
        os.makedirs(self._seg_dir(), exist_ok=True)
        path = os.path.join(
            self._seg_dir(),
            f"{t}.import.{now}-{os.getpid()}-"
            f"{int.from_bytes(os.urandom(4), 'big')}.seg",
        )
        footer = _seg.write_segment_file(path, cols)
        with self._c.lock:
            self._c.conn.execute(
                f"INSERT INTO {t}_segments (store, n, min_rowid, max_rowid, "
                f"min_ms, max_ms, events, entity_types, target_entity_types, "
                f"path, checksum, created_ms, dead) "
                f"VALUES (?,?,?,?,?,?,?,?,?,?,?,?,NULL)",
                (
                    0, footer["n"], 0, 0, footer["min_ms"], footer["max_ms"],
                    json.dumps(footer["event_names"]),
                    json.dumps(footer["entity_types"]),
                    json.dumps(footer["target_entity_types"]),
                    path, footer["checksum"], now,
                ),
            )
            self._c.commit()
        return n

    def find_columns_native(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        value_spec=None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        target_entity_type: OptFilter = UNSET,
        event_names: Optional[Sequence[str]] = None,
    ):
        """Binary columnar scan: np.frombuffer over the matching pages +
        a SQL-evaluated residual for row-store events — no per-event
        Python objects on the bulk path (reference
        JDBCPEvents.scala:51-129's partitioned scan)."""
        import numpy as np

        from predictionio_tpu.data.storage.columnar import (
            ColumnarEvents,
            ValueSpec,
        )

        spec = value_spec or ValueSpec()
        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            if not self._exists(t):
                raise StorageError(f"events table {t} not initialized")
        parts: List[ColumnarEvents] = []
        # segment state BEFORE the dict snapshot: a compaction commits
        # its dict inserts first, so any segment this state references
        # resolves through the names we read after it
        marks, segs = self._segment_state(t)
        names = None  # dict snapshot, fetched once on first need

        def dense(codes):
            # compress global dict codes to dense name-sorted
            # indices via a presence bitmap + LUT — three linear
            # passes instead of np.unique's 20M-element argsort
            # (the whole scan's former hot spot)
            seen = np.zeros(len(names), bool)
            seen[codes] = True
            present = np.nonzero(seen)[0]
            pnames = names[present]
            order = np.argsort(pnames)  # distinct-sized
            lut = np.zeros(len(names), np.int32)
            lut[present[order]] = np.arange(
                len(present), dtype=np.int32
            )
            return pnames[order], lut[codes]

        pages = self._page_rows(
            t, start_time, until_time, entity_type, event_names,
            target_entity_type,
        )
        if pages:
            overrides = spec.overrides
            lo = _ms(start_time) if start_time is not None else None
            hi = _ms(until_time) if until_time is not None else None
            e_parts, g_parts, v_parts = [], [], []
            for (
                page, ev, et, tet, prop, n, min_ms, max_ms, eb, gb, vb, tb, db
            ) in pages:
                e = np.frombuffer(eb, np.int32)
                g = np.frombuffer(gb, np.int32)
                ov = overrides.get(ev)
                if ov is not None:
                    v = np.full(n, ov, np.float32)
                elif prop == spec.prop:
                    v = np.frombuffer(vb, np.float32)
                else:  # stored under a different property: all defaults
                    v = np.full(n, spec.default, np.float32)
                needs_time = (lo is not None and min_ms < lo) or (
                    hi is not None and max_ms >= hi
                )
                if needs_time or db is not None:
                    keep = (
                        np.frombuffer(db, np.uint8) == 0
                        if db is not None
                        else np.ones(n, bool)
                    )
                    if needs_time:
                        ts = np.frombuffer(tb, np.int64)
                        if lo is not None:
                            keep = keep & (ts >= lo)
                        if hi is not None:
                            keep = keep & (ts < hi)
                    e, g, v = e[keep], g[keep], v[keep]
                e_parts.append(e)
                g_parts.append(g)
                v_parts.append(v)
            e_all = np.concatenate(e_parts)
            g_all = np.concatenate(g_parts)
            v_all = np.concatenate(v_parts)
            if len(e_all):
                if names is None:
                    names = self._dict_names(t)
                ue_names, e_codes = dense(e_all)
                ug_names, g_codes = dense(g_all)
                parts.append(
                    ColumnarEvents(
                        entity_names=ue_names,
                        target_names=ug_names,
                        entity_codes=e_codes,
                        target_codes=g_codes,
                        values=v_all,
                    )
                )

        # per row store, in deterministic order (main file, then hash
        # shards): first the store's sealed SEGMENTS (its compacted
        # rowid prefix, already in the table-global dict space), then
        # its residual rows — exactly the per-entity event order an
        # uncompacted store's residual scan yields, which is what keeps
        # the merged wire byte-identical. The streaming scan interleaves
        # identically.
        from predictionio_tpu.data.storage.columnar import encode_strings

        lo = _ms(start_time) if start_time is not None else None
        hi = _ms(until_time) if until_time is not None else None
        for key, store in enumerate(self._c.row_stores()):
            seg_e, seg_g, seg_v = [], [], []
            for seg in segs:
                if seg["store"] != key or not self._segs_match(
                    seg, event_names, entity_type, target_entity_type, lo, hi
                ):
                    continue
                data = self._open_segment(seg["path"])
                keep = data.keep_mask(
                    lo_ms=lo, hi_ms=hi, entity_type=entity_type,
                    target_entity_type=(
                        None if target_entity_type is None
                        else target_entity_type
                    ),
                    target_entity_type_set=target_entity_type is not UNSET,
                    event_names=event_names, dead=self._seg_dead(seg),
                )
                e = data.column("entities")
                g = data.column("targets")
                v = data.spec_values(spec)
                if keep is not None:
                    e, g, v = e[keep], g[keep], v[keep]
                if len(v):
                    seg_e.append(e)
                    seg_g.append(g)
                    seg_v.append(v)
            if seg_v:
                if names is None:
                    names = self._dict_names(t)
                ue_names, e_codes = dense(np.concatenate(seg_e))
                ug_names, g_codes = dense(np.concatenate(seg_g))
                parts.append(
                    ColumnarEvents(
                        entity_names=ue_names,
                        target_names=ug_names,
                        entity_codes=e_codes,
                        target_codes=g_codes,
                        values=np.concatenate(seg_v),
                    )
                )
            rows, values, _ = self._residual_scan(
                store, t, spec, start_time, until_time, entity_type,
                target_entity_type, event_names,
                extra=self._residual_clause(marks, key),
            )
            if rows:
                e_names, e_codes = encode_strings([r[0] for r in rows])
                g_names, g_codes = encode_strings([r[1] for r in rows])
                parts.append(
                    ColumnarEvents(
                        entity_names=e_names,
                        target_names=g_names,
                        entity_codes=e_codes,
                        target_codes=g_codes,
                        values=values,
                    )
                )
        return ColumnarEvents.concat(parts)

    def _residual_scan(
        self, store, t, spec, start_time, until_time, entity_type,
        target_entity_type, event_names, extra=None, stats=None,
    ):
        """Row-store residual of a columnar scan (REST-posted tail) for
        ONE row store (the main file or a hash shard) — value evaluated
        IN SQL (CASE per event override + json_extract), so even this
        path never parses JSON in Python. ``extra`` is an optional
        pre-bound ``(clause, params)`` predicate — the segment tier's
        watermark exclusion. Returns ``(rows, values, stat_rows)``: the
        raw (entity_id, target_entity_id, ...) rows, their float32
        training values, and one ``(count, max_rowid)`` pair per entry
        of ``stats`` (a list of pre-bound ``(clause, params)``
        predicates, None clause = whole table), evaluated in the SAME
        read snapshot as the row scan — the delta cursor's coverage
        accounting must be atomic with the rows it vouches for. The
        stat predicates are rowid ranges and watermark bounds only, so
        sqlite answers them from the rowid b-tree without touching the
        filter/json machinery."""
        import numpy as np

        empty_stats = [(0, 0)] * len(stats or [])
        if not store.has_table(t):
            return [], None, empty_stats

        clauses, params = self._find_clauses(
            start_time, until_time, entity_type, None, event_names,
            target_entity_type, UNSET,
        )
        clauses.append("target_entity_id IS NOT NULL")
        if extra is not None:
            clauses.append(extra[0])
            params = list(params) + list(extra[1])
        case_sql = ""
        case_params: list = []
        null_case_sql = ""
        null_case_params: list = []
        for ev_name, const in spec.overrides.items():
            case_sql += "WHEN ? THEN ? "
            case_params.extend([ev_name, float(const)])
            # override events never read the property — mask their type
            # so junk values there stay permitted (value_of skips them)
            null_case_sql += "WHEN ? THEN NULL "
            null_case_params.append(ev_name)
        # json path via parameter; quoted so property names with dots
        # stay one key
        value_sql = (
            "CAST(COALESCE(json_extract(properties, ?), ?) AS REAL)"
        )
        type_sql = "json_type(properties, ?)"
        raw_sql = "json_extract(properties, ?)"
        if case_sql:
            value_sql = f"CASE event {case_sql}ELSE {value_sql} END"
            # mask BOTH helper columns for override events — their
            # properties are never read, so malformed JSON there must not
            # fail the scan (the value CASE short-circuits past it too)
            type_sql = f"CASE event {null_case_sql}ELSE {type_sql} END"
            raw_sql = f"CASE event {null_case_sql}ELSE {raw_sql} END"
        # ORDER BY rowid pins the scan to insertion order. Without it
        # the order is the query planner's choice (the entity index
        # groups by entity id when entity_type filters) — and the
        # segment tier replays sealed rows in ROWID order, so the
        # residual must too or a compacted store's wire would diverge
        # from an uncompacted one's.
        sql = (
            f"SELECT entity_id, target_entity_id, {value_sql}, "
            f"{type_sql}, {raw_sql} FROM {t} "
            "WHERE " + " AND ".join(clauses) + " ORDER BY rowid"
        )
        prop_path = '$."' + spec.prop.replace('"', '""') + '"'
        all_params = (
            case_params + [prop_path, float(spec.default)]
            + null_case_params + [prop_path]
            + null_case_params + [prop_path] + params
        )
        stmts = [(sql, all_params)]
        for stat in stats or []:
            stat_sql = (
                f"SELECT COUNT(*), COALESCE(MAX(rowid), 0) FROM {t}"
            )
            stat_params: list = []
            if stat is not None:
                stat_sql += f" WHERE {stat[0]}"
                stat_params = list(stat[1])
            stmts.append((stat_sql, stat_params))
        results = store.read_snapshot(stmts)
        rows = results[0]
        stat_rows = [
            (int(r[0][0]), int(r[0][1])) for r in results[1:]
        ]
        if not rows:
            return [], None, stat_rows
        # CAST diverges from the per-event path on non-numeric
        # property values (unparseable text silently becomes 0.0;
        # 'nan'/'inf' strings parse in Python but not in CAST) — for
        # the rare rows whose json_type is not numeric, apply the
        # same float() rule ValueSpec.value_of uses, so bad events
        # surface (raise) and parseable text agrees exactly.
        # json null / missing keep the COALESCE default, as value_of
        # keeps its default.
        values = np.fromiter(
            (
                r[2]
                if r[3] in (None, "null", "integer", "real", "true", "false")
                else float(r[4])
                for r in rows
            ),
            np.float32,
            count=len(rows),
        )
        return rows, values, stat_rows

    def stream_columns_native(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        value_spec=None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        target_entity_type: OptFilter = UNSET,
        event_names: Optional[Sequence[str]] = None,
        batch_rows: int = 1_048_576,
    ):
        """Chunked binary columnar scan: one batch per page/segment
        (split past ``batch_rows``), all batches in the TABLE-GLOBAL
        dictionary code space, plus per-store residual batches whose new
        ids extend that space. Order per row store: the store's sealed
        SEGMENTS (its compacted rowid prefix), then its residual rows —
        the per-entity event order of an uncompacted store, which keeps
        the merged wire byte-identical. The page-id list and the segment
        manifest are snapshotted up front (ids/manifest only, no blobs),
        so peak memory is one page/segment and anything committed
        mid-scan is simply not part of this scan."""
        import numpy as np

        from predictionio_tpu.data.storage.columnar import (
            ColumnarStream,
            ValueSpec,
        )

        spec = value_spec or ValueSpec()
        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            if not self._exists(t):
                raise StorageError(f"events table {t} not initialized")
        # fingerprint (and the table generation) BEFORE the scan: a
        # concurrent write during the scan then makes the next cache
        # lookup miss, never hit stale
        fingerprint = self.store_fingerprint(app_id, channel_id)
        generation = self._table_generation(t)
        self._ensure_pages_schema(t)
        # segment state BEFORE the dict snapshot (compaction commits its
        # dict inserts first, so every referenced code resolves)
        marks, segs = self._segment_state(t)
        # The dict-name snapshot and the page-id listing are DEFERRED to
        # first iteration: a continuous-training fold round constructs
        # this stream only for its fingerprint/cursor identity and never
        # consumes it — eager setup would charge every delta round an
        # O(vocab) dict read it doesn't use.
        dict_snapshot, enc, names = self._residual_code_space(t)

        def _page_id_listing() -> List[int]:
            # ids only, no blobs (peak memory stays one page); the
            # filter is the SAME clause builder the monolithic scan
            # uses, so both paths select identical pages by construction
            filt = self._page_filter(
                start_time, until_time, entity_type, event_names,
                target_entity_type,
            )
            if filt is None:
                return []
            clauses, params = filt
            sql = f"SELECT page FROM {t}_pages"
            if clauses:
                sql += " WHERE " + " AND ".join(clauses)
            with self._c.lock:
                have_pages = self._exists(f"{t}_pages")
            if not have_pages:
                return []
            return [
                r[0]
                for r in self._c.read_execute(
                    sql + " ORDER BY page", params
                ).fetchall()
            ]
        # per row store (residual-live count, max residual rowid), read
        # in the SAME snapshot as that store's residual row scan —
        # finalized into the delta cursor on exhaustion. Snapshot
        # atomicity is what keeps the cursor exactly consistent with the
        # folded data: a row committed after the snapshot has a higher
        # rowid and is the next delta's business, never skipped, never
        # double-folded.
        cursor_state = {
            "stores": [(0, 0) for _ in self._c.row_stores()],
        }

        def batches():
            overrides = spec.overrides
            lo = _ms(start_time) if start_time is not None else None
            hi = _ms(until_time) if until_time is not None else None
            # snapshot order: segment state was read above; pages are
            # listed BEFORE the dict snapshot (writers commit dict
            # entries first, pages second — listing first guarantees
            # every listed page's global codes resolve in the names
            # snapshot, and the residual enc() extras can never collide
            # with codes a racing import minted)
            page_ids = _page_id_listing()
            dict_snapshot()
            for page_id in page_ids:
                row = self._c.read_execute(
                    f"SELECT event, prop, n, min_ms, max_ms, entities, "
                    f"targets, vals, times, dead FROM {t}_pages "
                    f"WHERE page=?",
                    (page_id,),
                ).fetchone()
                if row is None:
                    continue  # deleted since listing
                ev, prop, n, min_ms, max_ms, eb, gb, vb, tb, db = row
                e = np.frombuffer(eb, np.int32)
                g = np.frombuffer(gb, np.int32)
                ov = overrides.get(ev)
                if ov is not None:
                    v = np.full(n, ov, np.float32)
                elif prop == spec.prop:
                    v = np.frombuffer(vb, np.float32)
                else:  # stored under a different property: all defaults
                    v = np.full(n, spec.default, np.float32)
                needs_time = (lo is not None and min_ms < lo) or (
                    hi is not None and max_ms >= hi
                )
                if needs_time or db is not None:
                    keep = (
                        np.frombuffer(db, np.uint8) == 0
                        if db is not None
                        else np.ones(n, bool)
                    )
                    if needs_time:
                        ts = np.frombuffer(tb, np.int64)
                        if lo is not None:
                            keep = keep & (ts >= lo)
                        if hi is not None:
                            keep = keep & (ts < hi)
                    e, g, v = e[keep], g[keep], v[keep]
                for s in range(0, len(v), batch_rows):
                    sl = slice(s, s + batch_rows)
                    if len(v[sl]):
                        yield e[sl], g[sl], v[sl]
            # per row store, in deterministic order (main file, then
            # hash shards — the same order find_columns_native
            # concatenates them): the store's segments (already in the
            # global dict code space, like pages), then its residual
            # rows. All stores' residual ids map into ONE shared code
            # space through a name->code dict; unseen ids extend it
            # (the residual is the REST tail — small next to the
            # page/segment bulk). Events of one entity live in one
            # shard, so each entity's events keep their per-store order
            # and the consumer's stable counting-sort merge reproduces
            # the single-file, uncompacted wire byte-for-byte.
            tet_set = target_entity_type is not UNSET
            for key, store in enumerate(self._c.row_stores()):
                for seg in segs:
                    if seg["store"] != key or not self._segs_match(
                        seg, event_names, entity_type, target_entity_type,
                        lo, hi,
                    ):
                        continue
                    data = self._open_segment(seg["path"])
                    keep = data.keep_mask(
                        lo_ms=lo, hi_ms=hi, entity_type=entity_type,
                        target_entity_type=(
                            None if target_entity_type is None
                            else target_entity_type
                        ),
                        target_entity_type_set=tet_set,
                        event_names=event_names, dead=self._seg_dead(seg),
                    )
                    e = data.column("entities")
                    g = data.column("targets")
                    v = data.spec_values(spec)
                    if keep is not None:
                        e, g, v = e[keep], g[keep], v[keep]
                    for s in range(0, len(v), batch_rows):
                        sl = slice(s, s + batch_rows)
                        if len(v[sl]):
                            yield e[sl], g[sl], v[sl]
                residual_pred = self._residual_clause(marks, key)
                rows, values, stats = self._residual_scan(
                    store, t, spec, start_time, until_time, entity_type,
                    target_entity_type, event_names,
                    extra=residual_pred,
                    # UNFILTERED residual-live coverage, same snapshot
                    stats=[residual_pred],
                )
                cursor_state["stores"][key] = stats[0]
                if not rows:
                    continue
                e_codes = enc([r[0] for r in rows])
                g_codes = enc([r[1] for r in rows])
                for s in range(0, len(values), batch_rows):
                    sl = slice(s, s + batch_rows)
                    if len(values[sl]):
                        yield e_codes[sl], g_codes[sl], values[sl]

        def cursor():
            return self._delta_cursor(
                cursor_state["stores"], marks, segs, fingerprint,
                generation,
            )

        return ColumnarStream(
            batches(), names, fingerprint=fingerprint, cursor_fn=cursor
        )

    # --- delta scan (incremental training, round 9) ---
    #
    # A scan's cursor records, per row store, the high-water rowid it
    # covered (the store's max rowid at the scan's snapshot, residual
    # and sealed alike), how many LIVE rows sat at or below it —
    # unfiltered: residual-live count + sealed-live manifest sums — and
    # the compaction state (watermark + holdouts) it replayed under;
    # the page-store signature rides along whole. The delta scan
    # re-validates all of it: rowids are AUTOINCREMENT (PR 4 migrated
    # every row table) so the covered prefix can never grow back, the
    # live count at or below the mark is monotone non-increasing under
    # the only mutations sqlite allows (delete, tombstone, explicit-id
    # re-post — which reassigns the rowid), and compaction only moves
    # rows across the segment/residual split without changing the sum.
    # Count equality therefore PROVES the folded prefix is still
    # exactly what a full rescan would emit first — and the delta is
    # every matching row above the mark, sealed segments first (their
    # manifest order IS rowid order), then residual rows, the same
    # order the full scan interleaves. Everything the validation reads
    # is rowid-b-tree range counts and manifest/dead-bitmap sums — no
    # per-row filter or json evaluation, so polling a quiet 20M store
    # costs milliseconds, not a scan.

    @staticmethod
    def _seg_live_count(seg, dead_arr) -> int:
        n = int(seg["n"])
        return n - int(dead_arr.sum()) if dead_arr is not None else n

    def _residual_code_space(self, t: str):
        """The streaming scans' shared code space: a DEFERRED
        table-global dict snapshot, the residual-tail string encoder
        over it, and the post-iteration ``names`` resolver. One
        implementation for the native scan AND the delta scan — the
        fold's wire byte-identity depends on both paths encoding
        residual ids identically (code seeding, extra-name append
        order, names() concatenation), so they must never diverge.

        Deferral matters twice over: a continuous-training fold round
        constructs the native stream only for its fingerprint/cursor
        identity, and an empty delta round has no residual rows — in
        both cases the O(vocab) dict read never happens. Call
        ``snapshot()``/``enc()`` only AFTER the data they cover was
        listed: the dict is append-only, so a later snapshot is always
        a superset of the codes that data references, and extras minted
        past it can never collide."""
        import numpy as np

        state: dict = {"names": None, "extra": [], "code_of": None}

        def snapshot():
            if state["names"] is None:
                state["names"] = self._dict_names(t)
            return state["names"]

        def enc(strs):
            if state["code_of"] is None:
                state["code_of"] = {
                    str(nm): j for j, nm in enumerate(snapshot())
                }
            code_of = state["code_of"]
            out = np.empty(len(strs), np.int32)
            for j, s in enumerate(strs):
                c = code_of.get(s)
                if c is None:
                    c = len(code_of)
                    code_of[s] = c
                    state["extra"].append(s)
                out[j] = c
            return out

        def names():
            base_names = snapshot()
            if not state["extra"]:
                return base_names
            extra = np.empty(len(state["extra"]), object)
            extra[:] = state["extra"]
            return np.concatenate([base_names, extra])

        return snapshot, enc, names

    def _table_generation(self, t: str) -> int:
        """Monotone per-events-table generation (main db, survives the
        table itself): ``remove()`` bumps it, so a delta cursor taken
        before a DROP — which resets the AUTOINCREMENT sequence — can
        never validate against the recreated table."""
        with self._c.lock:
            self._c.execute(_GEN_SCHEMA)
            row = self._c.execute(
                "SELECT gen FROM pio_table_gen WHERE tbl=?", (t,)
            ).fetchone()
            if row is not None:
                return int(row[0])
            self._c.execute(
                "INSERT INTO pio_table_gen (tbl, gen) VALUES (?, 1)",
                (t,),
            )
            self._c.commit()
            return 1

    def _delta_cursor(
        self, stores, marks, segs, fingerprint, generation
    ) -> tuple:
        """Assemble the opaque cursor from the per-store residual
        coverage (``(residual-live count, max residual rowid)`` read in
        the residual scan's snapshot), the segment manifest, the
        compaction snapshot, the pre-scan fingerprint's page-store
        component, and the table generation."""
        parts = []
        for key, (rcount, rmax) in enumerate(stores):
            sealed_live = 0
            seg_max = 0
            for seg in segs:
                if seg["store"] != key:
                    continue
                sealed_live += self._seg_live_count(
                    seg, self._seg_dead(seg)
                )
                seg_max = max(seg_max, int(seg["max_rowid"]))
            hwm = max(int(rmax), seg_max)
            mark = marks.get(key) if marks else None
            wm = mark[0] if mark else 0
            holds = mark[1] if mark else ()
            parts.append(
                (
                    hwm,
                    int(rcount) + sealed_live,
                    int(wm),
                    tuple(h for h in holds if h <= hwm),
                )
            )
        pages_sig = (
            (fingerprint[2], fingerprint[3]) if fingerprint else None
        )
        return (
            "sqlite-delta", int(generation), len(parts), tuple(parts),
            pages_sig,
        )

    def stream_columns_delta(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        cursor: tuple,
        value_spec=None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        target_entity_type: OptFilter = UNSET,
        event_names: Optional[Sequence[str]] = None,
        batch_rows: int = 1_048_576,
    ):
        """Incremental columnar scan above a prior scan's cursor
        (``base.LEvents.stream_columns_delta``). Returns None — full
        repack — whenever appending the delta could NOT reproduce a full
        rescan: page-store changes (bulk imports order before all row
        stores), any shrink of the matching live rows at or below a
        store's high-water mark (delete / tombstone / explicit-id
        re-post), new holdouts at or below the mark or a watermark that
        moved past interleaved holdouts (both reorder the already-folded
        prefix), or a changed shard layout."""
        import numpy as np

        from predictionio_tpu.data.storage.columnar import (
            ColumnarStream,
            ValueSpec,
        )

        if (
            not isinstance(cursor, tuple)
            or len(cursor) != 5
            or cursor[0] != "sqlite-delta"
        ):
            return None
        spec = value_spec or ValueSpec()
        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            if not self._exists(t):
                return None
        stores = self._c.row_stores()
        if cursor[2] != len(stores):
            return None  # shard layout changed under the cursor
        generation = self._table_generation(t)
        if cursor[1] != generation:
            # the table was dropped and recreated since the cursor:
            # its AUTOINCREMENT sequence restarted, so rowid/count
            # arithmetic against the old prefix proves nothing
            return None
        # fingerprint BEFORE the scan (labels the folded artifact; a
        # racing write makes the next cache lookup miss, never hit stale)
        fingerprint = self.store_fingerprint(app_id, channel_id)
        self._ensure_pages_schema(t)
        marks, segs = self._segment_state(t)
        pages_sig = (
            (fingerprint[2], fingerprint[3]) if fingerprint else None
        )
        if pages_sig != cursor[4]:
            return None  # page store changed: pages order before rows
        lo = _ms(start_time) if start_time is not None else None
        hi = _ms(until_time) if until_time is not None else None

        per_store = []  # (seg_parts, rows, values) to emit, store order
        new_parts = []  # the chained cursor's per-store records
        for key, store in enumerate(stores):
            hwm, live_then, wm_then, holds_then = cursor[3][key]
            mark = marks.get(key) if marks else None
            wm_now = mark[0] if mark else 0
            holds_now = mark[1] if mark else ()
            if tuple(h for h in holds_now if h <= hwm) != holds_then:
                # compaction held out rows inside the folded prefix: a
                # full rescan now replays them AFTER sealed rows the
                # fold placed them before
                return None
            if holds_then and wm_now != wm_then:
                # sealed rows moved past interleaved holdouts (see
                # docs/PERF.md, delta training): replay order of the
                # folded prefix changed
                return None
            sealed_le = 0  # live sealed rows at or below the mark
            sealed_above = 0  # live sealed rows above it (delta region)
            seg_max = 0
            seg_parts = []  # (SegmentData, mask): matching rows > hwm
            for seg in segs:
                if seg["store"] != key:
                    continue
                seg_max = max(seg_max, int(seg["max_rowid"]))
                dead_arr = self._seg_dead(seg)
                if seg["max_rowid"] <= hwm:
                    sealed_le += self._seg_live_count(seg, dead_arr)
                elif seg["min_rowid"] > hwm:
                    sealed_above += self._seg_live_count(seg, dead_arr)
                else:  # straddles the mark: split by source rowid
                    data = self._open_segment(seg["path"])
                    rid = data.column("rids")
                    alive = (
                        dead_arr == 0
                        if dead_arr is not None
                        else np.ones(data.n, bool)
                    )
                    sealed_le += int(
                        np.count_nonzero(alive & (rid <= hwm))
                    )
                    sealed_above += int(
                        np.count_nonzero(alive & (rid > hwm))
                    )
                if seg["max_rowid"] > hwm and self._segs_match(
                    seg, event_names, entity_type, target_entity_type,
                    lo, hi,
                ):
                    data = self._open_segment(seg["path"])
                    keep = data.keep_mask(
                        lo_ms=lo, hi_ms=hi, entity_type=entity_type,
                        target_entity_type=(
                            None if target_entity_type is None
                            else target_entity_type
                        ),
                        target_entity_type_set=(
                            target_entity_type is not UNSET
                        ),
                        event_names=event_names, dead=self._seg_dead(seg),
                    )
                    if keep is None:
                        keep = np.ones(data.n, bool)
                    dm = keep & (data.column("rids") > hwm)
                    if dm.any():
                        seg_parts.append((data, dm))
            residual_pred = self._residual_clause(marks, key)
            rows, values, stats = self._residual_scan(
                store, t, spec, start_time, until_time, entity_type,
                target_entity_type, event_names,
                extra=self._and_extras(
                    residual_pred, ("rowid > ?", [hwm])
                ),
                # same-snapshot coverage accounting, rowid ranges only:
                # live residual rows at/below the mark, and the count +
                # max rowid of the delta region
                stats=[
                    self._and_extras(
                        residual_pred, ("rowid <= ?", [hwm])
                    ),
                    self._and_extras(
                        residual_pred, ("rowid > ?", [hwm])
                    ),
                ],
            )
            (resid_le, _), (resid_above, resid_max_above) = stats
            if resid_le + sealed_le != live_then:
                return None  # the folded prefix shrank: full repack
            new_hwm = max(hwm, seg_max, resid_max_above)
            new_live = live_then + resid_above + sealed_above
            new_holds = tuple(h for h in holds_now if h <= new_hwm)
            new_parts.append((new_hwm, new_live, int(wm_now), new_holds))
            per_store.append((seg_parts, rows, values))

        # shared deferred code space (see _residual_code_space): an
        # empty delta round — common while polling — never pays the
        # O(vocab) dict read, and the residual encoding is the SAME
        # implementation the native scan uses, byte for byte
        _, enc, names = self._residual_code_space(t)

        new_cursor = (
            "sqlite-delta", generation, len(new_parts),
            tuple(new_parts), pages_sig,
        )

        def batches():
            for seg_parts, rows, values in per_store:
                for data, dm in seg_parts:
                    e = data.column("entities")[dm]
                    g = data.column("targets")[dm]
                    v = data.spec_values(spec)[dm]
                    for s in range(0, len(v), batch_rows):
                        sl = slice(s, s + batch_rows)
                        if len(v[sl]):
                            yield e[sl], g[sl], v[sl]
                if not rows:
                    continue
                e_codes = enc([r[0] for r in rows])
                g_codes = enc([r[1] for r in rows])
                for s in range(0, len(values), batch_rows):
                    sl = slice(s, s + batch_rows)
                    if len(values[sl]):
                        yield e_codes[sl], g_codes[sl], values[sl]

        return ColumnarStream(
            batches(), names, fingerprint=fingerprint,
            cursor_fn=lambda: new_cursor,
        )

    def store_fingerprint(
        self, app_id: int, channel_id: Optional[int] = None
    ) -> Optional[tuple]:
        """Cheap store-state aggregates: per row store (the main file
        plus every hash shard) a (count, max rowid, max event time)
        triple, + page store (count, max page id, total rows, max time)
        + exact tombstone populations + the segment manifest (id, n,
        dead population per segment). Every mutating path moves at
        least one component: inserts bump their shard's counts/max-rowid
        (INSERT OR REPLACE reassigns the rowid), bulk imports add pages,
        compactions register segments, deletes shrink counts or flip
        tombstone bits. Row triples apply the segment tier's residual
        predicate, so the DEFERRED physical delete of sealed rows (pure
        space reclaim, no logical change) never moves the fingerprint —
        the pack cache keeps hitting across cleanups. Costs a few
        aggregate scans plus one pass over the (rare) dead blobs."""
        import numpy as np

        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            if not self._exists(t):
                return None
        marks, segs = self._segment_state(t)
        row_parts = []
        for key, store in enumerate(self._c.row_stores()):
            if not store.has_table(t):
                row_parts.append((0, 0, 0))
                continue
            sql = (
                f"SELECT COUNT(*), COALESCE(MAX(rowid), 0), "
                f"COALESCE(MAX(event_time_ms), 0) FROM {t}"
            )
            pred = self._residual_clause(marks, key)
            params: list = []
            if pred is not None:
                sql += f" WHERE {pred[0]}"
                params = pred[1]
            row_parts.append(
                tuple(store.read_execute(sql, params).fetchone())
            )
        row = tuple(row_parts)
        seg_sig = tuple(
            (
                s["segment"], s["n"],
                int(np.frombuffer(s["dead"], np.uint8).sum())
                if s["dead"] is not None
                else 0,
            )
            for s in segs
        )
        pages = (0, 0, 0, 0)
        dead_sig: tuple = ()
        self._ensure_pages_schema(t)
        with self._c.lock:
            have_pages = self._exists(f"{t}_pages")
        if have_pages:
            pages = tuple(
                self._c.read_execute(
                    f"SELECT COUNT(*), COALESCE(MAX(page), 0), "
                    f"COALESCE(TOTAL(n), 0), COALESCE(MAX(max_ms), 0) "
                    f"FROM {t}_pages"
                ).fetchone()
            )
            dead_sig = tuple(
                (page, int(np.frombuffer(db, np.uint8).sum()))
                for page, db in self._c.read_execute(
                    f"SELECT page, dead FROM {t}_pages "
                    f"WHERE dead IS NOT NULL ORDER BY page"
                ).fetchall()
            )
        return ("sqlite", row, pages, dead_sig, seg_sig)


class _SQLiteMetaBase:
    def __init__(self, client: StorageClient, config=None, namespace: str = ""):
        self._c = client
        self._ns = namespace or "pio"
        with self._c.lock:
            self._create()
            self._c.commit()

    def _t(self, suffix: str) -> str:
        return _table_name(self._ns, suffix)

    def _create(self) -> None:
        raise NotImplementedError


class SQLiteApps(_SQLiteMetaBase, base.Apps):
    def _create(self):
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {self._t('apps')} (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                name TEXT NOT NULL UNIQUE,
                description TEXT)"""
        )

    def insert(self, app: App) -> Optional[int]:
        with self._c.lock:
            try:
                if app.id:
                    cur = self._c.execute(
                        f"INSERT INTO {self._t('apps')} (id,name,description) VALUES (?,?,?)",
                        (app.id, app.name, app.description),
                    )
                else:
                    cur = self._c.execute(
                        f"INSERT INTO {self._t('apps')} (name,description) VALUES (?,?)",
                        (app.name, app.description),
                    )
                self._c.commit()
                return cur.lastrowid if not app.id else app.id
            except sqlite3.IntegrityError:
                return None

    def get(self, app_id: int) -> Optional[App]:
        row = self._c.execute(
            f"SELECT id,name,description FROM {self._t('apps')} WHERE id=?", (app_id,)
        ).fetchone()
        return App(*row) if row else None

    def get_by_name(self, name: str) -> Optional[App]:
        row = self._c.execute(
            f"SELECT id,name,description FROM {self._t('apps')} WHERE name=?", (name,)
        ).fetchone()
        return App(*row) if row else None

    def get_all(self) -> List[App]:
        rows = self._c.execute(
            f"SELECT id,name,description FROM {self._t('apps')} ORDER BY id"
        ).fetchall()
        return [App(*r) for r in rows]

    def update(self, app: App) -> bool:
        with self._c.lock:
            cur = self._c.execute(
                f"UPDATE {self._t('apps')} SET name=?,description=? WHERE id=?",
                (app.name, app.description, app.id),
            )
            self._c.commit()
            return cur.rowcount > 0

    def delete(self, app_id: int) -> bool:
        with self._c.lock:
            cur = self._c.execute(
                f"DELETE FROM {self._t('apps')} WHERE id=?", (app_id,)
            )
            self._c.commit()
            return cur.rowcount > 0


class SQLiteAccessKeys(_SQLiteMetaBase, base.AccessKeys):
    def _create(self):
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {self._t('access_keys')} (
                key TEXT PRIMARY KEY, appid INTEGER NOT NULL, events TEXT)"""
        )

    def insert(self, access_key: AccessKey) -> Optional[str]:
        key = access_key.key or self.generate_key()
        with self._c.lock:
            try:
                self._c.execute(
                    f"INSERT INTO {self._t('access_keys')} VALUES (?,?,?)",
                    (key, access_key.appid, json.dumps(list(access_key.events))),
                )
                self._c.commit()
                return key
            except sqlite3.IntegrityError:
                return None

    @staticmethod
    def _row(row) -> AccessKey:
        return AccessKey(row[0], row[1], tuple(json.loads(row[2] or "[]")))

    def get(self, key: str) -> Optional[AccessKey]:
        row = self._c.execute(
            f"SELECT * FROM {self._t('access_keys')} WHERE key=?", (key,)
        ).fetchone()
        return self._row(row) if row else None

    def get_all(self) -> List[AccessKey]:
        return [
            self._row(r)
            for r in self._c.execute(
                f"SELECT * FROM {self._t('access_keys')}"
            ).fetchall()
        ]

    def get_by_app_id(self, app_id: int) -> List[AccessKey]:
        return [
            self._row(r)
            for r in self._c.execute(
                f"SELECT * FROM {self._t('access_keys')} WHERE appid=?", (app_id,)
            ).fetchall()
        ]

    def update(self, access_key: AccessKey) -> bool:
        with self._c.lock:
            cur = self._c.execute(
                f"UPDATE {self._t('access_keys')} SET appid=?,events=? WHERE key=?",
                (access_key.appid, json.dumps(list(access_key.events)), access_key.key),
            )
            self._c.commit()
            return cur.rowcount > 0

    def delete(self, key: str) -> bool:
        with self._c.lock:
            cur = self._c.execute(
                f"DELETE FROM {self._t('access_keys')} WHERE key=?", (key,)
            )
            self._c.commit()
            return cur.rowcount > 0


class SQLiteChannels(_SQLiteMetaBase, base.Channels):
    def _create(self):
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {self._t('channels')} (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                name TEXT NOT NULL, appid INTEGER NOT NULL)"""
        )

    def insert(self, channel: Channel) -> Optional[int]:
        if not Channel.is_valid_name(channel.name):
            return None
        with self._c.lock:
            if channel.id:
                self._c.execute(
                    f"INSERT INTO {self._t('channels')} (id,name,appid) VALUES (?,?,?)",
                    (channel.id, channel.name, channel.appid),
                )
                cid = channel.id
            else:
                cur = self._c.execute(
                    f"INSERT INTO {self._t('channels')} (name,appid) VALUES (?,?)",
                    (channel.name, channel.appid),
                )
                cid = cur.lastrowid
            self._c.commit()
            return cid

    def get(self, channel_id: int) -> Optional[Channel]:
        row = self._c.execute(
            f"SELECT id,name,appid FROM {self._t('channels')} WHERE id=?",
            (channel_id,),
        ).fetchone()
        return Channel(*row) if row else None

    def get_by_app_id(self, app_id: int) -> List[Channel]:
        rows = self._c.execute(
            f"SELECT id,name,appid FROM {self._t('channels')} WHERE appid=?",
            (app_id,),
        ).fetchall()
        return [Channel(*r) for r in rows]

    def delete(self, channel_id: int) -> bool:
        with self._c.lock:
            cur = self._c.execute(
                f"DELETE FROM {self._t('channels')} WHERE id=?", (channel_id,)
            )
            self._c.commit()
            return cur.rowcount > 0


class SQLiteEngineManifests(_SQLiteMetaBase, base.EngineManifests):
    def _create(self):
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {self._t('engine_manifests')} (
                id TEXT, version TEXT, name TEXT, description TEXT,
                files TEXT, engine_factory TEXT,
                PRIMARY KEY (id, version))"""
        )

    def insert(self, manifest: EngineManifest) -> None:
        self.update(manifest, upsert=True)

    def get(self, id: str, version: str) -> Optional[EngineManifest]:
        row = self._c.execute(
            f"SELECT * FROM {self._t('engine_manifests')} WHERE id=? AND version=?",
            (id, version),
        ).fetchone()
        if not row:
            return None
        return EngineManifest(
            row[0], row[1], row[2], row[3], tuple(json.loads(row[4] or "[]")), row[5]
        )

    def get_all(self) -> List[EngineManifest]:
        rows = self._c.execute(
            f"SELECT * FROM {self._t('engine_manifests')}"
        ).fetchall()
        return [
            EngineManifest(r[0], r[1], r[2], r[3], tuple(json.loads(r[4] or "[]")), r[5])
            for r in rows
        ]

    def update(self, manifest: EngineManifest, upsert: bool = False) -> None:
        with self._c.lock:
            self._c.execute(
                f"INSERT OR REPLACE INTO {self._t('engine_manifests')} VALUES (?,?,?,?,?,?)",
                (
                    manifest.id,
                    manifest.version,
                    manifest.name,
                    manifest.description,
                    json.dumps(list(manifest.files)),
                    manifest.engine_factory,
                ),
            )
            self._c.commit()

    def delete(self, id: str, version: str) -> None:
        with self._c.lock:
            self._c.execute(
                f"DELETE FROM {self._t('engine_manifests')} WHERE id=? AND version=?",
                (id, version),
            )
            self._c.commit()


_EI_COLS = (
    "id, status, start_time, end_time, engine_id, engine_version, "
    "engine_variant, engine_factory, batch, env, spark_conf, "
    "data_source_params, preparator_params, algorithms_params, serving_params"
)


class SQLiteEngineInstances(_SQLiteMetaBase, base.EngineInstances):
    def _create(self):
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {self._t('engine_instances')} (
                id TEXT PRIMARY KEY, status TEXT, start_time TEXT, end_time TEXT,
                engine_id TEXT, engine_version TEXT, engine_variant TEXT,
                engine_factory TEXT, batch TEXT, env TEXT, spark_conf TEXT,
                data_source_params TEXT, preparator_params TEXT,
                algorithms_params TEXT, serving_params TEXT)"""
        )

    @staticmethod
    def _row(r) -> EngineInstance:
        return EngineInstance(
            id=r[0],
            status=r[1],
            start_time=parse_iso8601(r[2]),
            end_time=parse_iso8601(r[3]),
            engine_id=r[4],
            engine_version=r[5],
            engine_variant=r[6],
            engine_factory=r[7],
            batch=r[8] or "",
            env=json.loads(r[9] or "{}"),
            spark_conf=json.loads(r[10] or "{}"),
            data_source_params=r[11] or "",
            preparator_params=r[12] or "",
            algorithms_params=r[13] or "",
            serving_params=r[14] or "",
        )

    def _write(self, i: EngineInstance) -> None:
        self._c.execute(
            f"INSERT OR REPLACE INTO {self._t('engine_instances')} "
            f"VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
            (
                i.id,
                i.status,
                _utc_iso(i.start_time),
                _utc_iso(i.end_time),
                i.engine_id,
                i.engine_version,
                i.engine_variant,
                i.engine_factory,
                i.batch,
                json.dumps(i.env),
                json.dumps(i.spark_conf),
                i.data_source_params,
                i.preparator_params,
                i.algorithms_params,
                i.serving_params,
            ),
        )

    def insert(self, instance: EngineInstance) -> str:
        import uuid

        iid = instance.id or uuid.uuid4().hex[:17]
        with self._c.lock:
            self._write(dataclasses.replace(instance, id=iid))
            self._c.commit()
        return iid

    def get(self, id: str) -> Optional[EngineInstance]:
        row = self._c.execute(
            f"SELECT {_EI_COLS} FROM {self._t('engine_instances')} WHERE id=?", (id,)
        ).fetchone()
        return self._row(row) if row else None

    def get_all(self) -> List[EngineInstance]:
        rows = self._c.execute(
            f"SELECT {_EI_COLS} FROM {self._t('engine_instances')}"
        ).fetchall()
        return [self._row(r) for r in rows]

    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> List[EngineInstance]:
        rows = self._c.execute(
            f"SELECT {_EI_COLS} FROM {self._t('engine_instances')} "
            "WHERE status=? AND engine_id=? AND engine_version=? AND engine_variant=? "
            "ORDER BY start_time DESC",
            (base.STATUS_COMPLETED, engine_id, engine_version, engine_variant),
        ).fetchall()
        return [self._row(r) for r in rows]

    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> Optional[EngineInstance]:
        out = self.get_completed(engine_id, engine_version, engine_variant)
        return out[0] if out else None

    def update(self, instance: EngineInstance) -> None:
        with self._c.lock:
            self._write(instance)
            self._c.commit()

    def delete(self, id: str) -> None:
        with self._c.lock:
            self._c.execute(
                f"DELETE FROM {self._t('engine_instances')} WHERE id=?", (id,)
            )
            self._c.commit()


class SQLiteEvaluationInstances(_SQLiteMetaBase, base.EvaluationInstances):
    def _create(self):
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {self._t('evaluation_instances')} (
                id TEXT PRIMARY KEY, status TEXT, start_time TEXT, end_time TEXT,
                evaluation_class TEXT, engine_params_generator_class TEXT,
                batch TEXT, env TEXT, spark_conf TEXT,
                evaluator_results TEXT, evaluator_results_html TEXT,
                evaluator_results_json TEXT)"""
        )

    @staticmethod
    def _row(r) -> EvaluationInstance:
        return EvaluationInstance(
            id=r[0],
            status=r[1],
            start_time=parse_iso8601(r[2]),
            end_time=parse_iso8601(r[3]),
            evaluation_class=r[4] or "",
            engine_params_generator_class=r[5] or "",
            batch=r[6] or "",
            env=json.loads(r[7] or "{}"),
            spark_conf=json.loads(r[8] or "{}"),
            evaluator_results=r[9] or "",
            evaluator_results_html=r[10] or "",
            evaluator_results_json=r[11] or "",
        )

    def _write(self, i: EvaluationInstance) -> None:
        self._c.execute(
            f"INSERT OR REPLACE INTO {self._t('evaluation_instances')} "
            f"VALUES (?,?,?,?,?,?,?,?,?,?,?,?)",
            (
                i.id,
                i.status,
                _utc_iso(i.start_time),
                _utc_iso(i.end_time),
                i.evaluation_class,
                i.engine_params_generator_class,
                i.batch,
                json.dumps(i.env),
                json.dumps(i.spark_conf),
                i.evaluator_results,
                i.evaluator_results_html,
                i.evaluator_results_json,
            ),
        )

    def insert(self, instance: EvaluationInstance) -> str:
        import uuid

        iid = instance.id or uuid.uuid4().hex[:17]
        with self._c.lock:
            self._write(dataclasses.replace(instance, id=iid))
            self._c.commit()
        return iid

    def get(self, id: str) -> Optional[EvaluationInstance]:
        row = self._c.execute(
            f"SELECT * FROM {self._t('evaluation_instances')} WHERE id=?", (id,)
        ).fetchone()
        return self._row(row) if row else None

    def get_all(self) -> List[EvaluationInstance]:
        rows = self._c.execute(
            f"SELECT * FROM {self._t('evaluation_instances')}"
        ).fetchall()
        return [self._row(r) for r in rows]

    def get_completed(self) -> List[EvaluationInstance]:
        rows = self._c.execute(
            f"SELECT * FROM {self._t('evaluation_instances')} "
            "WHERE status=? ORDER BY start_time DESC",
            (base.STATUS_COMPLETED,),
        ).fetchall()
        return [self._row(r) for r in rows]

    def update(self, instance: EvaluationInstance) -> None:
        with self._c.lock:
            self._write(instance)
            self._c.commit()

    def delete(self, id: str) -> None:
        with self._c.lock:
            self._c.execute(
                f"DELETE FROM {self._t('evaluation_instances')} WHERE id=?", (id,)
            )
            self._c.commit()


class SQLiteModels(_SQLiteMetaBase, base.Models):
    def _create(self):
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {self._t('models')} (
                id TEXT PRIMARY KEY, models BLOB)"""
        )

    def insert(self, model: Model) -> None:
        with self._c.lock:
            self._c.execute(
                f"INSERT OR REPLACE INTO {self._t('models')} VALUES (?,?)",
                (model.id, model.models),
            )
            self._c.commit()

    def get(self, id: str) -> Optional[Model]:
        row = self._c.execute(
            f"SELECT id, models FROM {self._t('models')} WHERE id=?", (id,)
        ).fetchone()
        return Model(row[0], row[1]) if row else None

    def delete(self, id: str) -> None:
        with self._c.lock:
            self._c.execute(
                f"DELETE FROM {self._t('models')} WHERE id=?", (id,)
            )
            self._c.commit()
