"""``http`` storage backend — client half of the client-server storage.

Implements every DAO trait in base.py against a storage gateway service
(api/storage_gateway.py) over HTTP, the role the reference's HBase/JDBC/
Elasticsearch clients play (Storage.getDataObject resolves
``io.prediction.data.storage.<type>.<prefix><Trait>`` exactly as the env
registry resolves ``HTTP<Trait>`` here, Storage.scala:263-312).

Configuration (env registry, data/storage/__init__.py):

    PIO_STORAGE_SOURCES_GATEWAY_TYPE=http
    PIO_STORAGE_SOURCES_GATEWAY_URL=http://storage-host:7077
    PIO_STORAGE_SOURCES_GATEWAY_SECRET=...            # optional
    PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE=GATEWAY  # etc.

Connections are pooled per thread (HTTP/1.1 keep-alive). READS retry
with bounded, jittered exponential backoff (first retry immediate — the
dropped-keepalive case — then ``_BACKOFF_BASE_S * 2^k`` with full
jitter, capped): a gateway restart mid-continuous-round (or
mid-promotion) rides through the restart window instead of aborting the
round. NON-IDEMPOTENT writes keep fail-fast semantics — they re-send
only when the request provably never reached the gateway (a send
failure on a reused keep-alive connection), because replaying an insert
that may have committed would duplicate it. Retry outcomes are counted
in ``pio_storage_client_retries_total{outcome}`` (``retried`` per
attempt, ``recovered`` when a retried call succeeds, ``exhausted`` when
retries run out).
"""

from __future__ import annotations

import datetime as _dt
import http.client
import json
import random
import socket
import threading
import time
import urllib.parse
from typing import Any, Dict, Iterator, List, Optional, Sequence

from predictionio_tpu.utils import metrics as _metrics

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import base, wire
from predictionio_tpu.data.storage.base import (
    UNSET,
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EngineManifest,
    EvaluationInstance,
    Model,
    OptFilter,
    PartialBatchError,
    StorageError,
    StorageSaturatedError,
)

PREFIX = "HTTP"

# reads may retry on any transport failure; everything else only when the
# request provably never reached the gateway (see StorageClient.call)
_IDEMPOTENT_METHODS = frozenset(
    {
        "get",
        "get_all",
        "get_by_name",
        "get_by_app_id",
        "get_latest_completed",
        "get_completed",
        "find",
        "aggregate_properties",
        "aggregate_properties_of_entity",
        "find_columns_native",
        "scan_columns",
        "scan_columns_delta",
        "store_fingerprint",
    }
)

# read-retry policy: attempts beyond the first (props RETRIES overrides),
# exponential base and cap for the jittered backoff between them. The
# FIRST retry is immediate — the common case is a dropped idle
# keep-alive connection, where waiting buys nothing.
_READ_RETRIES = 4
_BACKOFF_BASE_S = 0.05
_BACKOFF_CAP_S = 2.0


def _retries_counter() -> "_metrics.Counter":
    return _metrics.get_registry().counter(
        "pio_storage_client_retries_total",
        "Storage-gateway client retries by outcome (retried = one "
        "re-attempt; recovered = a retried call ultimately succeeded; "
        "exhausted = retries ran out and the call failed)",
        labels=("outcome",),
    )


class StorageClient(base.DAOCacheMixin):
    """Connection pool + RPC transport for one gateway URL."""

    def __init__(self, config=None):
        self.config = config
        props = getattr(config, "properties", None) or {}
        url = props.get("URL") or props.get("HOSTS") or "http://localhost:7077"
        if "://" not in url:
            url = f"http://{url}"
        parsed = urllib.parse.urlsplit(url)
        self.host = parsed.hostname or "localhost"
        self.port = parsed.port or 7077
        self.secret = props.get("SECRET", "")
        # per-request deadline, propagated as the socket timeout on
        # every connection: a WEDGED gateway node (accepting but never
        # answering) fails fast into the retry / circuit-breaker path
        # instead of hanging a scan until the 600 s unit-wait backstop.
        # Source precedence: source property, then the process-wide
        # PIO_STORAGE_CLIENT_TIMEOUT_S, then the reference's 60 s
        # (LEvents.scala:39).
        import os as _os

        timeout = float(
            props.get("TIMEOUT_S")
            or _os.environ.get("PIO_STORAGE_CLIENT_TIMEOUT_S")
            or "60"
        )
        self._timeout = timeout
        self._read_retries = int(props.get("RETRIES", _READ_RETRIES))
        self._backoff_cap_s = float(
            props.get("BACKOFF_CAP_S", _BACKOFF_CAP_S)
        )
        self._local = threading.local()
        self._init_dao_cache()

    # --- transport ---

    def _conn(self) -> "tuple[http.client.HTTPConnection, bool]":
        """Returns (connection, is_reused_keepalive)."""
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self._timeout
            )
            # TCP_NODELAY: RPC request/response pairs are small JSON
            # writes on a persistent connection — Nagle + delayed ACK
            # would stall each by tens of ms (the server side of every
            # REST frontend already disables it, api/http.py). Connect
            # errors are NOT raised here: call() owns transport failures
            # (retry-once + StorageError), and request() re-connects.
            try:
                conn.connect()
                conn.sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
            except OSError:
                pass
            self._local.conn = conn
            return conn, False
        return conn, True

    def _drop_conn(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            finally:
                self._local.conn = None

    def call(self, dao: str, method: str, args: Dict[str, Any]) -> Any:
        # the secret travels in the body, not the URL — request lines land
        # in access logs and proxies, bodies don't
        payload: Dict[str, Any] = {"dao": dao, "method": method, "args": args}
        if self.secret:
            payload["secret"] = self.secret
        body = json.dumps(payload)
        headers = {"Content-Type": "application/json"}
        # propagate the ambient trace (ingest http span, training round)
        # so the gateway's rpc span — and any group-commit flush it
        # causes over there — chains under this caller's span
        from predictionio_tpu.utils import tracing as _tracing

        trace = _tracing.current()
        if trace is not None:
            headers[_tracing.TRACE_HEADER] = trace.trace_id
            headers[_tracing.PARENT_HEADER] = trace.span_id
        idempotent = method in _IDEMPOTENT_METHODS
        # reads retry through a restart window with jittered exponential
        # backoff; non-idempotent calls keep the single safe reconnect
        # (send provably never reached the gateway)
        max_attempts = (self._read_retries + 1) if idempotent else 2
        last: Optional[Exception] = None
        retried = False
        for attempt in range(max_attempts):
            conn, reused = self._conn()
            sent = False
            try:
                conn.request("POST", "/rpc", body, headers)
                sent = True
                resp = conn.getresponse()
                data = resp.read()
            except (OSError, http.client.HTTPException) as e:
                self._drop_conn()
                last = e
                # Retry rules: a send failure on a reused keep-alive means
                # the gateway closed the idle connection and never saw the
                # request — always safe. A failure after the request went
                # out may have committed server-side, so only idempotent
                # reads retry (re-sending an insert could duplicate it).
                if idempotent:
                    may_retry = attempt < max_attempts - 1
                else:
                    may_retry = attempt == 0 and (not sent and reused)
                if not may_retry:
                    # "exhausted" means retries actually ran out — a
                    # fail-fast write that never retried must not
                    # inflate the retry-exhaustion signal operators
                    # alert on
                    if retried:
                        _retries_counter().labels(outcome="exhausted").inc()
                    break
                retried = True
                _retries_counter().labels(outcome="retried").inc()
                if idempotent and attempt > 0:
                    # first retry immediate (dropped idle keep-alive);
                    # later ones back off with full jitter so a fleet of
                    # clients doesn't stampede a restarting gateway
                    delay = min(
                        self._backoff_cap_s,
                        _BACKOFF_BASE_S * (2 ** (attempt - 1)),
                    )
                    time.sleep(delay * random.random())
                continue
            try:
                out = json.loads(data.decode("utf-8"))
            except ValueError as e:
                raise StorageError(
                    f"gateway returned non-JSON ({resp.status}): {data[:200]!r}"
                ) from e
            if retried:
                _retries_counter().labels(outcome="recovered").inc()
            if resp.status == 200:
                return out.get("result")
            if out.get("type") == "PartialBatchError":
                # reconstruct the typed error so the event server's
                # per-event retry contract survives the gateway hop
                retry_s = out.get("retry_after_s")
                raise PartialBatchError(
                    str(out.get("error")),
                    event_ids=out.get("event_ids") or [],
                    failed_ids=out.get("failed_ids") or [],
                    retry_after_s=None if retry_s is None else float(retry_s),
                )
            if out.get("type") == "StorageSaturatedError":
                # typed backpressure survives the hop: an event server
                # fronted by this gateway answers 503 + Retry-After
                raise StorageSaturatedError(
                    str(out.get("error")),
                    retry_after_s=float(out.get("retry_after_s") or 1.0),
                )
            raise StorageError(
                f"gateway {dao}.{method} failed ({resp.status}): "
                f"{out.get('error')}"
            )
        raise StorageError(
            f"storage gateway at {self.host}:{self.port} unreachable: {last}"
        ) from last

    def close(self) -> None:
        self._drop_conn()


class _RemoteDAO:
    DAO = ""

    def __init__(self, client: StorageClient, config=None, namespace: str = ""):
        self._client = client
        self.namespace = namespace

    def _call(self, method: str, **args) -> Any:
        return self._client.call(self.DAO, method, args)


class HTTPLEvents(_RemoteDAO, base.LEvents):
    DAO = "levents"

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        return self._call("init", app_id=app_id, channel_id=channel_id)

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        return self._call("remove", app_id=app_id, channel_id=channel_id)

    def close(self) -> None:
        self._client.close()

    def insert(self, event: Event, app_id: int, channel_id: Optional[int] = None) -> str:
        return self._call(
            "insert",
            event=wire.event_to_wire(event),
            app_id=app_id,
            channel_id=channel_id,
        )

    def write(self, events, app_id: int, channel_id: Optional[int] = None) -> List[str]:
        # one round trip for the whole batch (import path), not one per event
        return self._call(
            "write",
            events=[wire.event_to_wire(e) for e in events],
            app_id=app_id,
            channel_id=channel_id,
        )

    def insert_batch(
        self, events, app_id: int, channel_id: Optional[int] = None
    ) -> List[str]:
        # one round trip; the GATEWAY's backend provides the per-shard
        # atomicity (its own insert_batch), so the group-commit contract
        # holds end to end
        return self._call(
            "insert_batch",
            events=[wire.event_to_wire(e) for e in events],
            app_id=app_id,
            channel_id=channel_id,
        )

    def get(
        self, event_id: str, app_id: int, channel_id: Optional[int] = None
    ) -> Optional[Event]:
        out = self._call(
            "get", event_id=event_id, app_id=app_id, channel_id=channel_id
        )
        return None if out is None else wire.event_from_wire(out)

    def delete(
        self, event_id: str, app_id: int, channel_id: Optional[int] = None
    ) -> bool:
        return self._call(
            "delete", event_id=event_id, app_id=app_id, channel_id=channel_id
        )

    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: OptFilter = UNSET,
        target_entity_id: OptFilter = UNSET,
        limit: Optional[int] = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        # all 9 filter dimensions are pushed down to the gateway, which
        # runs them inside the owning backend (the reference pushes scan
        # filters into HBase the same way, HBEventsUtil.createScan)
        out = self._call(
            "find",
            app_id=app_id,
            channel_id=channel_id,
            start_time=wire.opt_dt_to_wire(start_time),
            until_time=wire.opt_dt_to_wire(until_time),
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=list(event_names) if event_names is not None else None,
            target_entity_type=(
                wire.UNSET_WIRE if target_entity_type is UNSET else target_entity_type
            ),
            target_entity_id=(
                wire.UNSET_WIRE if target_entity_id is UNSET else target_entity_id
            ),
            limit=limit,
            reversed=reversed,
        )
        return iter([wire.event_from_wire(e) for e in out])

    def aggregate_properties(
        self,
        app_id: int,
        entity_type: str,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        required: Optional[Sequence[str]] = None,
    ) -> Dict[str, "PropertyMap"]:
        # pushed down: the gateway folds $set/$unset/$delete next to the
        # store and ships one PropertyMap per entity — one round trip,
        # bytes proportional to entities, not history length (reference
        # folds at the store too, LEventAggregator.scala:39). Falls back
        # to the trait's find()+fold against gateways predating the RPC.
        try:
            out = self._call(
                "aggregate_properties",
                app_id=app_id,
                entity_type=entity_type,
                channel_id=channel_id,
                start_time=wire.opt_dt_to_wire(start_time),
                until_time=wire.opt_dt_to_wire(until_time),
                required=list(required) if required is not None else None,
            )
        except StorageError as e:
            if "unknown levents method" not in str(e):
                raise
            return super().aggregate_properties(
                app_id, entity_type, channel_id=channel_id,
                start_time=start_time, until_time=until_time,
                required=required,
            )
        return {
            k: wire.property_map_from_wire(v) for k, v in out.items()
        }

    def aggregate_properties_of_entity(
        self,
        app_id: int,
        entity_type: str,
        entity_id: str,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
    ) -> Optional["PropertyMap"]:
        try:
            out = self._call(
                "aggregate_properties_of_entity",
                app_id=app_id,
                entity_type=entity_type,
                entity_id=entity_id,
                channel_id=channel_id,
                start_time=wire.opt_dt_to_wire(start_time),
                until_time=wire.opt_dt_to_wire(until_time),
            )
        except StorageError as e:
            if "unknown levents method" not in str(e):
                raise
            return super().aggregate_properties_of_entity(
                app_id, entity_type, entity_id, channel_id=channel_id,
                start_time=start_time, until_time=until_time,
            )
        return wire.property_map_from_wire(out)

    # --- columnar path: packed columns over the wire, one round trip ---

    def insert_columns(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        event: str,
        entity_type: str,
        target_entity_type: str,
        entity_ids,
        target_ids,
        values,
        value_property: str = "rating",
        event_time: Optional[_dt.datetime] = None,
        event_times_ms=None,
    ) -> int:
        """Bulk import through the gateway: the id columns factorize
        CLIENT-side, so the wire carries each distinct id string once
        plus packed int32 codes — not one JSON event per row. Falls back
        to the batched row write against gateways predating the RPC."""
        import numpy as np

        from predictionio_tpu.data.storage import columnar as col

        e_names, e_codes = col.encode_strings(entity_ids)
        g_names, g_codes = col.encode_strings(target_ids)
        # per-row timestamps use a VERSIONED method name: a gateway
        # predating the field would otherwise accept "insert_columns",
        # ignore the unknown argument, and silently stamp every row with
        # its own clock — corrupting every time-windowed scan. An old
        # gateway rejects the v2 name and the client falls back to the
        # batched row write, which preserves per-event times.
        method = (
            "insert_columns" if event_times_ms is None
            else "insert_columns_v2"
        )
        try:
            return self._call(
                method,
                app_id=app_id,
                channel_id=channel_id,
                event=event,
                entity_type=entity_type,
                target_entity_type=target_entity_type,
                entity_names=[str(n) for n in e_names],
                entity_codes=col.array_to_b64(e_codes),
                target_names=[str(n) for n in g_names],
                target_codes=col.array_to_b64(g_codes),
                values=col.array_to_b64(np.asarray(values, np.float32)),
                value_property=value_property,
                event_time=wire.opt_dt_to_wire(event_time),
                event_times_ms=(
                    None
                    if event_times_ms is None
                    else col.array_to_b64(
                        np.asarray(event_times_ms, np.int64)
                    )
                ),
            )
        except StorageError as e:
            if "unknown levents method" not in str(e):
                raise
            return super().insert_columns(
                app_id, channel_id, event=event, entity_type=entity_type,
                target_entity_type=target_entity_type,
                entity_ids=entity_ids, target_ids=target_ids,
                values=values, value_property=value_property,
                event_time=event_time, event_times_ms=event_times_ms,
            )

    def insert_columns_encoded(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        event: str,
        entity_type: str,
        target_entity_type: str,
        entity_names,
        entity_codes,
        target_names,
        target_codes,
        values,
        value_property: str = "rating",
        event_time: Optional[_dt.datetime] = None,
        event_times_ms=None,
    ) -> int:
        """Pre-factorized columns pass straight onto the gateway wire —
        which already carries (distinct names + packed int32 codes) — so
        an encoded caller (the parquet bulk importer) never expands 20M
        id strings just for the client to re-factorize them (the base
        fallback's behavior)."""
        import numpy as np

        from predictionio_tpu.data.storage import columnar as col

        method = (
            "insert_columns" if event_times_ms is None
            else "insert_columns_v2"
        )
        try:
            return self._call(
                method,
                app_id=app_id,
                channel_id=channel_id,
                event=event,
                entity_type=entity_type,
                target_entity_type=target_entity_type,
                entity_names=[str(n) for n in entity_names],
                entity_codes=col.array_to_b64(
                    np.asarray(entity_codes, np.int32)
                ),
                target_names=[str(n) for n in target_names],
                target_codes=col.array_to_b64(
                    np.asarray(target_codes, np.int32)
                ),
                values=col.array_to_b64(np.asarray(values, np.float32)),
                value_property=value_property,
                event_time=wire.opt_dt_to_wire(event_time),
                event_times_ms=(
                    None
                    if event_times_ms is None
                    else col.array_to_b64(
                        np.asarray(event_times_ms, np.int64)
                    )
                ),
            )
        except StorageError as e:
            if "unknown levents method" not in str(e):
                raise
            # old gateway: go STRAIGHT to the batched row write — the
            # base insert_columns_encoded fallback would route through
            # self.insert_columns and re-attempt the very RPC that just
            # failed (a wasted 20M-id expand + doomed round trip per
            # row group)
            e_names = np.asarray(entity_names, object)
            g_names = np.asarray(target_names, object)
            return base.LEvents.insert_columns(
                self, app_id, channel_id, event=event,
                entity_type=entity_type,
                target_entity_type=target_entity_type,
                entity_ids=e_names[np.asarray(entity_codes, np.int64)],
                target_ids=g_names[np.asarray(target_codes, np.int64)],
                values=values, value_property=value_property,
                event_time=event_time, event_times_ms=event_times_ms,
            )

    def find_columns_native(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        value_spec=None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        target_entity_type: OptFilter = UNSET,
        event_names: Optional[Sequence[str]] = None,
    ):
        """Columnar scan through the gateway: the scan runs inside the
        owning backend (binary pages on sqlite) and the wire ships packed
        columns + small name dictionaries — never per-event JSON. Falls
        back to find()+columnarize against gateways predating the RPC."""
        from predictionio_tpu.data.storage import columnar as col
        from predictionio_tpu.data.storage.columnar import ValueSpec

        try:
            out = self._call(
                "find_columns_native",
                app_id=app_id,
                channel_id=channel_id,
                value_spec=col.spec_to_wire(value_spec or ValueSpec()),
                start_time=wire.opt_dt_to_wire(start_time),
                until_time=wire.opt_dt_to_wire(until_time),
                entity_type=entity_type,
                target_entity_type=(
                    wire.UNSET_WIRE
                    if target_entity_type is UNSET
                    else target_entity_type
                ),
                event_names=(
                    list(event_names) if event_names is not None else None
                ),
            )
        except StorageError as e:
            if "unknown levents method" not in str(e):
                raise
            return super().find_columns_native(
                app_id, channel_id, value_spec=value_spec,
                start_time=start_time, until_time=until_time,
                entity_type=entity_type,
                target_entity_type=target_entity_type,
                event_names=event_names,
            )
        return None if out is None else col.columnar_from_wire(out)

    # --- chunked/delta scan over the wire (cluster tier + remote
    # delta training): the gateway materializes its backend's stream
    # into one packed payload carrying the opaque cursor/fingerprint ---

    @staticmethod
    def _scan_args(
        value_spec, start_time, until_time, entity_type,
        target_entity_type, event_names, batch_rows,
    ) -> dict:
        from predictionio_tpu.data.storage import columnar as col
        from predictionio_tpu.data.storage.columnar import ValueSpec

        return {
            "value_spec": col.spec_to_wire(value_spec or ValueSpec()),
            "start_time": wire.opt_dt_to_wire(start_time),
            "until_time": wire.opt_dt_to_wire(until_time),
            "entity_type": entity_type,
            "target_entity_type": (
                wire.UNSET_WIRE
                if target_entity_type is UNSET
                else target_entity_type
            ),
            "event_names": (
                list(event_names) if event_names is not None else None
            ),
            "batch_rows": batch_rows,
        }

    @staticmethod
    def _stream_from_scan(out) -> "ColumnarStream":
        """One-batch ColumnarStream over a scan_columns payload, with
        the producing node's cursor and pre-scan fingerprint attached
        verbatim (tagged codec round-trips them exactly — the node
        validates its own cursor by equality on the next delta)."""
        import numpy as np

        from predictionio_tpu.data.storage import columnar as col
        from predictionio_tpu.data.storage.columnar import ColumnarStream

        names = np.empty(len(out["names"]), object)
        names[:] = out["names"]
        e_codes = col.array_from_b64(out["e_codes"], np.int64)
        t_codes = col.array_from_b64(out["t_codes"], np.int64)
        values = col.array_from_b64(out["values"], np.float32)
        batches = [(e_codes, t_codes, values)] if len(values) else []
        cursor = wire.opaque_from_wire(out.get("cursor"))
        return ColumnarStream(
            iter(batches),
            lambda: names,
            fingerprint=wire.opaque_from_wire(out.get("fingerprint")),
            cursor_fn=lambda: cursor,
        )

    def stream_columns_native(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        value_spec=None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        target_entity_type: OptFilter = UNSET,
        event_names: Optional[Sequence[str]] = None,
        batch_rows: int = 1_048_576,
    ):
        try:
            out = self._call(
                "scan_columns",
                app_id=app_id,
                channel_id=channel_id,
                **self._scan_args(
                    value_spec, start_time, until_time, entity_type,
                    target_entity_type, event_names, batch_rows,
                ),
            )
        except StorageError as e:
            if "unknown levents method" not in str(e):
                raise
            return None  # old gateway: find_columns_native fallback
        if out is None or out.get("invalid"):
            return None
        return self._stream_from_scan(out)

    def stream_columns_delta(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        cursor: tuple,
        value_spec=None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        target_entity_type: OptFilter = UNSET,
        event_names: Optional[Sequence[str]] = None,
        batch_rows: int = 1_048_576,
    ):
        try:
            out = self._call(
                "scan_columns_delta",
                app_id=app_id,
                channel_id=channel_id,
                cursor=wire.opaque_to_wire(cursor),
                **self._scan_args(
                    value_spec, start_time, until_time, entity_type,
                    target_entity_type, event_names, batch_rows,
                ),
            )
        except StorageError as e:
            if "unknown levents method" not in str(e):
                raise
            return None  # old gateway: full-repack fallback
        if out is None or out.get("invalid"):
            return None
        return self._stream_from_scan(out)

    def store_fingerprint(
        self, app_id: int, channel_id: Optional[int] = None
    ) -> Optional[tuple]:
        try:
            out = self._call(
                "store_fingerprint", app_id=app_id, channel_id=channel_id
            )
        except StorageError as e:
            if "unknown levents method" not in str(e):
                raise
            return None  # old gateway: caching disabled
        return wire.opaque_from_wire(out)


class HTTPApps(_RemoteDAO, base.Apps):
    DAO = "apps"

    def insert(self, app: App) -> Optional[int]:
        return self._call("insert", record=wire.record_to_wire(app))

    def get(self, app_id: int) -> Optional[App]:
        return wire.record_from_wire("app", self._call("get", app_id=app_id))

    def get_by_name(self, name: str) -> Optional[App]:
        return wire.record_from_wire(
            "app", self._call("get_by_name", name=name)
        )

    def get_all(self) -> List[App]:
        return [
            wire.record_from_wire("app", x) for x in self._call("get_all")
        ]

    def update(self, app: App) -> bool:
        return self._call("update", record=wire.record_to_wire(app))

    def delete(self, app_id: int) -> bool:
        return self._call("delete", app_id=app_id)


class HTTPAccessKeys(_RemoteDAO, base.AccessKeys):
    DAO = "access_keys"

    def insert(self, access_key: AccessKey) -> Optional[str]:
        return self._call("insert", record=wire.record_to_wire(access_key))

    def get(self, key: str) -> Optional[AccessKey]:
        return wire.record_from_wire(
            "access_key", self._call("get", key=key)
        )

    def get_all(self) -> List[AccessKey]:
        return [
            wire.record_from_wire("access_key", x)
            for x in self._call("get_all")
        ]

    def get_by_app_id(self, app_id: int) -> List[AccessKey]:
        return [
            wire.record_from_wire("access_key", x)
            for x in self._call("get_by_app_id", app_id=app_id)
        ]

    def update(self, access_key: AccessKey) -> bool:
        return self._call("update", record=wire.record_to_wire(access_key))

    def delete(self, key: str) -> bool:
        return self._call("delete", key=key)


class HTTPChannels(_RemoteDAO, base.Channels):
    DAO = "channels"

    def insert(self, channel: Channel) -> Optional[int]:
        return self._call("insert", record=wire.record_to_wire(channel))

    def get(self, channel_id: int) -> Optional[Channel]:
        return wire.record_from_wire(
            "channel", self._call("get", channel_id=channel_id)
        )

    def get_by_app_id(self, app_id: int) -> List[Channel]:
        return [
            wire.record_from_wire("channel", x)
            for x in self._call("get_by_app_id", app_id=app_id)
        ]

    def delete(self, channel_id: int) -> bool:
        return self._call("delete", channel_id=channel_id)


class HTTPEngineManifests(_RemoteDAO, base.EngineManifests):
    DAO = "engine_manifests"

    def insert(self, manifest: EngineManifest) -> None:
        return self._call("insert", record=wire.record_to_wire(manifest))

    def get(self, id: str, version: str) -> Optional[EngineManifest]:
        return wire.record_from_wire(
            "engine_manifest", self._call("get", id=id, version=version)
        )

    def get_all(self) -> List[EngineManifest]:
        return [
            wire.record_from_wire("engine_manifest", x)
            for x in self._call("get_all")
        ]

    def update(self, manifest: EngineManifest, upsert: bool = False) -> None:
        return self._call(
            "update", record=wire.record_to_wire(manifest), upsert=upsert
        )

    def delete(self, id: str, version: str) -> None:
        return self._call("delete", id=id, version=version)


class HTTPEngineInstances(_RemoteDAO, base.EngineInstances):
    DAO = "engine_instances"

    def insert(self, instance: EngineInstance) -> str:
        return self._call("insert", record=wire.record_to_wire(instance))

    def get(self, id: str) -> Optional[EngineInstance]:
        return wire.record_from_wire(
            "engine_instance", self._call("get", id=id)
        )

    def get_all(self) -> List[EngineInstance]:
        return [
            wire.record_from_wire("engine_instance", x)
            for x in self._call("get_all")
        ]

    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> Optional[EngineInstance]:
        return wire.record_from_wire(
            "engine_instance",
            self._call(
                "get_latest_completed",
                engine_id=engine_id,
                engine_version=engine_version,
                engine_variant=engine_variant,
            ),
        )

    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> List[EngineInstance]:
        return [
            wire.record_from_wire("engine_instance", x)
            for x in self._call(
                "get_completed",
                engine_id=engine_id,
                engine_version=engine_version,
                engine_variant=engine_variant,
            )
        ]

    def update(self, instance: EngineInstance) -> None:
        return self._call("update", record=wire.record_to_wire(instance))

    def delete(self, id: str) -> None:
        return self._call("delete", id=id)


class HTTPEvaluationInstances(_RemoteDAO, base.EvaluationInstances):
    DAO = "evaluation_instances"

    def insert(self, instance: EvaluationInstance) -> str:
        return self._call("insert", record=wire.record_to_wire(instance))

    def get(self, id: str) -> Optional[EvaluationInstance]:
        return wire.record_from_wire(
            "evaluation_instance", self._call("get", id=id)
        )

    def get_all(self) -> List[EvaluationInstance]:
        return [
            wire.record_from_wire("evaluation_instance", x)
            for x in self._call("get_all")
        ]

    def get_completed(self) -> List[EvaluationInstance]:
        return [
            wire.record_from_wire("evaluation_instance", x)
            for x in self._call("get_completed")
        ]

    def update(self, instance: EvaluationInstance) -> None:
        return self._call("update", record=wire.record_to_wire(instance))

    def delete(self, id: str) -> None:
        return self._call("delete", id=id)


class HTTPModels(_RemoteDAO, base.Models):
    DAO = "models"

    def insert(self, model: Model) -> None:
        return self._call("insert", record=wire.record_to_wire(model))

    def get(self, id: str) -> Optional[Model]:
        return wire.record_from_wire("model", self._call("get", id=id))

    def delete(self, id: str) -> None:
        return self._call("delete", id=id)
