"""JSON wire format for storage records — shared by the storage gateway
server (api/storage_gateway.py) and the ``http`` client backend
(data/storage/http.py).

The reference's client-server backends serialize DAO records onto the wire
too (HBase cell layout hbase/HBEventsUtil.scala:145-207, Elasticsearch
document JSON); here the wire is explicit JSON so any HTTP client can speak
it. Events reuse the API JSON format (event.py to_json/from_json) with
creationTime preserved verbatim; metadata dataclasses serialize field-wise
with ISO8601 datetimes; model blobs travel base64.
"""

from __future__ import annotations

import base64
import dataclasses
import datetime as _dt
from typing import Any, Dict, Optional

from predictionio_tpu.data.event import Event, parse_iso8601
from predictionio_tpu.data.storage.base import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EngineManifest,
    EvaluationInstance,
    Model,
)


# find()'s UNSET sentinel on the wire (absence-of-filter vs filter-for-None)
UNSET_WIRE = "\x00unset"


def event_to_wire(e: Event) -> Dict[str, Any]:
    out = e.to_json()
    # the API JSON format truncates times to milliseconds; the wire must
    # round-trip exactly or find()'s time-range semantics diverge from the
    # embedded backends at sub-ms boundaries
    out["eventTime"] = _dt_to_wire(e.event_time)
    out["creationTime"] = _dt_to_wire(e.creation_time)
    return out


def event_from_wire(obj: Dict[str, Any]) -> Event:
    # stored events were validated on ingestion; re-validating here would
    # reject reserved/builtin events ($set on pio_pr etc.) on read-back
    e = Event.from_json(obj, validate=False)
    raw_created = obj.get("creationTime")
    if raw_created:
        e = dataclasses.replace(e, creation_time=parse_iso8601(raw_created))
    return e


def _dt_to_wire(d: _dt.datetime) -> str:
    # full microsecond precision (datetime.isoformat), NOT the API format's
    # millisecond rendering — storage round-trips must be lossless
    if d.tzinfo is None:
        d = d.replace(tzinfo=_dt.timezone.utc)
    return d.isoformat()


def _dt_from_wire(s: str) -> _dt.datetime:
    return parse_iso8601(s)


_DATACLASS_TYPES = {
    "app": App,
    "access_key": AccessKey,
    "channel": Channel,
    "engine_manifest": EngineManifest,
    "engine_instance": EngineInstance,
    "evaluation_instance": EvaluationInstance,
}


def record_to_wire(rec: Any) -> Dict[str, Any]:
    """Serialize a metadata dataclass field-wise (datetimes -> ISO8601)."""
    if isinstance(rec, Model):
        return {
            "id": rec.id,
            "models": base64.b64encode(rec.models).decode("ascii"),
        }
    out = {}
    for f in dataclasses.fields(rec):
        v = getattr(rec, f.name)
        if isinstance(v, _dt.datetime):
            v = _dt_to_wire(v)
        elif isinstance(v, tuple):
            v = list(v)
        out[f.name] = v
    return out


def record_from_wire(kind: str, obj: Optional[Dict[str, Any]]) -> Any:
    if obj is None:
        return None
    if kind == "model":
        return Model(id=obj["id"], models=base64.b64decode(obj["models"]))
    cls = _DATACLASS_TYPES[kind]
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name not in obj:
            continue
        v = obj[f.name]
        # the only datetime fields across the metadata records
        if f.name in ("start_time", "end_time") and isinstance(v, str):
            v = _dt_from_wire(v)
        kwargs[f.name] = v
    return cls(**kwargs)


def property_map_to_wire(pm) -> Dict[str, Any]:
    """Folded PropertyMap for the gateway's aggregate pushdown — the wire
    carries the already-aggregated result, not the raw $set/$unset/$delete
    history (reference folds at the store, LEventAggregator.scala:39)."""
    return {
        "fields": dict(pm.fields),
        "firstUpdated": _dt_to_wire(pm.first_updated),
        "lastUpdated": _dt_to_wire(pm.last_updated),
    }


def property_map_from_wire(obj: Optional[Dict[str, Any]]):
    from predictionio_tpu.data.event import PropertyMap

    if obj is None:
        return None
    return PropertyMap(
        obj["fields"],
        first_updated=_dt_from_wire(obj["firstUpdated"]),
        last_updated=_dt_from_wire(obj["lastUpdated"]),
    )


def opt_dt_to_wire(d: Optional[_dt.datetime]) -> Optional[str]:
    return None if d is None else _dt_to_wire(d)


def opt_dt_from_wire(s: Optional[str]) -> Optional[_dt.datetime]:
    return None if s is None else _dt_from_wire(s)


# --- opaque-value codec (delta cursors, store fingerprints) ---
#
# Delta cursors and fingerprints are backend-opaque tuples (sqlite nests
# per-store tuples; memory embeds a datetime). They must round-trip the
# JSON wire EXACTLY — the producing backend validates them by equality,
# so tuple-vs-list or a truncated datetime would silently force a full
# repack on every delta round. Tagged encoding keeps plain JSON scalars
# untouched and wraps only what JSON cannot represent.

_TUPLE_TAG = "__pio_tuple"
_DT_TAG = "__pio_dt"
_BYTES_TAG = "__pio_bytes"


def opaque_to_wire(v: Any) -> Any:
    """Recursively encode an opaque cursor/fingerprint value for JSON."""
    if isinstance(v, tuple):
        return {_TUPLE_TAG: [opaque_to_wire(x) for x in v]}
    if isinstance(v, list):
        return [opaque_to_wire(x) for x in v]
    if isinstance(v, _dt.datetime):
        return {_DT_TAG: _dt_to_wire(v)}
    if isinstance(v, bytes):
        return {_BYTES_TAG: base64.b64encode(v).decode("ascii")}
    if isinstance(v, dict):
        return {str(k): opaque_to_wire(x) for k, x in v.items()}
    return v


def opaque_from_wire(v: Any) -> Any:
    """Inverse of :func:`opaque_to_wire`."""
    if isinstance(v, dict):
        if _TUPLE_TAG in v and len(v) == 1:
            return tuple(opaque_from_wire(x) for x in v[_TUPLE_TAG])
        if _DT_TAG in v and len(v) == 1:
            return _dt_from_wire(v[_DT_TAG])
        if _BYTES_TAG in v and len(v) == 1:
            return base64.b64decode(v[_BYTES_TAG])
        return {k: opaque_from_wire(x) for k, x in v.items()}
    if isinstance(v, list):
        return [opaque_from_wire(x) for x in v]
    return v
