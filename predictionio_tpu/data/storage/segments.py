"""Compacted columnar segment tier: the event store's read-optimized
half.

The sharded-WAL sqlite row stores (``data/storage/sqlite.py``) are
write-optimized: group-commit transactions, per-shard WAL write slots.
Training scans over them still pay sqlite page decode per row — ~3.3M
events/s — while the reference's production path never decodes one
object per event (HBase region scans and day-partitioned JDBC scans
feed columnar partitions directly, HBPEvents.scala:84-90 /
JDBCPEvents.scala:51-129). This module adds the LSM-style answer: a
background compactor seals COLD prefixes of each row store into
immutable columnar **segment files** that scan at ``np.frombuffer``/
mmap rate, atomically registers them in a manifest inside the main
database, and advances a per-store rowid **watermark** that excludes
the sealed rows from every residual scan. The physical DELETE of the
sealed rows is deferred by a grace period, so a scan that snapshotted
the manifest just before a compaction commit still finds every row it
expects (scans never coordinate with the compactor).

Correctness contract (the acceptance oracle): a compacted store's
streaming scan feeds the counting-sort merge in ``ops/streaming.py`` a
wire BYTE-identical to a never-compacted store's. The design choices
that guarantee it:

- a compaction round seals a contiguous rowid PREFIX ``(watermark,
  hi]`` of one row store, and a segment keeps its rows in rowid order
  with per-row event/type/prop codes — scans replay exactly the
  per-entity event order the residual SQL scan would have produced
  (mixed event names included; rows are never regrouped);
- rows that cannot round-trip through the columnar form (tags, prId,
  ``$``-events, targetless events, multi-key or non-numeric property
  bags, non-canonical timestamp text) become bounded **holdouts**:
  they stay in the row store, named by rowid in the compaction state,
  and every residual predicate re-admits them;
- entity/target ids are dict-encoded into the SAME table-global code
  space the columnar page store uses, so segment batches merge with
  page batches and the row-store residual without re-encoding.

Crash safety: a segment file is written and fsync-renamed BEFORE the
manifest transaction that makes it (and the new watermark) visible —
a crash in between leaves an orphan file and an untouched row store
(no loss, no duplication; orphans are swept by later rounds). The
physical delete runs last and is idempotent, so a crash between
manifest commit and delete just re-runs the delete next round.

Everything here is **instance-scoped** — no module-level mutable
state (``tests/test_lint.py`` enforces this): the compactor daemon, its
per-app threads, and all caches hang off objects owned by a server or
CLI invocation, never the module.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

SEGMENT_MAGIC = b"PIOSEG1\n"

# every per-row column a segment stores, in file order. Codes index the
# footer's small dictionaries (event names, types, props); entities and
# targets are TABLE-GLOBAL dict codes (the page store's code space).
_COLUMNS = (
    ("rids", np.int64),  # source rowids (recovery + debugging)
    ("entities", np.int32),
    ("targets", np.int32),
    ("values", np.float32),
    ("times_ms", np.int64),
    ("ctimes_ms", np.int64),
    ("evcodes", np.uint16),
    ("propcodes", np.uint16),
    ("etcodes", np.uint16),
    ("tetcodes", np.uint16),
    # "ids" is appended with a per-file fixed width (S<w> bytes)
)

# a row whose id exceeds this many utf-8 bytes stays in the row store —
# one giant id must not inflate the whole fixed-width id column
MAX_ID_BYTES = 64


@dataclasses.dataclass
class SegmentColumns:
    """The columnar image of one sealed rowid range, in rowid order."""

    rids: np.ndarray
    ids: np.ndarray  # S<w> fixed-width utf-8 bytes
    entities: np.ndarray  # int32, table-global dict codes
    targets: np.ndarray  # int32, table-global dict codes
    values: np.ndarray  # float32
    times_ms: np.ndarray  # int64
    ctimes_ms: np.ndarray  # int64
    evcodes: np.ndarray  # uint16 -> event_names
    propcodes: np.ndarray  # uint16 -> props
    etcodes: np.ndarray  # uint16 -> entity_types
    tetcodes: np.ndarray  # uint16 -> target_entity_types
    event_names: List[str]
    props: List[str]
    entity_types: List[str]
    target_entity_types: List[str]

    @property
    def n(self) -> int:
        return len(self.values)

    def slice(self, lo: int, hi: int) -> "SegmentColumns":
        return dataclasses.replace(
            self,
            rids=self.rids[lo:hi],
            ids=self.ids[lo:hi],
            entities=self.entities[lo:hi],
            targets=self.targets[lo:hi],
            values=self.values[lo:hi],
            times_ms=self.times_ms[lo:hi],
            ctimes_ms=self.ctimes_ms[lo:hi],
            evcodes=self.evcodes[lo:hi],
            propcodes=self.propcodes[lo:hi],
            etcodes=self.etcodes[lo:hi],
            tetcodes=self.tetcodes[lo:hi],
        )


# --- file format ---
#
# [MAGIC][column payloads, back to back][footer JSON][uint64 footer len]
# [MAGIC]. The footer carries the column offset/dtype table, per-segment
# counts, min/max rowid + event time, the small dictionaries, and a
# crc32 checksum of the payload region — readers verify it once per
# open, then every scan is np.frombuffer over one mmap.


def write_segment_file(path: str, cols: SegmentColumns) -> dict:
    """Write one immutable segment: temp file + fsync + atomic rename.
    Returns the footer dict (the manifest row's source of truth)."""
    payloads: List[Tuple[str, bytes, str]] = []
    for name, dtype in _COLUMNS:
        arr = np.ascontiguousarray(getattr(cols, name), dtype)
        payloads.append((name, arr.tobytes(), np.dtype(dtype).str))
    ids = np.ascontiguousarray(cols.ids)
    payloads.append(("ids", ids.tobytes(), ids.dtype.str))

    columns = {}
    offset = len(SEGMENT_MAGIC)
    crc = 0
    for name, blob, dstr in payloads:
        columns[name] = {"offset": offset, "nbytes": len(blob), "dtype": dstr}
        offset += len(blob)
        crc = zlib.crc32(blob, crc)
    footer = {
        "version": 1,
        "n": int(cols.n),
        "min_rowid": int(cols.rids.min()) if cols.n else 0,
        "max_rowid": int(cols.rids.max()) if cols.n else 0,
        "min_ms": int(cols.times_ms.min()) if cols.n else 0,
        "max_ms": int(cols.times_ms.max()) if cols.n else 0,
        "checksum": int(crc),
        "columns": columns,
        "event_names": list(cols.event_names),
        "props": list(cols.props),
        "entity_types": list(cols.entity_types),
        "target_entity_types": list(cols.target_entity_types),
    }
    footer_blob = json.dumps(footer).encode("utf-8")
    tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
    with open(tmp, "wb") as f:
        f.write(SEGMENT_MAGIC)
        for _, blob, _ in payloads:
            f.write(blob)
        f.write(footer_blob)
        f.write(np.uint64(len(footer_blob)).tobytes())
        f.write(SEGMENT_MAGIC)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return footer


class SegmentReadError(Exception):
    pass


class SegmentData:
    """An open (mmap'd) segment. Arrays are zero-copy views over the
    mapped file — resident pages belong to the OS page cache, so a
    long-lived process holding many open segments costs evictable
    cache, not anonymous heap. The object is immutable and safe to
    share across scans."""

    def __init__(self, path: str, verify: bool = True):
        import mmap as _mmap

        self.path = path
        with open(path, "rb") as f:
            # zero-copy scans over the mapping; the checksum pass below
            # touches every page once (sequential fault-in)
            buf = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
        if (
            len(buf) < 2 * len(SEGMENT_MAGIC) + 8
            or buf[: len(SEGMENT_MAGIC)] != SEGMENT_MAGIC
            or buf[-len(SEGMENT_MAGIC) :] != SEGMENT_MAGIC
        ):
            raise SegmentReadError(f"{path}: not a segment file")
        tail = len(buf) - len(SEGMENT_MAGIC) - 8
        flen = int(np.frombuffer(buf[tail : tail + 8], np.uint64)[0])
        footer = json.loads(buf[tail - flen : tail].decode("utf-8"))
        self.footer = footer
        self.n = int(footer["n"])
        cols = footer["columns"]
        if verify:
            lo = min(c["offset"] for c in cols.values())
            hi = max(c["offset"] + c["nbytes"] for c in cols.values())
            # memoryview slice: no heap copy of the payload region
            if zlib.crc32(memoryview(buf)[lo:hi]) != footer["checksum"]:
                raise SegmentReadError(f"{path}: checksum mismatch")
        self._arrays: Dict[str, np.ndarray] = {}
        for name, meta in cols.items():
            self._arrays[name] = np.frombuffer(
                buf, np.dtype(meta["dtype"]),
                count=meta["nbytes"] // np.dtype(meta["dtype"]).itemsize,
                offset=meta["offset"],
            )
        self.event_names = footer["event_names"]
        self.props = footer["props"]
        self.entity_types = footer["entity_types"]
        self.target_entity_types = footer["target_entity_types"]
        # lazy sorted-id index (id_rows): built on the first by-id probe
        self._ids_order: Optional[np.ndarray] = None
        self._ids_sorted: Optional[np.ndarray] = None

    def column(self, name: str) -> np.ndarray:
        return self._arrays[name]

    def id_rows(self, needles) -> np.ndarray:
        """Row indices whose event id matches any of ``needles`` (bytes,
        each already length-checked against the column width — a longer
        needle would silently truncate into a false match). One lazy
        sort per open segment, then O(k log n) probes, so explicit-id
        scrubs and deletes never rescan the whole id column per call."""
        if self._ids_order is None:
            col = self.column("ids")
            self._ids_order = np.argsort(col, kind="stable")
            self._ids_sorted = col[self._ids_order]
        srt = self._ids_sorted
        if not len(srt):
            return np.empty(0, np.int64)
        arr = np.asarray(needles, dtype=srt.dtype)
        pos = np.clip(np.searchsorted(srt, arr), 0, len(srt) - 1)
        hits = srt[pos] == arr
        return self._ids_order[pos[hits]]

    # --- scan-time evaluation (mirrors the residual SQL semantics) ---

    def keep_mask(
        self,
        *,
        lo_ms: Optional[int] = None,
        hi_ms: Optional[int] = None,
        entity_type: Optional[str] = None,
        target_entity_type=None,
        target_entity_type_set: bool = False,
        event_names: Optional[Sequence[str]] = None,
        dead: Optional[np.ndarray] = None,
    ) -> Optional[np.ndarray]:
        """Row filter identical to the residual scan's WHERE clauses.
        Returns None when every row survives (the common cold-scan
        case), or a bool mask. ``target_entity_type_set`` True with
        value None matches NOTHING (segments only hold targetful
        events)."""
        if target_entity_type_set and target_entity_type is None:
            return np.zeros(self.n, bool)
        keep: Optional[np.ndarray] = None

        def conj(m):
            nonlocal keep
            keep = m if keep is None else (keep & m)

        if dead is not None:
            conj(dead == 0)
        if event_names is not None:
            allowed = np.fromiter(
                (nm in event_names for nm in self.event_names),
                bool, count=len(self.event_names),
            )
            if not allowed.any():
                return np.zeros(self.n, bool)
            if not allowed.all():
                conj(allowed[self.column("evcodes")])
        if entity_type is not None:
            ok = np.fromiter(
                (nm == entity_type for nm in self.entity_types),
                bool, count=len(self.entity_types),
            )
            if not ok.any():
                return np.zeros(self.n, bool)
            if not ok.all():
                conj(ok[self.column("etcodes")])
        if target_entity_type_set:
            ok = np.fromiter(
                (nm == target_entity_type for nm in self.target_entity_types),
                bool, count=len(self.target_entity_types),
            )
            if not ok.any():
                return np.zeros(self.n, bool)
            if not ok.all():
                conj(ok[self.column("tetcodes")])
        if lo_ms is not None and self.footer["min_ms"] < lo_ms:
            conj(self.column("times_ms") >= lo_ms)
        if hi_ms is not None and self.footer["max_ms"] >= hi_ms:
            conj(self.column("times_ms") < hi_ms)
        return keep

    def spec_values(self, spec) -> np.ndarray:
        """Per-row training values under a ``columnar.ValueSpec`` —
        exactly the residual SQL's CASE/COALESCE rule, vectorized:
        an event-name override wins, else the stored value when the
        row's property key is the spec's, else the default."""
        overrides = spec.overrides
        ov_vals = np.fromiter(
            (overrides.get(nm, 0.0) for nm in self.event_names),
            np.float32, count=len(self.event_names),
        )
        ov_has = np.fromiter(
            (nm in overrides for nm in self.event_names),
            bool, count=len(self.event_names),
        )
        prop_is = np.fromiter(
            (p == spec.prop for p in self.props),
            bool, count=len(self.props),
        )
        v = np.where(
            prop_is[self.column("propcodes")],
            self.column("values"),
            np.float32(spec.default),
        )
        if ov_has.any():
            v = np.where(
                ov_has[self.column("evcodes")],
                ov_vals[self.column("evcodes")],
                v,
            )
        return v.astype(np.float32, copy=False)

    def ids_str(self) -> np.ndarray:
        """Decoded event ids (object array of str)."""
        raw = self.column("ids")
        out = np.empty(self.n, object)
        for j, b in enumerate(raw):
            out[j] = b.decode("utf-8")
        return out


# --- row qualification ---


def _canonical_iso(text: Optional[str], ms: int, format_iso8601, from_ms) -> bool:
    """True when ``text`` is exactly the canonical UTC millisecond
    rendering of ``ms`` — the only case the int64 column round-trips
    losslessly (offset renderings and sub-ms text stay in rows)."""
    if not text:
        return False
    return format_iso8601(from_ms(ms)) == text


class RowQualifier:
    """Decides whether a row round-trips through the columnar form and
    accumulates the qualified columns (in input = rowid order).

    Rows are the named tuples of the sqlite row layout:
    ``(rowid, id, event, entity_type, entity_id, target_entity_type,
    target_entity_id, properties, event_time, event_time_ms, tags,
    pr_id, creation_time)``. A row qualifies when every field the
    segment cannot store is absent/trivial and every stored field
    round-trips exactly — see ``docs/PERF.md`` (storage tier) for the
    one documented exception: property values are kept as float32 (the
    precision the training wire uses either way).
    """

    def __init__(self):
        from predictionio_tpu.data.event import format_iso8601

        self._format_iso = format_iso8601
        self.rids: List[int] = []
        self.ids: List[bytes] = []
        self.entity_ids: List[str] = []
        self.target_ids: List[str] = []
        self.values: List[float] = []
        self.times_ms: List[int] = []
        self.ctimes_ms: List[int] = []
        self.evcodes: List[int] = []
        self.propcodes: List[int] = []
        self.etcodes: List[int] = []
        self.tetcodes: List[int] = []
        self._events: Dict[str, int] = {}
        self._props: Dict[str, int] = {}
        self._etypes: Dict[str, int] = {}
        self._tetypes: Dict[str, int] = {}

    @staticmethod
    def _code(table: Dict[str, int], name: str) -> Optional[int]:
        """Dict code, or None when the table is full — the codes column
        is uint16, and event names are arbitrary client input, so a
        high-cardinality prefix must overflow into holdouts, not crash
        (and permanently stall) every future compaction round."""
        c = table.get(name)
        if c is None:
            if len(table) > 0xFFFF:
                return None
            c = len(table)
            table[name] = c
        return c

    def _ms_dt(self, ms: int):
        import datetime as _dt

        return _dt.datetime.fromtimestamp(ms / 1000.0, _dt.timezone.utc)

    def offer(self, row) -> bool:
        """Fold one row in; False means it must stay in the row store
        (the caller records its rowid as a holdout)."""
        (
            rid, eid, event, etype, entity_id, tetype, target_id,
            props_json, etime_text, etime_ms, tags_json, pr_id, ctime_text,
        ) = row
        if (
            target_id is None
            or tetype is None
            or pr_id is not None
            or event.startswith("$")
            or (tags_json not in (None, "[]"))
        ):
            return False
        eid_b = (eid or "").encode("utf-8")
        if not eid_b or len(eid_b) > MAX_ID_BYTES:
            return False
        # timestamps must be exactly their canonical UTC ms rendering —
        # anything else (client-zone offsets) can't rebuild the TEXT
        if not _canonical_iso(
            etime_text, etime_ms, self._format_iso, self._ms_dt
        ):
            return False
        try:
            import datetime as _dt

            from predictionio_tpu.data.event import parse_iso8601

            ctime = parse_iso8601(ctime_text)
            if ctime.utcoffset() not in (None, _dt.timedelta(0)):
                return False
            ctime_ms = int(ctime.timestamp() * 1000)
            if self._format_iso(self._ms_dt(ctime_ms)) != ctime_text:
                return False
        except (ValueError, TypeError):
            return False
        prop, value = "", 0.0
        if props_json and props_json != "{}":
            try:
                bag = json.loads(props_json)
            except ValueError:
                return False
            if not isinstance(bag, dict) or len(bag) != 1:
                return False
            prop, value = next(iter(bag.items()))
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                return False
            value = float(value)
        codes = (
            self._code(self._events, event),
            self._code(self._props, prop),
            self._code(self._etypes, etype),
            self._code(self._tetypes, tetype),
        )
        if any(c is None for c in codes):
            return False  # a uint16 dictionary is full: holdout
        self.rids.append(rid)
        self.ids.append(eid_b)
        self.entity_ids.append(str(entity_id))
        self.target_ids.append(str(target_id))
        self.values.append(value)
        self.times_ms.append(int(etime_ms))
        self.ctimes_ms.append(ctime_ms)
        self.evcodes.append(codes[0])
        self.propcodes.append(codes[1])
        self.etcodes.append(codes[2])
        self.tetcodes.append(codes[3])
        return True

    @property
    def n(self) -> int:
        return len(self.rids)

    def finish(self, entity_codes: np.ndarray, target_codes: np.ndarray) -> SegmentColumns:
        """Assemble the columns; the caller supplies the table-global
        dict codes for ``entity_ids``/``target_ids`` (the dict lives in
        the sqlite main database)."""
        width = max((len(b) for b in self.ids), default=1)
        ids = np.array(self.ids, dtype=f"S{width}")
        return SegmentColumns(
            rids=np.asarray(self.rids, np.int64),
            ids=ids,
            entities=np.asarray(entity_codes, np.int32),
            targets=np.asarray(target_codes, np.int32),
            values=np.asarray(self.values, np.float32),
            times_ms=np.asarray(self.times_ms, np.int64),
            ctimes_ms=np.asarray(self.ctimes_ms, np.int64),
            evcodes=np.asarray(self.evcodes, np.uint16),
            propcodes=np.asarray(self.propcodes, np.uint16),
            etcodes=np.asarray(self.etcodes, np.uint16),
            tetcodes=np.asarray(self.tetcodes, np.uint16),
            event_names=list(self._events),
            props=list(self._props),
            entity_types=list(self._etypes),
            target_entity_types=list(self._tetypes),
        )


# --- the background compactor daemon ---


@dataclasses.dataclass
class CompactionPolicy:
    """Compaction triggers and safety knobs (docs/PERF.md)."""

    # an event is COLD once its event time is this far in the past
    cold_s: float = 300.0
    # don't bother sealing ranges smaller than this many qualified rows
    min_events: int = 4096
    # per-round row ceiling (bounds compactor memory to one range)
    max_rows: int = 4_194_304
    # rows per segment file (a range splits into sequential files)
    rows_per_segment: int = 4_194_304
    # sealed rows stay physically present (but watermark-excluded) this
    # long, so scans that snapshotted the manifest just before the
    # commit still find every row they expect
    grace_s: float = 600.0
    # non-columnar rows in a sealed range stay behind as holdouts; past
    # this many per store, the watermark stops advancing
    max_holdouts: int = 4096


class SegmentCompactor:
    """Background compaction daemon: one worker thread per app (the
    reference's per-region HBase compactions, without the HBase). Owned
    by the event server (``EventServerConfig.compact``) or a standalone
    ``pio compact`` run; everything is instance state."""

    # watchdog deadline for one compaction round (seal + manifest
    # commit); a round silent past this while mid-work degrades /readyz
    HEARTBEAT_DEADLINE_S = 300.0

    def __init__(
        self,
        storage,
        policy: Optional[CompactionPolicy] = None,
        interval_s: float = 60.0,
        apps: Optional[Sequence[int]] = None,
    ):
        from predictionio_tpu.utils import health as _health

        self.storage = storage
        self.policy = policy or CompactionPolicy()
        self.interval_s = max(1.0, float(interval_s))
        self._apps = list(apps) if apps is not None else None
        self._threads: Dict[int, threading.Thread] = {}
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._started = False
        # per-app worker threads share one heartbeat: any app's round
        # stalling is a process-level readiness signal
        self._hb = _health.heartbeat(
            "segment-compactor", deadline_s=self.HEARTBEAT_DEADLINE_S
        )

    @staticmethod
    def supported(storage) -> bool:
        """Duck-typed backend gate: only stores exposing ``compact_app``
        (the sqlite tier) can compact; memory/http backends no-op."""
        try:
            return hasattr(storage.get_l_events(), "compact_app")
        except Exception:
            return False

    def _app_ids(self) -> List[int]:
        if self._apps is not None:
            return list(self._apps)
        try:
            return [a.id for a in self.storage.get_meta_data_apps().get_all()]
        except Exception:
            logger.exception("compactor: app listing failed")
            return []

    def run_once(self, app_id: int, channel_id: Optional[int] = None) -> dict:
        """One synchronous compaction round for one app/channel."""
        le = self.storage.get_l_events()
        with self._hb.busy():
            return le.compact_app(app_id, channel_id, policy=self.policy)

    def compact_all_once(self) -> Dict[int, dict]:
        """One round over every app (and its channels) — the ``pio
        compact --once`` path."""
        out: Dict[int, dict] = {}
        channels = self.storage.get_meta_data_channels()
        for app_id in self._app_ids():
            result = self.run_once(app_id)
            for ch in channels.get_by_app_id(app_id):
                ch_res = self.run_once(app_id, ch.id)
                for k, v in ch_res.items():
                    if isinstance(v, (int, float)):
                        result[k] = result.get(k, 0) + v
            out[app_id] = result
        return out

    def _app_loop(self, app_id: int) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.run_once(app_id)
                for ch in (
                    self.storage.get_meta_data_channels().get_by_app_id(app_id)
                ):
                    self.run_once(app_id, ch.id)
            except Exception:
                # the daemon must outlive any one round's failure
                logger.exception("compaction round failed for app %d", app_id)

    def start(self) -> "SegmentCompactor":
        """Spawn per-app worker threads (and a refresher that picks up
        apps created later). Idempotent; no-op for backends without
        compaction support."""
        with self._lock:
            if self._started or not self.supported(self.storage):
                return self
            self._started = True
            self._refresh_threads()
            t = threading.Thread(
                target=self._refresher, daemon=True, name="segment-compactor"
            )
            t.start()
            self._refresher_thread = t
        return self

    def _refresh_threads(self) -> None:
        for app_id in self._app_ids():
            if app_id in self._threads:
                continue
            t = threading.Thread(
                target=self._app_loop, args=(app_id,), daemon=True,
                name=f"segment-compactor-app{app_id}",
            )
            t.start()
            self._threads[app_id] = t

    def _refresher(self) -> None:
        while not self._stop.wait(self.interval_s * 5):
            with self._lock:
                if self._stop.is_set():
                    return
                self._refresh_threads()

    def close(self) -> None:
        self._stop.set()


class CachedCompactionStatus:
    """Instance-scoped TTL cache over :func:`compaction_status`: the
    underlying stats cost COUNT(*) scans per app, and both surfaces
    that expose them (event-server status route, admin app listing)
    face pollers — neither may hand anonymous clients a repeated
    full-table-scan lever. One helper so TTL and recompute behavior
    can't drift between the two."""

    def __init__(self, storage, ttl_s: float = 5.0):
        self.storage = storage
        self.ttl_s = float(ttl_s)
        self._cached: Optional[Tuple[float, Dict[str, dict]]] = None

    def get(self) -> Dict[str, dict]:
        import time as _time

        now = _time.monotonic()
        cached = self._cached
        if cached is None or now - cached[0] >= self.ttl_s:
            self._cached = cached = (now, compaction_status(self.storage))
        return cached[1]


def compaction_status(storage) -> Dict[str, dict]:
    """Per-app compaction observability (event-server ``status.json``
    and the admin app listing): segment count, compacted-event count and
    fraction, last-compaction timestamp. Empty for backends without a
    segment tier."""
    out: Dict[str, dict] = {}
    try:
        le = storage.get_l_events()
    except Exception:
        return out
    stats = getattr(le, "compaction_stats", None)
    if stats is None:
        return out
    try:
        apps = storage.get_meta_data_apps().get_all()
    except Exception:
        return out
    for app in apps:
        try:
            s = stats(app.id)
        except Exception:
            logger.exception("compaction stats failed for app %s", app.name)
            continue
        if s is not None:
            out[app.name] = s
    return out
