"""Event model: Event, DataMap, PropertyMap, and validation.

Capability parity with the reference event model
(data/src/main/scala/io/prediction/data/storage/Event.scala:39-167,
DataMap.scala:42-191, PropertyMap.scala:33-96, EventJson4sSupport.scala:29-213):

- an Event is an immutable record of (event name, entity, optional target
  entity, JSON property bag, event time, tags, prId, creation time);
- names starting with ``$`` or ``pio_`` are reserved; the special events are
  ``$set`` / ``$unset`` / ``$delete``; the built-in entity type is ``pio_pr``;
- DataMap is an immutable JSON property bag with typed accessors and
  merge/remove operators; PropertyMap additionally carries first/last-updated
  times produced by property aggregation.

Times are timezone-aware ``datetime`` (UTC default, matching
EventValidation.defaultTimeZone, Event.scala:67).
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import itertools
import json
import os as _os
from typing import Any, Iterator, Mapping, Optional, Sequence


# --- reserved-name rules (reference Event.scala:65-167) ---

SPECIAL_EVENTS = frozenset({"$set", "$unset", "$delete"})
BUILTIN_ENTITY_TYPES = frozenset({"pio_pr"})
BUILTIN_PROPERTIES: frozenset = frozenset()


def is_reserved_prefix(name: str) -> bool:
    return name.startswith("$") or name.startswith("pio_")


def is_special_event(name: str) -> bool:
    return name in SPECIAL_EVENTS


class EventValidationError(ValueError):
    """Raised when an event violates the validation rules."""


def utcnow() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


def _ensure_aware(t: _dt.datetime) -> _dt.datetime:
    if t.tzinfo is None:
        return t.replace(tzinfo=_dt.timezone.utc)
    return t


def parse_iso8601(s: str) -> _dt.datetime:
    """Parse an ISO8601 timestamp, preserving its zone (UTC if naive)."""
    if s.endswith("Z"):
        s = s[:-1] + "+00:00"
    return _ensure_aware(_dt.datetime.fromisoformat(s))


def format_iso8601(t: _dt.datetime) -> str:
    """Render with millisecond precision, e.g. 2026-07-29T12:00:00.000Z."""
    t = _ensure_aware(t)
    base = t.strftime("%Y-%m-%dT%H:%M:%S")
    millis = t.microsecond // 1000
    off = t.utcoffset()
    if off is None or off == _dt.timedelta(0):
        zone = "Z"
    else:
        total = int(off.total_seconds())
        sign = "+" if total >= 0 else "-"
        total = abs(total)
        zone = f"{sign}{total // 3600:02d}:{(total % 3600) // 60:02d}"
    return f"{base}.{millis:03d}{zone}"


class DataMap(Mapping[str, Any]):
    """Immutable JSON property bag (reference DataMap.scala:42-191).

    Values are JSON-compatible Python values (str/int/float/bool/list/dict/
    None). Supports typed access (``get``, ``get_opt``, ``get_or_else``,
    ``require``), merge (``merged`` / ``|``) and key removal (``removed`` /
    ``-``).
    """

    __slots__ = ("_fields",)

    def __init__(self, fields: Optional[Mapping[str, Any]] = None):
        object.__setattr__(self, "_fields", dict(fields or {}))

    # Mapping protocol
    def __getitem__(self, key: str) -> Any:
        return self._fields[key]

    def __iter__(self) -> Iterator[str]:
        return iter(self._fields)

    def __len__(self) -> int:
        return len(self._fields)

    def __contains__(self, key: object) -> bool:
        return key in self._fields

    @property
    def fields(self) -> dict:
        return dict(self._fields)

    def is_empty(self) -> bool:
        return not self._fields

    def require(self, name: str) -> None:
        if name not in self._fields:
            raise KeyError(f"The field {name} is required.")

    def get(self, name: str, default: Any = None) -> Any:
        """Return the field value; fields present but JSON-null raise."""
        if name in self._fields:
            v = self._fields[name]
            if v is None:
                raise ValueError(f"The required field {name} cannot be null.")
            return v
        if default is not None:
            return default
        raise KeyError(f"The field {name} is required.")

    def get_opt(self, name: str) -> Optional[Any]:
        return self._fields.get(name)

    def get_or_else(self, name: str, default: Any) -> Any:
        v = self._fields.get(name)
        return default if v is None else v

    def merged(self, other: "DataMap | Mapping[str, Any]") -> "DataMap":
        out = dict(self._fields)
        out.update(dict(other))
        return DataMap(out)

    def removed(self, keys: Sequence[str]) -> "DataMap":
        out = {k: v for k, v in self._fields.items() if k not in set(keys)}
        return DataMap(out)

    def __or__(self, other: "DataMap | Mapping[str, Any]") -> "DataMap":
        return self.merged(other)

    def __sub__(self, keys: Sequence[str]) -> "DataMap":
        return self.removed(keys)

    def __eq__(self, other: object) -> bool:
        if isinstance(other, DataMap):
            return self._fields == other._fields
        if isinstance(other, Mapping):
            return self._fields == dict(other)
        return NotImplemented

    def __hash__(self) -> int:
        return hash(json.dumps(self._fields, sort_keys=True, default=str))

    def __repr__(self) -> str:
        return f"DataMap({self._fields!r})"

    def to_json(self) -> dict:
        return dict(self._fields)

    @staticmethod
    def from_json(obj: Optional[Mapping[str, Any]]) -> "DataMap":
        return DataMap(obj or {})


class PropertyMap(DataMap):
    """DataMap plus aggregation timestamps (reference PropertyMap.scala:33-96)."""

    __slots__ = ("first_updated", "last_updated")

    def __init__(
        self,
        fields: Optional[Mapping[str, Any]],
        first_updated: _dt.datetime,
        last_updated: _dt.datetime,
    ):
        super().__init__(fields)
        object.__setattr__(self, "first_updated", _ensure_aware(first_updated))
        object.__setattr__(self, "last_updated", _ensure_aware(last_updated))

    def __repr__(self) -> str:
        return (
            f"PropertyMap({self.fields!r}, first_updated={self.first_updated},"
            f" last_updated={self.last_updated})"
        )

    def __eq__(self, other: object) -> bool:
        if isinstance(other, PropertyMap):
            return (
                self.fields == other.fields
                and self.first_updated == other.first_updated
                and self.last_updated == other.last_updated
            )
        return super().__eq__(other)

    __hash__ = DataMap.__hash__


@dataclasses.dataclass(frozen=True)
class Event:
    """An immutable event record (reference Event.scala:39-57)."""

    event: str
    entity_type: str
    entity_id: str
    target_entity_type: Optional[str] = None
    target_entity_id: Optional[str] = None
    properties: DataMap = dataclasses.field(default_factory=DataMap)
    event_time: _dt.datetime = dataclasses.field(default_factory=utcnow)
    tags: tuple = ()
    pr_id: Optional[str] = None
    event_id: Optional[str] = None
    creation_time: _dt.datetime = dataclasses.field(default_factory=utcnow)

    def __post_init__(self):
        if not isinstance(self.properties, DataMap):
            object.__setattr__(self, "properties", DataMap(self.properties))
        object.__setattr__(self, "event_time", _ensure_aware(self.event_time))
        object.__setattr__(self, "creation_time", _ensure_aware(self.creation_time))
        object.__setattr__(self, "tags", tuple(self.tags))

    def with_event_id(self, event_id: str) -> "Event":
        # shallow clone + one field write: dataclasses.replace re-runs
        # __init__/__post_init__ normalization this (already-normalized)
        # record doesn't need — it was a measurable slice of batch ingest
        clone = object.__new__(Event)
        clone.__dict__.update(self.__dict__)
        object.__setattr__(clone, "event_id", event_id)
        return clone

    # --- JSON (API format: ISO8601 times, reference EventJson4sSupport) ---

    def to_json(self) -> dict:
        out: dict = {
            "eventId": self.event_id,
            "event": self.event,
            "entityType": self.entity_type,
            "entityId": self.entity_id,
        }
        if self.target_entity_type is not None:
            out["targetEntityType"] = self.target_entity_type
        if self.target_entity_id is not None:
            out["targetEntityId"] = self.target_entity_id
        out["properties"] = self.properties.to_json()
        out["eventTime"] = format_iso8601(self.event_time)
        if self.tags:
            out["tags"] = list(self.tags)
        if self.pr_id is not None:
            out["prId"] = self.pr_id
        out["creationTime"] = format_iso8601(self.creation_time)
        return out

    @staticmethod
    def from_json(obj: Mapping[str, Any], *, validate: bool = True) -> "Event":
        try:
            event = obj["event"]
            entity_type = obj["entityType"]
            entity_id = obj["entityId"]
        except KeyError as e:
            raise EventValidationError(f"field {e.args[0]} is required") from e
        for f in ("event", "entityType", "entityId"):
            if not isinstance(obj[f], str):
                raise EventValidationError(f"field {f} must be a string")
        raw_time = obj.get("eventTime")
        if raw_time is not None:
            if not isinstance(raw_time, str):
                raise EventValidationError(
                    f"eventTime {raw_time!r} must be an ISO8601 string"
                )
            try:
                event_time = parse_iso8601(raw_time)
            except (ValueError, TypeError) as e:
                raise EventValidationError(
                    f"eventTime {raw_time!r} is not ISO8601"
                ) from e
        else:
            event_time = utcnow()
        props = obj.get("properties") or {}
        if not isinstance(props, Mapping):
            raise EventValidationError("properties must be a JSON object")
        e = Event(
            event=event,
            entity_type=entity_type,
            entity_id=entity_id,
            target_entity_type=obj.get("targetEntityType"),
            target_entity_id=obj.get("targetEntityId"),
            properties=DataMap(props),
            event_time=event_time,
            tags=tuple(obj.get("tags") or ()),
            pr_id=obj.get("prId"),
            event_id=obj.get("eventId"),
        )
        if validate:
            validate_event(e)
        return e


def validate_event(e: Event) -> None:
    """Apply the reference validation rules (Event.scala:110-140).

    Raises EventValidationError on the first violated rule.
    """

    def req(cond: bool, msg: str) -> None:
        if not cond:
            raise EventValidationError(msg)

    req(bool(e.event), "event must not be empty.")
    req(bool(e.entity_type), "entityType must not be empty string.")
    req(bool(e.entity_id), "entityId must not be empty string.")
    req(
        e.target_entity_type is None or bool(e.target_entity_type),
        "targetEntityType must not be empty string",
    )
    req(
        e.target_entity_id is None or bool(e.target_entity_id),
        "targetEntityId must not be empty string.",
    )
    req(
        (e.target_entity_type is None) == (e.target_entity_id is None),
        "targetEntityType and targetEntityId must be specified together.",
    )
    req(
        not (e.event == "$unset" and e.properties.is_empty()),
        "properties cannot be empty for $unset event",
    )
    req(
        not is_reserved_prefix(e.event) or is_special_event(e.event),
        f"{e.event} is not a supported reserved event name.",
    )
    req(
        not is_special_event(e.event)
        or (e.target_entity_type is None and e.target_entity_id is None),
        f"Reserved event {e.event} cannot have targetEntity",
    )
    req(
        not is_reserved_prefix(e.entity_type)
        or e.entity_type in BUILTIN_ENTITY_TYPES,
        f"The entityType {e.entity_type} is not allowed. "
        "'pio_' is a reserved name prefix.",
    )
    req(
        e.target_entity_type is None
        or not is_reserved_prefix(e.target_entity_type)
        or e.target_entity_type in BUILTIN_ENTITY_TYPES,
        f"The targetEntityType {e.target_entity_type} is not allowed. "
        "'pio_' is a reserved name prefix.",
    )
    for k in e.properties:
        req(
            not is_reserved_prefix(k) or k in BUILTIN_PROPERTIES,
            f"The property {k} is not allowed. 'pio_' is a reserved name prefix.",
        )


# 64-bit random per-process prefix + monotone counter. uuid4 paid an
# os.urandom syscall PER EVENT — measured ~30% of the batch-ingest
# request core; this keeps the same 32-hex-char shape at the cost of an
# atomic counter increment. Cross-process uniqueness rests on the
# random prefix (collision odds 2^-64 per process pair).
_ID_PREFIX = _os.urandom(8).hex()
_ID_COUNTER = itertools.count(int.from_bytes(_os.urandom(4), "big"))


def new_event_id() -> str:
    """Generate a unique event id (reference derives it from the storage row
    key, HBEventsUtil.scala:93; here a random-prefix counter suffices)."""
    return _ID_PREFIX + format(
        next(_ID_COUNTER) & 0xFFFFFFFFFFFFFFFF, "016x"
    )
