"""Segment.io webhook connector.

Parity with the reference SegmentIOConnector
(data/src/main/scala/io/prediction/data/webhooks/segmentio/SegmentIOConnector.scala:26-80):
the six Segment spec message types (identify / track / alias / page /
screen / group) become events named after the message type, with
``entityType: "user"`` and the ``userId`` (or ``anonymousId``) as the
entity id; type-specific payload fields land in ``properties``, with the
optional ``context`` object merged alongside them.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping

from predictionio_tpu.data.webhooks import ConnectorException, JsonConnector

# per message type: payload fields copied into event properties
_TYPE_FIELDS = {
    "identify": ("traits",),
    "track": ("properties", "event"),
    "alias": ("previousId",),
    "page": ("name", "properties"),
    "screen": ("name", "properties"),
    "group": ("groupId", "traits"),
}


class SegmentIOConnector(JsonConnector):
    def to_event_json(self, data: Mapping[str, Any]) -> Dict[str, Any]:
        msg_type = data.get("type")
        if msg_type is None:
            raise ConnectorException(
                "Cannot extract the message type from the Segment.io payload."
            )
        if msg_type not in _TYPE_FIELDS:
            raise ConnectorException(
                f"Cannot convert unknown type {msg_type} to event JSON."
            )
        user_id = data.get("userId") or data.get("anonymousId")
        if not user_id:
            raise ConnectorException(
                "there was no `userId` or `anonymousId` in the common fields."
            )
        timestamp = data.get("timestamp")
        if timestamp is None:
            raise ConnectorException(
                "there was no `timestamp` in the common fields."
            )

        properties: Dict[str, Any] = {}
        context = data.get("context")
        if context is not None:
            properties["context"] = context
        for field in _TYPE_FIELDS[msg_type]:
            if data.get(field) is not None:
                properties[field] = data[field]

        return {
            "event": msg_type,
            "entityType": "user",
            "entityId": user_id,
            "eventTime": timestamp,
            "properties": properties,
        }
