"""MailChimp webhook connector (form-encoded payloads).

Parity with the reference MailChimpConnector
(data/src/main/scala/io/prediction/data/webhooks/mailchimp/MailChimpConnector.scala):
the six MailChimp webhook types map to events as

  subscribe / unsubscribe / profile : user -> list, merge fields in props
  upemail                           : user (new_id) -> list, old/new email
  cleaned                           : entity = the list, campaign/reason/email
  campaign                          : campaign -> list, subject/status/reason

MailChimp timestamps ("fired_at") are "YYYY-MM-DD HH:MM:SS" in UTC and are
rewritten to ISO8601 for the canonical event JSON.
"""

from __future__ import annotations

import datetime as _dt
from typing import Any, Dict, Mapping

from predictionio_tpu.data.event import format_iso8601
from predictionio_tpu.data.webhooks import ConnectorException, FormConnector


def _fired_at_iso(data: Mapping[str, str]) -> str:
    raw = _require(data, "fired_at")
    try:
        t = _dt.datetime.strptime(raw, "%Y-%m-%d %H:%M:%S").replace(
            tzinfo=_dt.timezone.utc
        )
    except ValueError as e:
        raise ConnectorException(
            f"fired_at {raw!r} is not 'YYYY-MM-DD HH:MM:SS'"
        ) from e
    return format_iso8601(t)


def _require(data: Mapping[str, str], key: str) -> str:
    if key not in data:
        raise ConnectorException(
            f"The field '{key}' is required for MailChimp data."
        )
    return data[key]


def _merges(data: Mapping[str, str]) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "EMAIL": _require(data, "data[merges][EMAIL]"),
        "FNAME": _require(data, "data[merges][FNAME]"),
        "LNAME": _require(data, "data[merges][LNAME]"),
    }
    interests = data.get("data[merges][INTERESTS]")
    if interests is not None:
        out["INTERESTS"] = interests
    return out


class MailChimpConnector(FormConnector):
    def to_event_json(self, data: Mapping[str, str]) -> Dict[str, Any]:
        handlers = {
            "subscribe": self._subscribe,
            "unsubscribe": self._unsubscribe,
            "profile": self._profile,
            "upemail": self._upemail,
            "cleaned": self._cleaned,
            "campaign": self._campaign,
        }
        msg_type = data.get("type")
        if msg_type is None:
            raise ConnectorException(
                "The field 'type' is required for MailChimp data."
            )
        handler = handlers.get(msg_type)
        if handler is None:
            raise ConnectorException(
                f"Cannot convert unknown MailChimp data type {msg_type} to event JSON"
            )
        return handler(data)

    def _subscribe(self, d: Mapping[str, str]) -> Dict[str, Any]:
        return {
            "event": "subscribe",
            "entityType": "user",
            "entityId": _require(d, "data[id]"),
            "targetEntityType": "list",
            "targetEntityId": _require(d, "data[list_id]"),
            "eventTime": _fired_at_iso(d),
            "properties": {
                "email": _require(d, "data[email]"),
                "email_type": _require(d, "data[email_type]"),
                "merges": _merges(d),
                "ip_opt": _require(d, "data[ip_opt]"),
                "ip_signup": _require(d, "data[ip_signup]"),
            },
        }

    def _unsubscribe(self, d: Mapping[str, str]) -> Dict[str, Any]:
        return {
            "event": "unsubscribe",
            "entityType": "user",
            "entityId": _require(d, "data[id]"),
            "targetEntityType": "list",
            "targetEntityId": _require(d, "data[list_id]"),
            "eventTime": _fired_at_iso(d),
            "properties": {
                "action": _require(d, "data[action]"),
                "reason": _require(d, "data[reason]"),
                "email": _require(d, "data[email]"),
                "email_type": _require(d, "data[email_type]"),
                "merges": _merges(d),
                "ip_opt": _require(d, "data[ip_opt]"),
                "campaign_id": _require(d, "data[campaign_id]"),
            },
        }

    def _profile(self, d: Mapping[str, str]) -> Dict[str, Any]:
        return {
            "event": "profile",
            "entityType": "user",
            "entityId": _require(d, "data[id]"),
            "targetEntityType": "list",
            "targetEntityId": _require(d, "data[list_id]"),
            "eventTime": _fired_at_iso(d),
            "properties": {
                "email": _require(d, "data[email]"),
                "email_type": _require(d, "data[email_type]"),
                "merges": _merges(d),
                "ip_opt": _require(d, "data[ip_opt]"),
            },
        }

    def _upemail(self, d: Mapping[str, str]) -> Dict[str, Any]:
        return {
            "event": "upemail",
            "entityType": "user",
            "entityId": _require(d, "data[new_id]"),
            "targetEntityType": "list",
            "targetEntityId": _require(d, "data[list_id]"),
            "eventTime": _fired_at_iso(d),
            "properties": {
                "new_email": _require(d, "data[new_email]"),
                "old_email": _require(d, "data[old_email]"),
            },
        }

    def _cleaned(self, d: Mapping[str, str]) -> Dict[str, Any]:
        return {
            "event": "cleaned",
            "entityType": "list",
            "entityId": _require(d, "data[list_id]"),
            "eventTime": _fired_at_iso(d),
            "properties": {
                "campaignId": _require(d, "data[campaign_id]"),
                "reason": _require(d, "data[reason]"),
                "email": _require(d, "data[email]"),
            },
        }

    def _campaign(self, d: Mapping[str, str]) -> Dict[str, Any]:
        return {
            "event": "campaign",
            "entityType": "campaign",
            "entityId": _require(d, "data[id]"),
            "targetEntityType": "list",
            "targetEntityId": _require(d, "data[list_id]"),
            "eventTime": _fired_at_iso(d),
            "properties": {
                "subject": _require(d, "data[subject]"),
                "status": _require(d, "data[status]"),
                "reason": _require(d, "data[reason]"),
            },
        }
