"""Webhook connector framework.

Capability parity with the reference webhooks layer
(data/src/main/scala/io/prediction/data/webhooks/): connectors translate
third-party payloads into the canonical event-JSON shape, which is then
parsed through the same ``Event.from_json`` path as first-party events so
validation stays uniform (ConnectorUtil.scala:28-46 makes the same point:
connectors may only produce event JSON, never Event objects directly).

A ``JsonConnector`` receives a parsed JSON object; a ``FormConnector``
receives a flat str->str form-field map. The dispatch table lives in
``predictionio_tpu.api.event_server`` (reference WebhooksConnectors.scala).
"""

from __future__ import annotations

import abc
from typing import Any, Dict, Mapping

from predictionio_tpu.data.event import Event


class ConnectorException(Exception):
    """A payload could not be translated (reference ConnectorException)."""


class JsonConnector(abc.ABC):
    """Translate a third-party JSON payload into event JSON
    (reference JsonConnector.scala:24-31)."""

    @abc.abstractmethod
    def to_event_json(self, data: Mapping[str, Any]) -> Dict[str, Any]:
        ...


class FormConnector(abc.ABC):
    """Translate form-encoded fields into event JSON
    (reference FormConnector.scala:24-32)."""

    @abc.abstractmethod
    def to_event_json(self, data: Mapping[str, str]) -> Dict[str, Any]:
        ...


def to_event(connector, data) -> Event:
    """Connector payload -> Event, via the canonical JSON parse + validation
    (reference ConnectorUtil.toEvent, ConnectorUtil.scala:38-45)."""
    return Event.from_json(connector.to_event_json(data), validate=True)
