"""Example webhook connectors — the template third parties copy to write
their own (reference data/webhooks/examplejson/ExampleJsonConnector.scala
and exampleform/ExampleFormConnector.scala). Both translate two payload
types, ``userAction`` and ``userActionItem``, into the canonical event
JSON; the form variant also demonstrates two-level ``context[...]``
fields and string->number coercion.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional

from predictionio_tpu.data.webhooks import (
    ConnectorException,
    FormConnector,
    JsonConnector,
)


class ExampleJsonConnector(JsonConnector):
    """Reference ExampleJsonConnector (examplejson/ExampleJsonConnector.scala:60-126)."""

    def to_event_json(self, data: Mapping[str, Any]) -> Dict[str, Any]:
        kind = data.get("type")
        if kind is None:
            raise ConnectorException(
                f"Cannot extract Common field from {dict(data)!r}: "
                "'type' is required."
            )
        # optional fields are OMITTED when absent — the reference's json4s
        # DSL drops None options, so emitting explicit nulls here would
        # store properties (e.g. "context": null) the reference omits
        def props(required: Dict[str, Any], *optional: str) -> Dict[str, Any]:
            return dict(
                required,
                **{k: data[k] for k in optional if data.get(k) is not None},
            )

        try:
            if kind == "userAction":
                return {
                    "event": data["event"],
                    "entityType": "user",
                    "entityId": data["userId"],
                    "eventTime": data["timestamp"],
                    "properties": props(
                        {"anotherProperty1": data["anotherProperty1"]},
                        "context", "anotherProperty2",
                    ),
                }
            if kind == "userActionItem":
                return {
                    "event": data["event"],
                    "entityType": "user",
                    "entityId": data["userId"],
                    "targetEntityType": "item",
                    "targetEntityId": data["itemId"],
                    "eventTime": data["timestamp"],
                    "properties": props(
                        {}, "context", "anotherPropertyA", "anotherPropertyB",
                    ),
                }
        except KeyError as e:
            raise ConnectorException(
                f"Cannot convert {dict(data)!r} to event JSON: "
                f"missing field {e}."
            ) from e
        raise ConnectorException(
            f"Cannot convert unknown type {kind!r} to Event JSON."
        )


class ExampleFormConnector(FormConnector):
    """Reference ExampleFormConnector (exampleform/ExampleFormConnector.scala:52-130)."""

    def to_event_json(self, data: Mapping[str, str]) -> Dict[str, Any]:
        kind = data.get("type")
        if kind is None:
            raise ConnectorException("The field 'type' is required.")
        try:
            if kind == "userAction":
                props: Dict[str, Any] = {
                    "anotherProperty1": int(data["anotherProperty1"]),
                }
                if "anotherProperty2" in data:
                    props["anotherProperty2"] = data["anotherProperty2"]
                context = self._context(data)
                if context is not None:
                    props["context"] = context
                return self._base(data, props)
            if kind == "userActionItem":
                props = {}
                if "anotherPropertyA" in data:
                    props["anotherPropertyA"] = float(data["anotherPropertyA"])
                if "anotherPropertyB" in data:
                    props["anotherPropertyB"] = (
                        data["anotherPropertyB"].lower() == "true"
                    )
                context = self._context(data)
                if context is not None:
                    props["context"] = context
                out = self._base(data, props)
                out["targetEntityType"] = "item"
                out["targetEntityId"] = data["itemId"]
                return out
        except (KeyError, ValueError) as e:
            raise ConnectorException(
                f"Cannot convert {dict(data)!r} to event JSON: {e}."
            ) from e
        raise ConnectorException(
            f"Cannot convert unknown type {kind!r} to event JSON"
        )

    @staticmethod
    def _base(data: Mapping[str, str], props: Dict[str, Any]) -> Dict[str, Any]:
        return {
            "event": data["event"],
            "entityType": "user",
            "entityId": data["userId"],
            "eventTime": data["timestamp"],
            "properties": props,
        }

    @staticmethod
    def _context(data: Mapping[str, str]) -> Optional[Dict[str, Any]]:
        """Two-level optional ``context[...]`` form fields
        (ExampleFormConnector.scala:77-86)."""
        if not any(k.startswith("context[") for k in data):
            return None
        out: Dict[str, Any] = {}
        if "context[ip]" in data:
            out["ip"] = data["context[ip]"]
        if "context[prop1]" in data:
            out["prop1"] = float(data["context[prop1]"])
        if "context[prop2]" in data:
            out["prop2"] = data["context[prop2]"]
        return out
