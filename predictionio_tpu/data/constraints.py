"""TTL-cached constraint-entity reader for the serving hot path.

The reference's e-commerce template re-reads the ``unavailableItems``
constraint entity from the event store INSIDE every predict
(ALSAlgorithm.scala of the train-with-rate-event variant) — ported
literally, that put one storage round trip (and, with the ``http``
backend, one gateway RPC) on every served batch, and a stalled store
stalled serving. This module extracts that read behind a TTL cache with
OUT-OF-BAND refresh:

- ``get()`` returns the cached set and NEVER touches the store once
  primed: past the TTL it kicks a single background refresh thread and
  keeps serving the cached value, so a store stall can no longer block
  a batch (only the very first call, typically at deploy, reads
  inline).
- Refreshes that CHANGE the set notify ``on_change`` listeners — the
  retrieval tier (ops/retrieval.py) subscribes to rebuild its resident
  on-device candidacy mask, which is what "refreshed out-of-band on
  constraint-entity change" means end to end. The mask's device
  residency is accounted in the HBM ledger under the retriever's
  ``<component>-mask`` entry (utils/device_ledger.py): every
  constraint-driven re-upload re-``set``s that entry, so
  ``pio_device_ledger_bytes`` tracks the mask through its whole
  refresh lifecycle.
- Every read outcome is counted in
  ``pio_constraint_cache_total{outcome=hit|miss|error}`` (miss = an
  actual store read, inline or background; error = the store raised and
  the cached value was served).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, FrozenSet, List, Optional

from predictionio_tpu.utils import metrics as _metrics

logger = logging.getLogger(__name__)


def _m_outcomes():
    return _metrics.get_registry().counter(
        "pio_constraint_cache_total",
        "Constraint-entity reads by outcome (hit=served from cache, "
        "miss=store read, error=store failed and cache served)",
        labels=("outcome",),
    )


def read_constraint_items(
    app_name: str,
    entity_id: str = "unavailableItems",
    prop: str = "items",
    storage=None,
    timeout_seconds: Optional[float] = 10.0,
) -> FrozenSet[str]:
    """One store read of the latest ``$set`` on the constraint entity
    (reference semantics: only the single latest event counts)."""
    from predictionio_tpu.data.store import LEventStore

    events = list(
        LEventStore(storage).find_by_entity(
            app_name=app_name,
            entity_type="constraint",
            entity_id=entity_id,
            event_names=["$set"],
            limit=1,
            latest=True,
            timeout_seconds=timeout_seconds,
        )
    )
    if events:
        return frozenset(events[0].properties.get_or_else(prop, []))
    return frozenset()


class ConstraintCache:
    """TTL cache over one constraint entity's item set.

    Thread-safe; shared by the predict hot path (``get``) and the
    retrieval mask-refresh path (``on_change`` listeners fire from the
    background refresh thread whenever the set changes). ``ttl_s=0``
    disables caching entirely (every ``get`` reads inline — the
    pre-round-12 behavior, kept for tests that assert store-read
    semantics)."""

    def __init__(
        self,
        app_name: str,
        entity_id: str = "unavailableItems",
        prop: str = "items",
        ttl_s: float = 5.0,
        storage=None,
        reader: Optional[Callable[[], FrozenSet[str]]] = None,
    ):
        self.app_name = app_name
        self.ttl_s = float(ttl_s)
        self._reader = reader or (
            lambda: read_constraint_items(
                app_name, entity_id=entity_id, prop=prop, storage=storage
            )
        )
        self._lock = threading.Lock()
        self._value: Optional[FrozenSet[str]] = None
        self._loaded_at = 0.0
        self._refreshing = False
        self._listeners: List[Callable[[FrozenSet[str]], None]] = []

    def on_change(self, fn: Callable[[FrozenSet[str]], None]) -> None:
        """Register a listener called (from the refreshing thread) with
        the NEW set whenever a refresh observes a change."""
        with self._lock:
            self._listeners.append(fn)

    @property
    def age_s(self) -> float:
        with self._lock:
            if self._value is None:
                return float("inf")
            return time.monotonic() - self._loaded_at

    def get(self) -> FrozenSet[str]:
        """The constraint set, from cache. Primed + fresh -> hit. Primed
        + stale -> hit NOW, one background refresh kicked (out-of-band:
        the caller's batch never waits on the store). Unprimed -> one
        inline read (deploy-time)."""
        with self._lock:
            value = self._value
            stale = (
                value is not None
                and self.ttl_s > 0
                and (time.monotonic() - self._loaded_at) > self.ttl_s
            )
            kick = stale and not self._refreshing
            if kick:
                self._refreshing = True
        if value is None or self.ttl_s <= 0:
            return self._read_inline()
        _m_outcomes().labels(outcome="hit").inc()
        if kick:
            threading.Thread(
                target=self._refresh_bg, daemon=True,
                name=f"constraint-refresh:{self.app_name}",
            ).start()
        return value

    def refresh(self) -> bool:
        """Force one inline read; returns whether the set changed.
        Listeners fire on change. Used by tests and by deploy-time
        priming; the serving path never calls it."""
        before = self._value
        value = self._read_inline()
        changed = before is not None and value != before
        if changed:
            self._notify(value)
        return changed or before is None

    def _read_inline(self) -> FrozenSet[str]:
        try:
            value = self._reader()
            _m_outcomes().labels(outcome="miss").inc()
        except Exception as e:
            _m_outcomes().labels(outcome="error").inc()
            logger.error("Error when reading constraint entity: %s", e)
            with self._lock:
                if self._value is None:
                    # error-PRIME: an unprimed cache whose first read
                    # fails (store down at deploy) must not stay
                    # unprimed — that would put a blocking inline read
                    # (up to the reader timeout) on EVERY batch until
                    # the store recovers. Serve the empty set as the
                    # cached value instead; the normal TTL tick retries
                    # out-of-band and the on_change listeners fire once
                    # the store answers.
                    self._value = frozenset()
                    self._loaded_at = time.monotonic()
                return self._value
        with self._lock:
            self._value = value
            self._loaded_at = time.monotonic()
        return value

    def _refresh_bg(self) -> None:
        try:
            before = self._value
            value = self._read_inline()
            if before is not None and value != before:
                self._notify(value)
        finally:
            with self._lock:
                self._refreshing = False

    def _notify(self, value: FrozenSet[str]) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(value)
            except Exception:
                logger.exception("constraint on_change listener failed")
