"""Property aggregation: fold $set/$unset/$delete streams into PropertyMaps.

Capability parity with the reference's LEventAggregator
(data/src/main/scala/io/prediction/data/storage/LEventAggregator.scala:39-145)
and PEventAggregator (PEventAggregator.scala). The fold semantics:

- events are processed in event-time order;
- ``$set`` merges properties over the current map (creating it if absent);
- ``$unset`` removes the named keys (no-op when no map exists yet);
- ``$delete`` discards the map entirely;
- any other event name leaves the state untouched;
- first/last-updated times track only the special events' event times;
- entities whose final state is "deleted" (or never set) are omitted.

The reference runs this fold as a Spark ``aggregateByKey``; here it is a plain
host-side fold — property aggregation is string/JSON manipulation that belongs
on the host, with the *output* (feature batches) being what moves to TPU.
"""

from __future__ import annotations

import datetime as _dt
from typing import Dict, Iterable, Optional, Tuple

from predictionio_tpu.data.event import DataMap, Event, PropertyMap

_AGG_EVENTS = ("$set", "$unset", "$delete")


class _Prop:
    __slots__ = ("dm", "first_updated", "last_updated")

    def __init__(self):
        self.dm: Optional[DataMap] = None
        self.first_updated: Optional[_dt.datetime] = None
        self.last_updated: Optional[_dt.datetime] = None

    def fold(self, e: Event) -> None:
        if e.event not in _AGG_EVENTS:
            return
        if e.event == "$set":
            self.dm = e.properties if self.dm is None else self.dm.merged(e.properties)
        elif e.event == "$unset":
            if self.dm is not None:
                self.dm = self.dm.removed(list(e.properties.keys()))
        elif e.event == "$delete":
            self.dm = None
        t = e.event_time
        self.first_updated = t if self.first_updated is None else min(self.first_updated, t)
        self.last_updated = t if self.last_updated is None else max(self.last_updated, t)

    def to_property_map(self) -> Optional[PropertyMap]:
        if self.dm is None:
            return None
        assert self.first_updated is not None and self.last_updated is not None
        return PropertyMap(self.dm.fields, self.first_updated, self.last_updated)


def aggregate_properties(events: Iterable[Event]) -> Dict[str, PropertyMap]:
    """Aggregate per-entity properties from an event stream.

    Returns {entityId: PropertyMap} for entities whose latest state exists
    (reference LEventAggregator.aggregateProperties:39-66).
    """
    by_entity: Dict[str, list] = {}
    for e in events:
        by_entity.setdefault(e.entity_id, []).append(e)
    out: Dict[str, PropertyMap] = {}
    for entity_id, evs in by_entity.items():
        evs.sort(key=lambda e: e.event_time)
        prop = _Prop()
        for e in evs:
            prop.fold(e)
        pm = prop.to_property_map()
        if pm is not None:
            out[entity_id] = pm
    return out


def aggregate_properties_single(events: Iterable[Event]) -> Optional[PropertyMap]:
    """Aggregate properties of a single entity's event stream
    (reference LEventAggregator.aggregatePropertiesSingle:67-91)."""
    evs = sorted(events, key=lambda e: e.event_time)
    prop = _Prop()
    for e in evs:
        prop.fold(e)
    return prop.to_property_map()


def aggregate_properties_keyed(
    events: Iterable[Event],
) -> Dict[Tuple[str, str], PropertyMap]:
    """Aggregate grouped by (entityType, entityId) — used by stores that serve
    multiple entity types from one scan."""
    by_key: Dict[Tuple[str, str], list] = {}
    for e in events:
        by_key.setdefault((e.entity_type, e.entity_id), []).append(e)
    out: Dict[Tuple[str, str], PropertyMap] = {}
    for key, evs in by_key.items():
        pm = aggregate_properties_single(evs)
        if pm is not None:
            out[key] = pm
    return out
