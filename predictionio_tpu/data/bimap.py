"""BiMap: bidirectional id <-> dense-index mapping.

Capability parity with the reference's BiMap
(data/src/main/scala/io/prediction/data/storage/BiMap.scala:93-164). In the
TPU build this is the bridge between string entity ids (host-side) and dense
integer indices addressing rows of device arrays (factor matrices, count
tables) — the reference's role of indexing MLlib ALS inputs.
"""

from __future__ import annotations

from typing import Dict, Generic, Iterable, Iterator, List, Mapping, Optional, TypeVar

K = TypeVar("K")
V = TypeVar("V")


class BiMap(Generic[K, V]):
    """Immutable bidirectional map. Values must be unique."""

    __slots__ = ("_forward", "_inverse")

    def __init__(self, forward: Mapping[K, V], _inverse: Optional[Dict[V, K]] = None):
        fwd = dict(forward)
        if _inverse is None:
            inv: Dict[V, K] = {}
            for k, v in fwd.items():
                if v in inv:
                    raise ValueError(f"BiMap values must be unique; duplicate {v!r}")
                inv[v] = k
        else:
            inv = _inverse
        self._forward = fwd
        self._inverse = inv

    def __getitem__(self, key: K) -> V:
        return self._forward[key]

    def get(self, key: K, default: Optional[V] = None) -> Optional[V]:
        return self._forward.get(key, default)

    def __contains__(self, key: K) -> bool:
        return key in self._forward

    def __len__(self) -> int:
        return len(self._forward)

    def __iter__(self) -> Iterator[K]:
        return iter(self._forward)

    def keys(self):
        return self._forward.keys()

    def values(self):
        return self._forward.values()

    def items(self):
        return self._forward.items()

    def inverse(self) -> "BiMap[V, K]":
        return BiMap(self._inverse, dict(self._forward))

    def to_dict(self) -> Dict[K, V]:
        return dict(self._forward)

    def __eq__(self, other: object) -> bool:
        if isinstance(other, BiMap):
            return self._forward == other._forward
        return NotImplemented

    def __repr__(self) -> str:
        return f"BiMap({self._forward!r})"

    # --- constructors (reference BiMap object :93-164) ---

    @staticmethod
    def string_int(keys: Iterable[str]) -> "BiMap[str, int]":
        """Map distinct string keys to dense 0-based int indices, in sorted
        order for determinism (the reference uses RDD `.distinct.collect`
        ordering, which is unspecified; sorted is reproducible)."""
        distinct = sorted(set(keys))
        return BiMap({k: i for i, k in enumerate(distinct)})

    @staticmethod
    def string_long(keys: Iterable[str]) -> "BiMap[str, int]":
        return BiMap.string_int(keys)

    @staticmethod
    def int_index(keys: Iterable[K]) -> "BiMap[K, int]":
        """Dense index over arbitrary hashable keys, insertion-ordered."""
        out: Dict[K, int] = {}
        for k in keys:
            if k not in out:
                out[k] = len(out)
        return BiMap(out)

    def take(self, n: int) -> "BiMap[K, V]":
        out = {}
        for i, (k, v) in enumerate(self._forward.items()):
            if i >= n:
                break
            out[k] = v
        return BiMap(out)

    def map_values_to_list(self, keys: Iterable[K]) -> List[V]:
        fw = self._forward
        return [fw[k] for k in keys]
