"""Event store access layer for engine developers.

Capability parity with the reference's store layer
(data/src/main/scala/io/prediction/data/store/): ``PEventStore``
(PEventStore.scala:30 — find + aggregateProperties by app *name*),
``LEventStore`` (LEventStore.scala:146 — findByEntity serving-time lookups
with timeout), and app-name/channel resolution (Common.scala:28-49).

Where the reference returns RDDs, the batch API here returns host lists
plus a columnar view (``EventColumns``) holding dense numpy id/value
columns with BiMap indexes — the form that `jax.device_put` moves straight
into HBM for kernel consumption (SURVEY.md §7 step 1).
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.event import Event, PropertyMap
from predictionio_tpu.data.storage import Storage, get_storage
from predictionio_tpu.data.storage.base import UNSET, OptFilter


class AppNotFoundError(KeyError):
    pass


class ChannelNotFoundError(KeyError):
    pass


def app_name_to_id(
    app_name: str, channel_name: Optional[str] = None, storage: Optional[Storage] = None
) -> Tuple[int, Optional[int]]:
    """Resolve appName (+ optional channel) to ids
    (reference store/Common.scala:28-49)."""
    storage = storage or get_storage()
    app = storage.get_meta_data_apps().get_by_name(app_name)
    if app is None:
        raise AppNotFoundError(f"App {app_name!r} does not exist; use pio app new")
    channel_id: Optional[int] = None
    if channel_name is not None:
        channels = storage.get_meta_data_channels().get_by_app_id(app.id)
        match = [c for c in channels if c.name == channel_name]
        if not match:
            raise ChannelNotFoundError(
                f"Channel {channel_name!r} does not exist in app {app_name!r}"
            )
        channel_id = match[0].id
    return app.id, channel_id


@dataclasses.dataclass
class EventColumns:
    """Column-oriented batch of (entity, target, value) triples with dense
    indexes — the device-bound form of an event scan."""

    entity_index: BiMap  # entityId -> dense int
    target_index: BiMap  # targetEntityId -> dense int
    entity_idx: np.ndarray  # [n] int32
    target_idx: np.ndarray  # [n] int32
    values: np.ndarray  # [n] float32
    events: List[Event]  # originating events (host metadata)

    @property
    def n(self) -> int:
        return len(self.values)


class PEventStore:
    """Batch event reads by app name (reference PEventStore.scala:30-116)."""

    def __init__(self, storage: Optional[Storage] = None):
        self._storage = storage

    @property
    def storage(self) -> Storage:
        return self._storage or get_storage()

    def find(
        self,
        app_name: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: OptFilter = UNSET,
        target_entity_id: OptFilter = UNSET,
    ) -> Iterator[Event]:
        app_id, channel_id = app_name_to_id(app_name, channel_name, self.storage)
        return self.storage.get_p_events().find(
            app_id=app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
        )

    def aggregate_properties(
        self,
        app_name: str,
        entity_type: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        required: Optional[Sequence[str]] = None,
    ) -> Dict[str, PropertyMap]:
        app_id, channel_id = app_name_to_id(app_name, channel_name, self.storage)
        return self.storage.get_p_events().aggregate_properties(
            app_id=app_id,
            entity_type=entity_type,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            required=required,
        )

    def extract_entity_map(
        self,
        app_name: str,
        entity_type: str,
        mapper,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        required: Optional[Sequence[str]] = None,
    ):
        """Fold an entity type's property history into a typed
        :class:`~predictionio_tpu.data.entity_map.EntityMap` (reference
        PEvents.extractEntityMap, data/storage/PEvents.scala:73-102):
        aggregate ``$set/$unset/$delete``, drop entities missing a
        ``required`` property, and apply ``mapper(PropertyMap) -> A``.
        The resulting dense indices are what device kernels consume as
        factor/feature matrix rows."""
        from predictionio_tpu.data.entity_map import EntityMap

        props = self.aggregate_properties(
            app_name,
            entity_type=entity_type,
            channel_name=channel_name,
            start_time=start_time,
            until_time=until_time,
            required=required,
        )
        return EntityMap({eid: mapper(pm) for eid, pm in props.items()})

    # --- columnar view: events -> device-ready arrays ---

    def find_columns(
        self,
        app_name: str,
        value_of=None,
        entity_index: Optional[BiMap] = None,
        target_index: Optional[BiMap] = None,
        **find_kwargs,
    ) -> EventColumns:
        """Scan events and columnarize (entityId, targetEntityId, value).

        ``value_of(event) -> float`` extracts the numeric value (default:
        the ``rating`` property, else 1.0 — the implicit-feedback case).
        Events without a target entity are skipped. Existing BiMaps may be
        passed to keep indices aligned across scans (e.g. train vs eval).
        """
        events = [
            e
            for e in self.find(app_name, **find_kwargs)
            if e.target_entity_id is not None
        ]
        if value_of is None:
            def value_of(e: Event) -> float:
                return float(e.properties.get_or_else("rating", 1.0))

        if entity_index is None:
            entity_index = BiMap.string_int(e.entity_id for e in events)
        if target_index is None:
            target_index = BiMap.string_int(e.target_entity_id for e in events)
        kept = [
            e
            for e in events
            if e.entity_id in entity_index and e.target_entity_id in target_index
        ]
        entity_idx = np.fromiter(
            (entity_index[e.entity_id] for e in kept), np.int32, count=len(kept)
        )
        target_idx = np.fromiter(
            (target_index[e.target_entity_id] for e in kept), np.int32, count=len(kept)
        )
        values = np.fromiter(
            (value_of(e) for e in kept), np.float32, count=len(kept)
        )
        return EventColumns(
            entity_index=entity_index,
            target_index=target_index,
            entity_idx=entity_idx,
            target_idx=target_idx,
            values=values,
            events=kept,
        )


class LEventStore:
    """Serving-time entity reads (reference LEventStore.scala:146-230).

    The reference enforces a wall-clock timeout on these lookups because a
    slow HBase read stalls the serving hot path; the embedded backends here
    are local and fast, so the timeout parameter is accepted for parity and
    currently unenforced.
    """

    def __init__(self, storage: Optional[Storage] = None):
        self._storage = storage

    @property
    def storage(self) -> Storage:
        return self._storage or get_storage()

    def find_by_entity(
        self,
        app_name: str,
        entity_type: str,
        entity_id: str,
        channel_name: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: OptFilter = UNSET,
        target_entity_id: OptFilter = UNSET,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        limit: Optional[int] = None,
        latest: bool = True,
        timeout_seconds: float = 10.0,
    ) -> Iterator[Event]:
        app_id, channel_id = app_name_to_id(app_name, channel_name, self.storage)
        return self.storage.get_l_events().find(
            app_id=app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
            limit=limit,
            reversed=latest,
        )

    def find(
        self,
        app_name: str,
        channel_name: Optional[str] = None,
        timeout_seconds: float = 10.0,
        **find_kwargs,
    ) -> Iterator[Event]:
        app_id, channel_id = app_name_to_id(app_name, channel_name, self.storage)
        return self.storage.get_l_events().find(
            app_id=app_id, channel_id=channel_id, **find_kwargs
        )
