"""Event store access layer for engine developers.

Capability parity with the reference's store layer
(data/src/main/scala/io/prediction/data/store/): ``PEventStore``
(PEventStore.scala:30 — find + aggregateProperties by app *name*),
``LEventStore`` (LEventStore.scala:146 — findByEntity serving-time lookups
with timeout), and app-name/channel resolution (Common.scala:28-49).

Where the reference returns RDDs, the batch API here returns host lists
plus a columnar view (``EventColumns``) holding dense numpy id/value
columns with BiMap indexes — the form that `jax.device_put` moves straight
into HBM for kernel consumption (SURVEY.md §7 step 1).
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import threading
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.event import Event, PropertyMap
from predictionio_tpu.data.storage import Storage, get_storage
from predictionio_tpu.data.storage.base import UNSET, OptFilter


class AppNotFoundError(KeyError):
    pass


class ChannelNotFoundError(KeyError):
    pass


def app_name_to_id(
    app_name: str, channel_name: Optional[str] = None, storage: Optional[Storage] = None
) -> Tuple[int, Optional[int]]:
    """Resolve appName (+ optional channel) to ids
    (reference store/Common.scala:28-49)."""
    storage = storage or get_storage()
    app = storage.get_meta_data_apps().get_by_name(app_name)
    if app is None:
        raise AppNotFoundError(f"App {app_name!r} does not exist; use pio app new")
    channel_id: Optional[int] = None
    if channel_name is not None:
        channels = storage.get_meta_data_channels().get_by_app_id(app.id)
        match = [c for c in channels if c.name == channel_name]
        if not match:
            raise ChannelNotFoundError(
                f"Channel {channel_name!r} does not exist in app {app_name!r}"
            )
        channel_id = match[0].id
    return app.id, channel_id


@dataclasses.dataclass
class EventColumns:
    """Column-oriented batch of (entity, target, value) triples with dense
    indexes — the device-bound form of an event scan."""

    entity_index: BiMap  # entityId -> dense int
    target_index: BiMap  # targetEntityId -> dense int
    entity_idx: np.ndarray  # [n] int32
    target_idx: np.ndarray  # [n] int32
    values: np.ndarray  # [n] float32
    events: List[Event]  # originating events (host metadata)

    @property
    def n(self) -> int:
        return len(self.values)


class PEventStore:
    """Batch event reads by app name (reference PEventStore.scala:30-116)."""

    def __init__(self, storage: Optional[Storage] = None):
        self._storage = storage

    @property
    def storage(self) -> Storage:
        return self._storage or get_storage()

    def find(
        self,
        app_name: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: OptFilter = UNSET,
        target_entity_id: OptFilter = UNSET,
    ) -> Iterator[Event]:
        app_id, channel_id = app_name_to_id(app_name, channel_name, self.storage)
        return self.storage.get_p_events().find(
            app_id=app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
        )

    def aggregate_properties(
        self,
        app_name: str,
        entity_type: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        required: Optional[Sequence[str]] = None,
    ) -> Dict[str, PropertyMap]:
        app_id, channel_id = app_name_to_id(app_name, channel_name, self.storage)
        return self.storage.get_p_events().aggregate_properties(
            app_id=app_id,
            entity_type=entity_type,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            required=required,
        )

    def extract_entity_map(
        self,
        app_name: str,
        entity_type: str,
        mapper,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        required: Optional[Sequence[str]] = None,
    ):
        """Fold an entity type's property history into a typed
        :class:`~predictionio_tpu.data.entity_map.EntityMap` (reference
        PEvents.extractEntityMap, data/storage/PEvents.scala:73-102):
        aggregate ``$set/$unset/$delete``, drop entities missing a
        ``required`` property, and apply ``mapper(PropertyMap) -> A``.
        The resulting dense indices are what device kernels consume as
        factor/feature matrix rows."""
        from predictionio_tpu.data.entity_map import EntityMap

        props = self.aggregate_properties(
            app_name,
            entity_type=entity_type,
            channel_name=channel_name,
            start_time=start_time,
            until_time=until_time,
            required=required,
        )
        return EntityMap({eid: mapper(pm) for eid, pm in props.items()})

    # --- columnar view: events -> device-ready arrays ---

    _NATIVE_FILTERS = frozenset(
        (
            "channel_name", "start_time", "until_time", "entity_type",
            "target_entity_type", "event_names",
        )
    )

    def find_columns(
        self,
        app_name: str,
        value_of=None,
        entity_index: Optional[BiMap] = None,
        target_index: Optional[BiMap] = None,
        value_spec=None,
        **find_kwargs,
    ) -> EventColumns:
        """Scan events and columnarize (entityId, targetEntityId, value).

        The value rule is declarative by default (``value_spec``, a
        ``columnar.ValueSpec`` — property name, default, and per-event
        constant overrides like the recommendation template's buy->4.0),
        which lets the backend run its NATIVE columnar scan: binary page
        decode + SQL-evaluated residual on sqlite, packed columns over
        the wire on the http backend — no per-event Python objects
        (reference HBPEvents.scala:84-90's partitioned scan). On that
        path the returned ``events`` list is empty.

        Passing a ``value_of(event) -> float`` callable (or filters the
        native scan does not support, e.g. ``entity_id``) falls back to
        the per-event path, where ``events`` carries the scanned Events.
        Existing BiMaps may be passed to keep indices aligned across
        scans (e.g. train vs eval); both paths honor them and index
        distinct ids in sorted order.
        """
        from predictionio_tpu.data.storage.columnar import ValueSpec

        if value_of is None and set(find_kwargs) <= self._NATIVE_FILTERS:
            spec = value_spec or ValueSpec()
            kwargs = dict(find_kwargs)
            app_id, channel_id = app_name_to_id(
                app_name, kwargs.pop("channel_name", None), self.storage
            )
            cols = self.storage.get_p_events().find_columns_native(
                app_id=app_id,
                channel_id=channel_id,
                value_spec=spec,
                **kwargs,
            )
            if cols is not None:
                return self._from_columnar(cols, entity_index, target_index)

        events = [
            e
            for e in self.find(app_name, **find_kwargs)
            if e.target_entity_id is not None
        ]
        if value_of is None:
            spec = value_spec or ValueSpec()
            value_of = spec.value_of

        if entity_index is None:
            entity_index = BiMap.string_int(e.entity_id for e in events)
        if target_index is None:
            target_index = BiMap.string_int(e.target_entity_id for e in events)
        kept = [
            e
            for e in events
            if e.entity_id in entity_index and e.target_entity_id in target_index
        ]
        entity_idx = np.fromiter(
            (entity_index[e.entity_id] for e in kept), np.int32, count=len(kept)
        )
        target_idx = np.fromiter(
            (target_index[e.target_entity_id] for e in kept), np.int32, count=len(kept)
        )
        values = np.fromiter(
            (value_of(e) for e in kept), np.float32, count=len(kept)
        )
        return EventColumns(
            entity_index=entity_index,
            target_index=target_index,
            entity_idx=entity_idx,
            target_idx=target_idx,
            values=values,
            events=kept,
        )

    def stream_columns(
        self,
        app_name: str,
        value_spec=None,
        channel_name: Optional[str] = None,
        batch_rows: int = 1_048_576,
        **find_kwargs,
    ):
        """Chunked columnar scan for the streaming store→device training
        pipeline (``ops/streaming.py``): a ``columnar.ColumnarStream`` of
        batches in one shared code space, carrying the store's pre-scan
        fingerprint and a cache identity for the pack-artifact cache.

        Only the native filter set is streamable (the per-event fallback
        would defeat the point); backends without a chunked scan wrap the
        monolithic native scan in a one-batch stream, so callers keep one
        code path. Returns None when the filters need the per-event path
        or the backend has no native scan at all — callers fall back to
        ``find_columns`` + the materialized trainer.
        """
        from predictionio_tpu.data.storage.columnar import (
            ColumnarStream,
            ValueSpec,
        )

        native = self._NATIVE_FILTERS - {"channel_name"}
        if not set(find_kwargs) <= native:
            return None
        spec = value_spec or ValueSpec()
        app_id, channel_id = app_name_to_id(
            app_name, channel_name, self.storage
        )
        le = self.storage.get_p_events()
        key = (
            "stream", app_id, channel_id, spec,
            tuple(
                (k, tuple(v) if isinstance(v, (list, tuple)) else v)
                for k, v in sorted(find_kwargs.items())
            ),
        )
        stream = le.stream_columns_native(
            app_id=app_id, channel_id=channel_id, value_spec=spec,
            batch_rows=batch_rows, **find_kwargs,
        )
        if stream is None:
            # one-batch fallback: fingerprint read BEFORE the scan so a
            # cached artifact can never be labeled newer than its data
            fp = le.store_fingerprint(app_id, channel_id)
            cols = le.find_columns_native(
                app_id=app_id, channel_id=channel_id, value_spec=spec,
                **find_kwargs,
            )
            if cols is None:
                return None
            stream = ColumnarStream.from_columnar(cols, fingerprint=fp)

        def delta_factory(cursor):
            """Delta scan of the same app/filters from a prior scan's
            cursor (None when the backend has no delta path or the
            cursor no longer covers a clean prefix). The returned
            stream keeps this factory, so delta rounds chain."""
            dstream = le.stream_columns_delta(
                app_id=app_id, channel_id=channel_id, cursor=cursor,
                value_spec=spec, batch_rows=batch_rows, **find_kwargs,
            )
            if dstream is not None:
                dstream.cache_key = key
                dstream.cache_scope = le
                dstream.delta_factory = delta_factory
            return dstream

        stream.cache_key = key
        stream.cache_scope = le
        stream.delta_factory = delta_factory
        return stream

    @staticmethod
    def _from_columnar(
        cols,
        entity_index: Optional[BiMap],
        target_index: Optional[BiMap],
    ) -> EventColumns:
        """ColumnarEvents -> EventColumns: build BiMaps from the (sorted)
        name dictionaries, or remap onto caller-provided BiMaps with a
        vectorized lookup table, dropping rows with unknown ids."""

        def index_and_map(names, codes, provided: Optional[BiMap]):
            if provided is None:
                index = BiMap(
                    {str(n): j for j, n in enumerate(names)}
                )
                return index, codes, None
            lut = np.array(
                [provided.get(str(n), -1) for n in names], np.int32
            )
            mapped = lut[codes] if len(codes) else codes
            return provided, mapped, mapped >= 0

        e_index, e_idx, e_ok = index_and_map(
            cols.entity_names, cols.entity_codes, entity_index
        )
        t_index, t_idx, t_ok = index_and_map(
            cols.target_names, cols.target_codes, target_index
        )
        values = cols.values
        if e_ok is not None or t_ok is not None:
            keep = np.ones(len(values), bool)
            if e_ok is not None:
                keep &= e_ok
            if t_ok is not None:
                keep &= t_ok
            e_idx, t_idx, values = e_idx[keep], t_idx[keep], values[keep]
        return EventColumns(
            entity_index=e_index,
            target_index=t_index,
            entity_idx=e_idx.astype(np.int32),
            target_idx=t_idx.astype(np.int32),
            values=values.astype(np.float32),
            events=[],
        )


class _DaemonLookupPool:
    """Bounded pool of DAEMON worker threads for deadline-enforced
    serving lookups. A timed-out lookup's worker keeps running until the
    backend returns — with a fully stuck backend up to max_workers
    threads wedge and later lookups spend their deadline in the queue,
    still raising TimeoutError on schedule (the reference's Await.result
    behaves the same way: the HBase client call keeps running after the
    TimeoutException, LEventStore.scala:146-230). Daemon threads matter:
    concurrent.futures' workers are non-daemon and joined at interpreter
    exit, so one truly-stuck backend call would hang process shutdown
    forever."""

    def __init__(self, max_workers: int = 8):
        import queue

        self._q: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self._spawned = 0
        self._max = max_workers

    def _worker(self) -> None:
        while True:
            fn, box, done = self._q.get()
            try:
                box["result"] = fn()
            except BaseException as e:  # delivered to the caller
                box["error"] = e
            done.set()

    def submit(self, fn):
        with self._lock:
            if self._spawned < self._max:
                self._spawned += 1
                threading.Thread(
                    target=self._worker,
                    daemon=True,
                    name=f"levents-{self._spawned}",
                ).start()
        box: dict = {}
        done = threading.Event()
        self._q.put((fn, box, done))
        return box, done


_LOOKUP_POOL = _DaemonLookupPool(max_workers=8)


def _with_deadline(fn, timeout_seconds: Optional[float]):
    """Run ``fn`` under a wall-clock deadline; raises TimeoutError.
    ``timeout_seconds`` of None/0/negative means no deadline (inline)."""
    if not timeout_seconds or timeout_seconds <= 0:
        return fn()
    box, done = _LOOKUP_POOL.submit(fn)
    if not done.wait(timeout_seconds):
        raise TimeoutError(
            f"LEventStore lookup exceeded {timeout_seconds}s; a slow "
            "backend must not stall the serving hot path"
        )
    if "error" in box:
        raise box["error"]
    return box["result"]


class LEventStore:
    """Serving-time entity reads (reference LEventStore.scala:146-230).

    The wall-clock ``timeout_seconds`` is ENFORCED (round 4): with the
    ``http`` storage backend in the loop a slow gateway can stall the
    serving hot path, exactly the failure the reference's
    Await.result(timeout) guards against. The lookup materializes on a
    worker thread and raises ``TimeoutError`` past the deadline; serving
    engines catch it and degrade (e.g. ecommerce's rule reads fall back
    to empty sets). Pass ``timeout_seconds=None`` (or <= 0) to run
    inline without a deadline.
    """

    def __init__(self, storage: Optional[Storage] = None):
        self._storage = storage

    @property
    def storage(self) -> Storage:
        return self._storage or get_storage()

    def find_by_entity(
        self,
        app_name: str,
        entity_type: str,
        entity_id: str,
        channel_name: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: OptFilter = UNSET,
        target_entity_id: OptFilter = UNSET,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        limit: Optional[int] = None,
        latest: bool = True,
        timeout_seconds: Optional[float] = 10.0,
    ) -> Iterator[Event]:
        def lookup() -> List[Event]:
            app_id, channel_id = app_name_to_id(
                app_name, channel_name, self.storage
            )
            # materialize inside the deadline: the backend may hand back
            # a lazy iterator whose cost lands on first next()
            return list(
                self.storage.get_l_events().find(
                    app_id=app_id,
                    channel_id=channel_id,
                    start_time=start_time,
                    until_time=until_time,
                    entity_type=entity_type,
                    entity_id=entity_id,
                    event_names=event_names,
                    target_entity_type=target_entity_type,
                    target_entity_id=target_entity_id,
                    limit=limit,
                    reversed=latest,
                )
            )

        return iter(_with_deadline(lookup, timeout_seconds))

    def find(
        self,
        app_name: str,
        channel_name: Optional[str] = None,
        timeout_seconds: Optional[float] = 10.0,
        **find_kwargs,
    ) -> Iterator[Event]:
        def lookup() -> List[Event]:
            app_id, channel_id = app_name_to_id(
                app_name, channel_name, self.storage
            )
            return list(
                self.storage.get_l_events().find(
                    app_id=app_id, channel_id=channel_id, **find_kwargs
                )
            )

        return iter(_with_deadline(lookup, timeout_seconds))
