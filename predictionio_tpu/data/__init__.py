"""Event data layer: event model, property maps, storage backends, stores.

Mirrors the capability of the reference's ``data`` module
(data/src/main/scala/io/prediction/data) — event model + validation, property
aggregation, pluggable storage, event-store access APIs, and the Event Server
REST API — redesigned for a single-controller Python/JAX runtime.
"""

from predictionio_tpu.data.event import (
    DataMap,
    Event,
    EventValidationError,
    PropertyMap,
    validate_event,
)
from predictionio_tpu.data.bimap import BiMap

__all__ = [
    "BiMap",
    "DataMap",
    "Event",
    "EventValidationError",
    "PropertyMap",
    "validate_event",
]
