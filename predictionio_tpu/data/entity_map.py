"""EntityMap: dense-indexed entity data.

Capability parity with the reference EntityIdIxMap/EntityMap
(data/src/main/scala/io/prediction/data/storage/EntityMap.scala:23-98):
a BiMap of entity id -> dense index, optionally carrying per-entity data.
The dense index is what device kernels consume (rows of a factor or
feature matrix); the map translates between the string-id world of the
event store and array coordinates.
"""

from __future__ import annotations

from typing import Any, Dict, Generic, Iterable, Mapping, Optional, TypeVar

from predictionio_tpu.data.bimap import BiMap

A = TypeVar("A")


class EntityIdIxMap:
    """String id <-> dense index (reference EntityIdIxMap :23-52)."""

    def __init__(self, id_to_ix: BiMap):
        self.id_to_ix = id_to_ix
        self.ix_to_id = id_to_ix.inverse()

    @classmethod
    def from_keys(cls, keys: Iterable[str]) -> "EntityIdIxMap":
        return cls(BiMap.string_long(keys))

    def __getitem__(self, id_or_ix):
        if isinstance(id_or_ix, str):
            return self.id_to_ix[id_or_ix]
        return self.ix_to_id[id_or_ix]

    def __contains__(self, id_or_ix) -> bool:
        if isinstance(id_or_ix, str):
            return id_or_ix in self.id_to_ix
        return id_or_ix in self.ix_to_id

    def get(self, id_or_ix, default=None):
        if isinstance(id_or_ix, str):
            return self.id_to_ix.get(id_or_ix, default)
        return self.ix_to_id.get(id_or_ix, default)

    def to_map(self) -> Dict[str, int]:
        return self.id_to_ix.to_dict()

    def __len__(self) -> int:
        return len(self.id_to_ix)

    def take(self, n: int) -> "EntityIdIxMap":
        return EntityIdIxMap(self.id_to_ix.take(n))

    def __repr__(self) -> str:
        return f"EntityIdIxMap({self.id_to_ix!r})"


class EntityMap(EntityIdIxMap, Generic[A]):
    """EntityIdIxMap + per-entity payload (reference EntityMap :60-98)."""

    def __init__(
        self,
        id_to_data: Mapping[str, A],
        id_to_ix: Optional[BiMap] = None,
    ):
        super().__init__(
            id_to_ix
            if id_to_ix is not None
            else BiMap.string_long(id_to_data.keys())
        )
        self.id_to_data: Dict[str, A] = dict(id_to_data)

    def data(self, id_or_ix) -> A:
        if isinstance(id_or_ix, str):
            return self.id_to_data[id_or_ix]
        return self.id_to_data[self.ix_to_id[id_or_ix]]

    def get_data(self, id_or_ix, default: Any = None):
        try:
            return self.data(id_or_ix)
        except KeyError:
            return default

    def take(self, n: int) -> "EntityMap[A]":
        new_ix = self.id_to_ix.take(n)
        return EntityMap(
            {k: v for k, v in self.id_to_data.items() if k in new_ix},
            new_ix,
        )

    def __repr__(self) -> str:
        return f"EntityMap({len(self)} entities)"
