"""Engine: the concrete DASE composition with train/eval orchestration.

Capability parity with the reference Engine
(core/src/main/scala/io/prediction/controller/Engine.scala): class maps per
DASE slot (:80), instance ``train`` (:154) delegating to the static train
pipeline (:621-708 — read -> sanityCheck -> prepare -> per-algorithm train,
with stop-after-read/prepare interruptions :662-686), ``eval`` (:311 ->
:726-816 — per-fold train, supplement queries, per-algorithm batch predict,
regroup per query, serve), ``prepare_deploy`` (:196-265 — re-train when the
persisted form is absent, PersistentModel loading), and engine.json ->
EngineParams extraction (:353-416).

EngineParams / SimpleEngine mirror controller/EngineParams.scala:32-149.
"""

from __future__ import annotations

import dataclasses
import json
import logging
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple, Type

from predictionio_tpu.controller.base import (
    BaseAlgorithm,
    BaseDataSource,
    BasePreparator,
    BaseServing,
    FirstServing,
    IdentityPreparator,
    SanityCheck,
    doer,
)
from predictionio_tpu.controller.params import (
    EmptyParams,
    Params,
    params_from_json,
    params_to_json,
)

logger = logging.getLogger(__name__)


import contextlib


@contextlib.contextmanager
def _null_phase(name):
    yield


class StopAfterReadInterruption(Exception):
    """--stop-after-read debug stop (reference WorkflowUtils.scala:410)."""


class StopAfterPrepareInterruption(Exception):
    """--stop-after-prepare debug stop (reference WorkflowUtils.scala:412)."""


@dataclasses.dataclass(frozen=True)
class EngineParams:
    """Named (name, params) per DASE slot + ordered algorithm list
    (reference controller/EngineParams.scala:32)."""

    data_source_params: Tuple[str, Params] = ("", EmptyParams())
    preparator_params: Tuple[str, Params] = ("", EmptyParams())
    algorithm_params_list: Tuple[Tuple[str, Params], ...] = ()
    serving_params: Tuple[str, Params] = ("", EmptyParams())

    def __post_init__(self):
        object.__setattr__(
            self, "algorithm_params_list", tuple(self.algorithm_params_list)
        )

    def to_json(self) -> Dict[str, Any]:
        return {
            "datasource": {
                "name": self.data_source_params[0],
                "params": params_to_json(self.data_source_params[1]),
            },
            "preparator": {
                "name": self.preparator_params[0],
                "params": params_to_json(self.preparator_params[1]),
            },
            "algorithms": [
                {"name": n, "params": params_to_json(p)}
                for n, p in self.algorithm_params_list
            ],
            "serving": {
                "name": self.serving_params[0],
                "params": params_to_json(self.serving_params[1]),
            },
        }


def _multi_host() -> bool:
    try:
        import jax

        return jax.process_count() > 1
    except Exception:  # backend not initializable — single host
        return False


def _run_grid(
    items: Sequence[Any], fn, workflow_params, collective_free: bool = False
) -> List[Any]:
    """Map fn over grid items, in order, with a thread pool when
    workflow_params.eval_parallelism > 1.

    On a multi-host runtime the grid runs serially UNLESS the caller
    attests ``collective_free``: by default each item's train issues
    collective device programs over the multi-process mesh, and JAX
    multi-controller semantics require every process to enqueue the same
    collectives in the same order — thread scheduling would reorder them
    differently per host and deadlock the pod. FastEvalEngine lifts this
    by training the whole grid in ONE batched program first (order-safe
    by construction) and passing collective_free=True for the remaining
    per-variant host stages — the `.par` the reference runs regardless of
    cluster shape (MetricEvaluator.scala:221-230)."""
    items = list(items)
    workers = getattr(workflow_params, "eval_parallelism", 1) or 1
    workers = min(int(workers), len(items))
    if workers > 1 and not collective_free and _multi_host():
        logger.info(
            "multi-host run: evaluating the grid serially (collective "
            "order must match across hosts; eval_parallelism ignored)"
        )
        workers = 1
    elif workers > 1 and collective_free and _multi_host():
        # deterministic marker the two-process gate asserts on
        logger.info(
            "multi-host grid: thread-parallel over %d items "
            "(collective-free serving)", len(items),
        )
    if workers <= 1 or len(items) <= 1:
        return [fn(x) for x in items]
    import concurrent.futures

    with concurrent.futures.ThreadPoolExecutor(max_workers=workers) as pool:
        return list(pool.map(fn, items))


def _as_class_map(classes) -> Dict[str, type]:
    """A single class becomes the default-name map (reference's implicit
    ``Map("" -> cls)`` helpers, Engine.scala:512-575)."""
    if classes is None:
        return {}
    if isinstance(classes, Mapping):
        return dict(classes)
    return {"": classes}


class BaseEngine:
    """Abstract engine (reference core/BaseEngine.scala:35-100)."""

    def train(self, ctx, engine_params: EngineParams, workflow_params) -> List[Any]:
        raise NotImplementedError

    def eval(
        self, ctx, engine_params: EngineParams, workflow_params
    ) -> List[Tuple[Any, List[Tuple[Any, Any, Any]]]]:
        raise NotImplementedError

    def batch_eval(
        self, ctx, engine_params_list: Sequence[EngineParams], workflow_params
    ) -> List[Tuple[EngineParams, List[Tuple[Any, List[Tuple[Any, Any, Any]]]]]]:
        """Eval over the params grid, concurrently when
        workflow_params.eval_parallelism > 1 (the reference's `.par` over
        param sets, MetricEvaluator.scala:221-230; here a thread pool —
        device programs serialize on the chip but each variant's host
        stages overlap the others' device time). Results keep grid order.
        """
        return _run_grid(
            engine_params_list,
            lambda ep: (ep, self.eval(ctx, ep, workflow_params)),
            workflow_params,
        )

    def jvalue_to_engine_params(self, json_obj: Mapping[str, Any]) -> EngineParams:
        raise NotImplementedError


class Engine(BaseEngine):
    """The concrete 4-map engine (reference controller/Engine.scala:80)."""

    def __init__(
        self,
        data_source_classes,
        preparator_classes=None,
        algorithm_classes=None,
        serving_classes=None,
    ):
        self.data_source_class_map = _as_class_map(data_source_classes)
        self.preparator_class_map = _as_class_map(
            preparator_classes if preparator_classes is not None else IdentityPreparator
        )
        self.algorithm_class_map = _as_class_map(algorithm_classes)
        self.serving_class_map = _as_class_map(
            serving_classes if serving_classes is not None else FirstServing
        )

    # --- component instantiation ---

    def _lookup(self, class_map: Dict[str, type], name: str, slot: str) -> type:
        if name not in class_map:
            if name == "" and len(class_map) == 1:
                # an unnamed params block resolves to the slot's only class
                return next(iter(class_map.values()))
            raise KeyError(
                f"{slot} class with name {name!r} is not defined; "
                f"available: {sorted(class_map)}"
            )
        return class_map[name]

    def make_components(self, engine_params: EngineParams):
        ds_name, ds_params = engine_params.data_source_params
        prep_name, prep_params = engine_params.preparator_params
        serv_name, serv_params = engine_params.serving_params
        data_source = doer(
            self._lookup(self.data_source_class_map, ds_name, "DataSource"), ds_params
        )
        preparator = doer(
            self._lookup(self.preparator_class_map, prep_name, "Preparator"),
            prep_params,
        )
        algorithms = [
            doer(self._lookup(self.algorithm_class_map, name, "Algorithm"), params)
            for name, params in engine_params.algorithm_params_list
        ]
        if not algorithms:
            raise ValueError("EngineParams defines no algorithms")
        serving = doer(
            self._lookup(self.serving_class_map, serv_name, "Serving"), serv_params
        )
        return data_source, preparator, algorithms, serving

    # --- training pipeline (reference object Engine.train :621-708) ---

    def train(self, ctx, engine_params: EngineParams, workflow_params) -> List[Any]:
        data_source, preparator, algorithms, _ = self.make_components(engine_params)
        return self._train_pipeline(
            ctx, data_source, preparator, algorithms, workflow_params
        )

    @staticmethod
    def _sanity(obj: Any, label: str, workflow_params) -> None:
        if getattr(workflow_params, "skip_sanity_check", False):
            return
        if isinstance(obj, SanityCheck):
            logger.info("%s: performing data sanity check", label)
            obj.sanity_check()

    def _train_pipeline(
        self, ctx, data_source, preparator, algorithms, workflow_params
    ) -> List[Any]:
        timer = getattr(ctx, "timer", None)
        phase = timer.phase if timer is not None else _null_phase
        with phase("read"):
            td = data_source.read_training(ctx)
        self._sanity(td, "TrainingData", workflow_params)
        if getattr(workflow_params, "stop_after_read", False):
            raise StopAfterReadInterruption()
        with phase("prepare"):
            pd = preparator.prepare(ctx, td)
        self._sanity(pd, "PreparedData", workflow_params)
        if getattr(workflow_params, "stop_after_prepare", False):
            raise StopAfterPrepareInterruption()
        models = []
        for i, algo in enumerate(algorithms):
            with phase(f"train[{i}]:{type(algo).__name__}"):
                model = algo.train(ctx, pd)
            self._sanity(model, f"Model of algorithm[{i}]", workflow_params)
            models.append(model)
        return models

    # --- evaluation pipeline (reference object Engine.eval :726-816) ---

    @staticmethod
    def serve_fold(algorithms, models, serving, qa_pairs) -> List[Tuple[Any, Any, Any]]:
        """Supplement queries, batch-predict per algorithm, regroup per
        query index, serve (reference union + groupByKey + serve
        :786-810). Shared by Engine.eval and FastEvalEngineWorkflow."""
        queries = [(qx, serving.supplement(q)) for qx, (q, _) in enumerate(qa_pairs)]
        per_query: Dict[int, List[Any]] = {qx: [] for qx, _ in queries}
        for algo, model in zip(algorithms, models):
            for qx, p in algo.batch_predict(model, queries):
                per_query[qx].append(p)
        return [
            (q, serving.serve(q, per_query[qx]), a)
            for qx, (q, a) in enumerate(qa_pairs)
        ]

    def eval(
        self, ctx, engine_params: EngineParams, workflow_params
    ) -> List[Tuple[Any, List[Tuple[Any, Any, Any]]]]:
        data_source, preparator, algorithms, serving = self.make_components(
            engine_params
        )
        eval_sets = data_source.read_eval(ctx)
        out = []
        for td, eval_info, qa_pairs in eval_sets:
            pd = preparator.prepare(ctx, td)
            models = [algo.train(ctx, pd) for algo in algorithms]
            qpa = self.serve_fold(algorithms, models, serving, qa_pairs)
            out.append((eval_info, qpa))
        return out

    # --- deploy-time model restoration (reference prepareDeploy :196-265) ---

    def prepare_deploy(
        self,
        ctx,
        engine_params: EngineParams,
        engine_instance_id: str,
        persisted_models: List[Any],
        workflow_params,
    ) -> List[Any]:
        from predictionio_tpu.controller.persistent_model import (
            PersistentModelManifest,
            load_persistent_model,
        )

        _, _, algorithms, _ = self.make_components(engine_params)
        if len(persisted_models) != len(algorithms):
            raise ValueError(
                f"persisted {len(persisted_models)} models for "
                f"{len(algorithms)} algorithms"
            )
        pd = None
        if any(m is None for m in persisted_models):
            # sharded/unserialized models are re-trained on deploy
            # (reference Engine.scala:208-230)
            logger.info("some persisted models are absent; re-training for deploy")
            data_source, preparator, _, _ = self.make_components(engine_params)
            td = data_source.read_training(ctx)
            pd = preparator.prepare(ctx, td)
        out = []
        for algo, m in zip(algorithms, persisted_models):
            if m is None:
                out.append(algo.train(ctx, pd))
            elif isinstance(m, PersistentModelManifest):
                # manifests load in EVERY deploy path — a mixed engine
                # (one re-training algorithm + one persistent-model
                # algorithm) must not hand the raw manifest to serving
                out.append(
                    load_persistent_model(
                        m, engine_instance_id, algo.params, ctx
                    )
                )
            else:
                out.append(m)
        # serving-resource attachment (e.g. the device mesh for
        # data-parallel top-N) — runs for every deploy path
        return [
            algo.prepare_serving(ctx, m)
            for algo, m in zip(algorithms, out)
        ]

    def make_serializable_models(
        self, ctx, engine_instance_id: str, engine_params: EngineParams,
        models: List[Any],
    ) -> List[Any]:
        """Convert trained models to their persisted form
        (reference makeSerializableModels :282-300): PersistentModel ->
        save + manifest; sharded models that opt out -> None (re-trained on
        deploy); everything else passes through for pickling."""
        from predictionio_tpu.controller.persistent_model import (
            PersistentModel,
            PersistentModelManifest,
        )

        _, _, algorithms, _ = self.make_components(engine_params)
        out = []
        for algo, model in zip(algorithms, models):
            if isinstance(model, PersistentModel):
                saved = model.save(engine_instance_id, algo.params, ctx)
                out.append(
                    PersistentModelManifest(type(model).__module__ + "." + type(model).__qualname__)
                    if saved
                    else model
                )
            elif algo.sharded_model:
                out.append(None)
            else:
                out.append(model)
        return out

    # --- engine.json -> EngineParams (reference :353-416) ---

    def _params_for(
        self, class_map: Dict[str, type], block: Optional[Mapping[str, Any]], slot: str
    ) -> Tuple[str, Params]:
        block = block or {}
        name = block.get("name", "")
        cls = self._lookup(class_map, name, slot)
        params_cls = getattr(cls, "params_class", None)
        raw = block.get("params") or {}
        if params_cls is None:
            if raw:
                logger.warning(
                    "%s %s has no params_class; wrapping raw JSON params — "
                    "declare `params_class` on %s for typed params",
                    slot, cls.__name__, cls.__name__,
                )
                return name, _DictParams(dict(raw))
            return name, EmptyParams()
        return name, params_from_json(raw, params_cls)

    def engine_instance_to_engine_params(self, instance) -> EngineParams:
        """Rebuild EngineParams from the params JSONs stored on a trained
        EngineInstance record (reference engineInstanceToEngineParams,
        Engine.scala:418-488)."""
        return self.jvalue_to_engine_params(
            {
                "datasource": json.loads(instance.data_source_params or "null"),
                "preparator": json.loads(instance.preparator_params or "null"),
                "algorithms": json.loads(instance.algorithms_params or "[]"),
                "serving": json.loads(instance.serving_params or "null"),
            }
        )

    def jvalue_to_engine_params(self, json_obj: Mapping[str, Any]) -> EngineParams:
        algo_blocks = json_obj.get("algorithms") or []
        algorithm_params_list = []
        for block in algo_blocks:
            name, p = self._params_for(self.algorithm_class_map, block, "Algorithm")
            algorithm_params_list.append((name, p))
        if not algorithm_params_list:
            # engine.json may omit algorithms when the engine defines exactly one
            if len(self.algorithm_class_map) == 1:
                only = next(iter(self.algorithm_class_map))
                name, p = self._params_for(
                    self.algorithm_class_map, {"name": only}, "Algorithm"
                )
                algorithm_params_list = [(name, p)]
        return EngineParams(
            data_source_params=self._params_for(
                self.data_source_class_map, json_obj.get("datasource"), "DataSource"
            ),
            preparator_params=self._params_for(
                self.preparator_class_map, json_obj.get("preparator"), "Preparator"
            ),
            algorithm_params_list=tuple(algorithm_params_list),
            serving_params=self._params_for(
                self.serving_class_map, json_obj.get("serving"), "Serving"
            ),
        )


@dataclasses.dataclass(frozen=True)
class _DictParams(Params):
    """Fallback params wrapper for components that declare no params_class
    but receive a JSON params block. Serializes back to the raw dict so
    train-store-deploy round trips don't double-wrap."""

    values: Any = dataclasses.field(default_factory=dict)

    def to_json(self):
        return dict(self.values)


class SimpleEngine(Engine):
    """1 algorithm + identity preparator + first serving
    (reference controller/EngineParams.scala:127)."""

    def __init__(self, data_source_class, algorithm_class):
        super().__init__(
            data_source_classes=data_source_class,
            preparator_classes=IdentityPreparator,
            algorithm_classes=algorithm_class,
            serving_classes=FirstServing,
        )


@dataclasses.dataclass(frozen=True)
class SimpleEngineParams:
    """Sugar mirroring reference SimpleEngineParams :141."""

    data_source_params: Params = EmptyParams()
    algorithm_params: Params = EmptyParams()

    def to_engine_params(self) -> EngineParams:
        return EngineParams(
            data_source_params=("", self.data_source_params),
            algorithm_params_list=(("", self.algorithm_params),),
        )


class EngineFactory:
    """User object returning an Engine (reference controller/EngineFactory.scala:24-37).

    Subclass and implement ``apply()``; optionally override
    ``engine_params(key)`` for params-by-key lookup.
    """

    def apply(self) -> BaseEngine:
        raise NotImplementedError

    def engine_params(self, key: str) -> EngineParams:
        raise KeyError(f"engine params key {key!r} is not defined")


class Deployment(EngineFactory):
    """EngineFactory variant wrapping a set-once engine (reference
    controller/Deployment.scala:27-56): assign ``deployment.engine = e``
    once — typically in a module-level object an engine.json points its
    ``engineFactory`` at — and ``apply()`` serves it. Re-assignment
    raises, mirroring the reference's assert-guarded setter."""

    def __init__(self, engine: Optional[BaseEngine] = None):
        self._engine: Optional[BaseEngine] = None
        if engine is not None:
            self.engine = engine

    @property
    def engine(self) -> BaseEngine:
        if self._engine is None:
            raise ValueError("Deployment's engine is not set")
        return self._engine

    @engine.setter
    def engine(self, value: BaseEngine) -> None:
        if self._engine is not None:
            raise ValueError("Deployment's engine can only be set once")
        self._engine = value

    def apply(self) -> BaseEngine:
        return self.engine


def engine_params_from_file(engine: BaseEngine, path: str) -> EngineParams:
    """Load an engine.json variant file into EngineParams."""
    with open(path) as f:
        variant = json.load(f)
    return engine.jvalue_to_engine_params(variant)
