"""DASE base abstractions: DataSource / Preparator / Algorithm / Serving.

Capability parity with the reference's type-erased core
(core/src/main/scala/io/prediction/core/BaseDataSource.scala:31,
BasePreparator.scala:32, BaseAlgorithm.scala:55, BaseServing.scala:28,
BaseEngine.scala:35) and the typed controller variants
(controller/{PDataSource,LDataSource,PPreparator,LPreparator,
P2LAlgorithm,PAlgorithm,LAlgorithm,LServing}.scala).

Design divergence, deliberate: the reference needs a P (distributed-model) /
P2L (distributed-train, local-model) / L (local) split because Spark
distinguishes RDD-resident from driver-resident values. JAX erases that
split — a model is a pytree whose leaves may be host numpy arrays or
device-sharded jax.Arrays; the same class covers all three cases. The
``sharded_model`` flag records intent (whether leaves should live sharded in
HBM across the mesh) and decides persistence handling.

Components receive a WorkflowContext (the SparkContext analog carrying
storage + the device mesh) in their lifecycle methods.
"""

from __future__ import annotations

import abc
import inspect
from typing import Any, Generic, List, Optional, Sequence, Tuple, TypeVar

from predictionio_tpu.annotation import developer_api
from predictionio_tpu.controller.params import EmptyParams, Params

TD = TypeVar("TD")  # training data
EI = TypeVar("EI")  # evaluation info
PD = TypeVar("PD")  # prepared data
M = TypeVar("M")  # model
Q = TypeVar("Q")  # query
P = TypeVar("P")  # predicted result
A = TypeVar("A")  # actual result


class SanityCheck(abc.ABC):
    """Data-validation hook (reference controller/SanityCheck.scala:30).
    Implement on TrainingData/PreparedData/models; the workflow invokes
    ``sanity_check()`` after each stage unless skipped."""

    @abc.abstractmethod
    def sanity_check(self) -> None: ...


@developer_api  # reference core/AbstractDoer.scala:25
def doer(cls, params: Optional[Params] = None):
    """Instantiate a controller class with (params) or zero-arg constructor
    (reference Doer.apply, core/AbstractDoer.scala:33-66). The instance's
    params are always available as ``self.params``."""
    params = params if params is not None else EmptyParams()
    # an EmptyParams slot (EngineParams default) upgrades to the class's
    # declared params defaults, mirroring Controller.__init__
    if isinstance(params, EmptyParams) and getattr(cls, "params_class", None):
        params = cls.params_class()
    try:
        sig = inspect.signature(cls.__init__)
        takes_params = any(n != "self" for n in sig.parameters)
    except (TypeError, ValueError):
        takes_params = True
    if takes_params:
        obj = cls(params)
    else:
        obj = cls()
        if not isinstance(getattr(obj, "params", None), Params) or isinstance(
            getattr(obj, "params", None), EmptyParams
        ):
            obj.params = params
    return obj


class Controller:
    """Common base: every DASE component may take a Params in its
    constructor; ``self.params`` is always set (by the ctor or by doer).
    A declared ``params_class`` supplies the default (all-defaults)
    instance when none is given."""

    params_class: Optional[type] = None

    def __init__(self, params: Optional[Params] = None):
        if params is not None:
            self.params = params
        elif type(self).params_class is not None:
            self.params = type(self).params_class()
        else:
            self.params = EmptyParams()


class BaseDataSource(Controller, Generic[TD, EI, Q, A]):
    """Reads training / evaluation data from the event store
    (reference core/BaseDataSource.scala:31-52)."""

    def read_training(self, ctx) -> TD:
        raise NotImplementedError

    def read_eval(self, ctx) -> List[Tuple[TD, EI, List[Tuple[Q, A]]]]:
        """Return evaluation folds: (training data, eval info, (query,
        actual) pairs). Default: no eval data (reference PDataSource
        readEval default)."""
        return []


class BasePreparator(Controller, Generic[TD, PD]):
    """Transforms TrainingData into PreparedData
    (reference core/BasePreparator.scala:32-42)."""

    def prepare(self, ctx, training_data: TD) -> PD:
        raise NotImplementedError


class IdentityPreparator(BasePreparator[TD, TD]):
    """Pass-through preparator (reference controller/IdentityPreparator.scala:30-92)."""

    def prepare(self, ctx, training_data: TD) -> TD:
        return training_data


class BaseAlgorithm(Controller, Generic[PD, M, Q, P]):
    """Trains a model and predicts (reference core/BaseAlgorithm.scala:55-123).

    ``sharded_model=True`` declares that model leaves live device-sharded
    across the mesh (the reference's PAlgorithm role); such models are
    re-materialized at deploy rather than naively serialized, unless the
    model implements PersistentModel.
    """

    sharded_model: bool = False

    # Param field names allowed to differ between variants that train
    # TOGETHER in one batched device program (see train_grid). Empty =
    # this algorithm has no device-side grid path; the eval grid falls
    # back to thread-parallel per-variant training.
    GRID_AXES: Tuple[str, ...] = ()

    # Whether predict/batch_predict dispatches device programs over a
    # multi-process mesh. False (every current algorithm: serving runs
    # local single-device programs) lets a fully grid-pretrained
    # multi-host evaluation thread-parallelize its serving stages; an
    # algorithm that serves THROUGH mesh collectives must set True so
    # the multi-host grid keeps its collective-order-safe serialization
    # (controller/engine.py _run_grid).
    MESH_SERVING: bool = False

    def train(self, ctx, prepared_data: PD) -> M:
        raise NotImplementedError

    @classmethod
    def train_grid(
        cls, ctx, prepared_data: PD, algos: Sequence["BaseAlgorithm"]
    ) -> Optional[List[M]]:
        """Train several param-variants of this algorithm in ONE batched
        device program, returning one model per entry of ``algos`` (same
        order), or None when these variants can't be batched (the caller
        falls back to per-variant ``train``). Called by the FastEval grid
        with variants whose params differ only in ``GRID_AXES`` fields.

        No reference analog: the reference's grid parallelism is host
        threads (`.par`, MetricEvaluator.scala:221-230). On TPU, a
        vmapped train amortizes dispatch and batches the per-variant
        math onto the MXU — see ops/als.py train_als_grid."""
        return None

    def predict(self, model: M, query: Q) -> P:
        raise NotImplementedError

    def batch_predict(self, model: M, queries: Sequence[Tuple[int, Q]]) -> List[Tuple[int, P]]:
        """Predict for indexed queries (reference P2LAlgorithm.batchPredict
        default ``qs.mapValues(predict)``, P2LAlgorithm.scala:66). Override
        with a vectorized device predict for the TPU fast path."""
        return [(i, self.predict(model, q)) for i, q in queries]

    def prepare_serving(self, ctx, model: M) -> M:
        """Deploy-time hook between model resolution and warm-up
        (Engine.prepare_deploy calls it per algorithm): attach serving
        resources to the model — e.g. the workflow mesh, so top-N
        serving runs data-parallel over every attached device instead of
        chip 0 only. Default: model unchanged. No reference analog (one
        JVM, no accelerator topology to bind)."""
        return model

    def warm(self, model: M) -> None:
        """Deploy-time warm-up hook (no reference analog — JIT frameworks
        need it): compile the serving executables NOW so the first real
        queries don't pay multi-second cold-compile tail latency. Called
        once per algorithm when a DeployedEngine is constructed. Default:
        nothing."""

    def serving_precision(self, model: M) -> Optional[str]:
        """The residency precision ("float32"/"bf16"/"int8") the model's
        prepared serving state stores the catalog at, or None when no
        quantization-aware serving state exists (training-time predicts,
        or an engine without the retrieval tier). Surfaces in the engine
        server's status.json per deployed version. Default: None."""
        return None

    def release_serving(self, model: M) -> None:
        """Undeploy-time inverse of ``prepare_serving`` (no reference
        analog): free the device-resident serving state a displaced
        model holds, called by the promotion pipeline's drain→release
        step only after the model's last in-flight batch resolved
        (DeployedEngine.release). CONTRACT: a query racing past the
        release must still be servable — implementations null the
        device-state fields so predict falls back to the host
        (training-time) path instead of erroring. Default: nothing."""

    # --- query class resolution (reference queryClass via TypeResolver) ---

    def query_from_json(self, json_obj: Any) -> Q:
        """Build a query from a JSON payload. Default: if the class declares
        a ``query_class`` dataclass, construct it; otherwise pass the raw
        dict through."""
        qcls = getattr(self, "query_class", None)
        if qcls is not None:
            from predictionio_tpu.controller.params import params_from_json

            return params_from_json(json_obj, qcls)
        return json_obj

    def result_to_json(self, result: P) -> Any:
        """Serialize a predicted result to JSON. Dataclasses serialize
        field-wise; other values must be JSON-compatible already."""
        import dataclasses

        if dataclasses.is_dataclass(result) and not isinstance(result, type):
            return dataclasses.asdict(result)
        return result


class BaseServing(Controller, Generic[Q, P]):
    """Combines per-algorithm predictions into the served result
    (reference core/BaseServing.scala:28-51)."""

    def supplement(self, query: Q) -> Q:
        """Pre-process the query (default identity, LServing.scala:31-52)."""
        return query

    def serve(self, query: Q, predictions: Sequence[P]) -> P:
        raise NotImplementedError


class LServing(BaseServing[Q, P]):
    """Alias kept for reference-parity naming."""


class FirstServing(BaseServing[Q, P]):
    """Serves the first algorithm's prediction
    (reference controller/LFirstServing.scala:24-39)."""

    def serve(self, query: Q, predictions: Sequence[P]) -> P:
        return predictions[0]


class AverageServing(BaseServing[Q, float]):
    """Averages numeric predictions
    (reference controller/LAverageServing.scala:24-41)."""

    def serve(self, query: Q, predictions: Sequence[float]) -> float:
        return sum(predictions) / len(predictions)


# reference-parity aliases: the P/P2L/L split collapses in JAX (see module
# docstring); these names exist so engine code reads like the reference's.
PDataSource = BaseDataSource
LDataSource = BaseDataSource
PPreparator = BasePreparator
LPreparator = BasePreparator
P2LAlgorithm = BaseAlgorithm
LAlgorithm = BaseAlgorithm


class PAlgorithm(BaseAlgorithm[PD, M, Q, P]):
    """Algorithm whose model is device-sharded across the mesh
    (reference controller/PAlgorithm.scala:44)."""

    sharded_model = True
