"""FastEvalEngine: eval-time stage memoization for grid search.

Capability parity with reference controller/FastEvalEngine.scala:309-343 and
FastEvalEngineWorkflow (:86-298): during ``batch_eval`` over a params grid,
stage results are cached keyed by the params *prefix* — data-source reads by
data-source params; prepared data by (datasource, preparator); trained
models by (datasource, preparator, algorithms); served eval results by the
full tuple — so a grid varying only algorithm params reads and prepares the
data once. A natural fit for the TPU runtime: the cached prepared data is
typically device-resident and stays in HBM across the sweep.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import threading
from typing import Any, Callable, Dict, List, Sequence, Tuple

from predictionio_tpu.annotation import experimental
from predictionio_tpu.controller.engine import Engine, EngineParams
from predictionio_tpu.controller.params import Params, params_to_json

logger = logging.getLogger(__name__)


def _key_of(pairs: Sequence[Tuple[str, Params]]) -> str:
    return json.dumps(
        [[name, params_to_json(p)] for name, p in pairs], sort_keys=True, default=str
    )


@experimental  # reference FastEvalEngine.scala:282
class FastEvalEngineWorkflow:
    """Holds the per-stage caches (reference FastEvalEngineWorkflow:295-298)."""

    def __init__(self, engine: "FastEvalEngine", ctx, workflow_params):
        self.engine = engine
        self.ctx = ctx
        self.workflow_params = workflow_params
        self.data_source_cache: Dict[str, Any] = {}
        self.preparator_cache: Dict[str, Any] = {}
        self.algorithms_cache: Dict[str, Any] = {}
        self.serving_cache: Dict[str, Any] = {}
        # Concurrent grid variants sharing a params-prefix must compute the
        # cached stage exactly once: a per-(cache, key) build lock makes the
        # second variant wait for the first's result instead of duplicating
        # an expensive train/prepare (memoization is the whole point here).
        self._guard = threading.Lock()
        self._build_locks: Dict[Tuple[int, str], threading.Lock] = {}

    def _memo(self, cache: Dict[str, Any], key: str, build: Callable[[], Any]) -> Any:
        if key in cache:
            return cache[key]
        with self._guard:
            lock = self._build_locks.setdefault((id(cache), key), threading.Lock())
        with lock:
            if key not in cache:
                cache[key] = build()
        return cache[key]

    # --- stage getters (reference :86-278) ---

    def get_eval_sets(self, ds_pair: Tuple[str, Params]):
        def build():
            from predictionio_tpu.controller.base import doer

            cls = self.engine._lookup(
                self.engine.data_source_class_map, ds_pair[0], "DataSource"
            )
            return doer(cls, ds_pair[1]).read_eval(self.ctx)

        return self._memo(self.data_source_cache, _key_of([ds_pair]), build)

    def get_prepared(self, ds_pair, prep_pair):
        def build():
            from predictionio_tpu.controller.base import doer

            cls = self.engine._lookup(
                self.engine.preparator_class_map, prep_pair[0], "Preparator"
            )
            prep = doer(cls, prep_pair[1])
            eval_sets = self.get_eval_sets(ds_pair)
            return [
                (prep.prepare(self.ctx, td), ei, qa) for td, ei, qa in eval_sets
            ]

        return self._memo(
            self.preparator_cache, _key_of([ds_pair, prep_pair]), build
        )

    def get_models(self, ds_pair, prep_pair, algo_list):
        def build():
            from predictionio_tpu.controller.base import doer

            algos = [
                doer(
                    self.engine._lookup(
                        self.engine.algorithm_class_map, name, "Algorithm"
                    ),
                    p,
                )
                for name, p in algo_list
            ]
            prepared = self.get_prepared(ds_pair, prep_pair)
            return [
                [algo.train(self.ctx, pd) for algo in algos]
                for pd, _, _ in prepared
            ]

        return self._memo(
            self.algorithms_cache,
            _key_of([ds_pair, prep_pair] + list(algo_list)),
            build,
        )

    def prefill_grid_models(
        self, engine_params_list: Sequence[EngineParams]
    ) -> int:
        """Device-side grid training: single-algorithm variants whose
        params differ only in the algorithm's GRID_AXES fields train
        together in one batched program (BaseAlgorithm.train_grid), and
        the per-variant models seed algorithms_cache so get_models is a
        cache hit. Returns the number of variants trained this way.

        Anything that doesn't group (multi-algo engines, differing
        non-axis params, an algorithm without a grid path) is left for
        the thread-parallel fallback in batch_eval."""
        from predictionio_tpu.controller.base import doer

        # value validated by WorkflowParams.__post_init__
        mode = getattr(self.workflow_params, "grid_train", "auto")
        if mode == "never":
            return 0
        if mode == "auto":
            import jax

            if jax.default_backend() == "cpu" and jax.process_count() == 1:
                # CPU dispatch is cheap and the vmapped program serializes
                # the variants anyway — measured slower than per-variant
                # trains with shared (bucketed-shape) executables. On a
                # MULTI-HOST runtime the grid runs regardless of backend:
                # one batched program is collective-order-safe by
                # construction, which is what lets batch_eval lift the
                # per-variant serialization (reference `.par` parity,
                # MetricEvaluator.scala:221-230)
                return 0

        # group by (ds, prep, algo name, params-with-axes-normalized)
        groups: Dict[Tuple, List[EngineParams]] = {}
        defaults_by_class: Dict[type, Any] = {}
        for ep in engine_params_list:
            if len(ep.algorithm_params_list) != 1:
                continue
            name, params = ep.algorithm_params_list[0]
            try:
                cls = self.engine._lookup(
                    self.engine.algorithm_class_map, name, "Algorithm"
                )
            except (KeyError, ValueError):
                continue
            axes = getattr(cls, "GRID_AXES", ())
            if not axes or not dataclasses.is_dataclass(params):
                continue
            fields = {f.name for f in dataclasses.fields(params)}
            if not all(a in fields for a in axes):
                continue
            pcls = type(params)
            if pcls not in defaults_by_class:
                try:
                    defaults_by_class[pcls] = pcls()
                except TypeError:
                    # params class with required fields can't provide
                    # neutral axis values — skip grouping, don't crash
                    defaults_by_class[pcls] = None
            default_params = defaults_by_class[pcls]
            if default_params is None:
                continue
            normalized = dataclasses.replace(
                params, **{a: getattr(default_params, a, None) for a in axes}
            )
            key = (
                _key_of([ep.data_source_params, ep.preparator_params]),
                name,
                _key_of([("", normalized)]),
            )
            groups.setdefault(key, []).append(ep)

        def grid_one_group(item) -> int:
            (_, name, _), eps = item
            # dedup variants whose FULL algo params match (they share a
            # cache entry anyway)
            unique: Dict[str, EngineParams] = {}
            for ep in eps:
                unique.setdefault(self._models_key(ep), ep)
            eps = list(unique.values())
            if len(eps) < 2:
                return 0
            cls = self.engine._lookup(
                self.engine.algorithm_class_map, name, "Algorithm"
            )
            algos = [
                doer(cls, ep.algorithm_params_list[0][1]) for ep in eps
            ]
            prepared = self.get_prepared(
                eps[0].data_source_params, eps[0].preparator_params
            )
            fold_models = []  # [fold][variant]
            for pd, _, _ in prepared:
                try:
                    models = cls.train_grid(self.ctx, pd, algos)
                except Exception:
                    # a failed batched train (e.g. the vmapped program
                    # OOMs where serial variants would fit) must fall
                    # back, not abort the evaluation
                    logger.warning(
                        "train_grid failed for %s; falling back to "
                        "per-variant training", cls.__name__, exc_info=True,
                    )
                    return 0
                if models is None or len(models) != len(algos):
                    return 0
                fold_models.append(models)
            for v, ep in enumerate(eps):
                self.algorithms_cache[self._models_key(ep)] = [
                    [models[v]] for models in fold_models
                ]
            return len(eps)

        # groups (e.g. the rank-8 and rank-16 halves of a grid) run
        # concurrently: their XLA compiles release the GIL and overlap
        from predictionio_tpu.controller.engine import _run_grid

        n_gridded = sum(
            _run_grid(list(groups.items()), grid_one_group, self.workflow_params)
        )
        if n_gridded:
            logger.info(
                "FastEval: %d grid variants trained device-side (vmapped)",
                n_gridded,
            )
        return n_gridded

    def _models_key(self, ep: EngineParams) -> str:
        return _key_of(
            [ep.data_source_params, ep.preparator_params]
            + list(ep.algorithm_params_list)
        )

    def get_results(self, engine_params: EngineParams):
        ds_pair = engine_params.data_source_params
        prep_pair = engine_params.preparator_params
        algo_list = list(engine_params.algorithm_params_list)
        serv_pair = engine_params.serving_params
        def build():
            from predictionio_tpu.controller.base import doer

            algos = [
                doer(
                    self.engine._lookup(
                        self.engine.algorithm_class_map, name, "Algorithm"
                    ),
                    p,
                )
                for name, p in algo_list
            ]
            serving = doer(
                self.engine._lookup(
                    self.engine.serving_class_map, serv_pair[0], "Serving"
                ),
                serv_pair[1],
            )
            prepared = self.get_prepared(ds_pair, prep_pair)
            fold_models = self.get_models(ds_pair, prep_pair, algo_list)
            out = []
            for (pd, eval_info, qa_pairs), models in zip(prepared, fold_models):
                qpa = Engine.serve_fold(algos, models, serving, qa_pairs)
                out.append((eval_info, qpa))
            return out

        return self._memo(
            self.serving_cache,
            _key_of([ds_pair, prep_pair] + algo_list + [serv_pair]),
            build,
        )


@experimental  # reference FastEvalEngine.scala:309
class FastEvalEngine(Engine):
    """Engine whose batch_eval memoizes shared params-prefixes
    (reference FastEvalEngine.scala:309-343)."""

    def batch_eval(
        self, ctx, engine_params_list: Sequence[EngineParams], workflow_params
    ):
        from predictionio_tpu.controller.engine import _run_grid

        workflow = FastEvalEngineWorkflow(self, ctx, workflow_params)
        # device-side grid pass first: variants differing only in an
        # algorithm's GRID_AXES train in one vmapped program; whatever
        # it can't batch runs through the thread-parallel fallback below
        workflow.prefill_grid_models(engine_params_list)
        # when the grid pass covered EVERY variant AND no algorithm
        # serves through mesh collectives (MESH_SERVING), the remaining
        # map is serving/metric host work plus local-device programs —
        # no multi-process collectives — so the multi-host serialization
        # (collective ordering) no longer applies and threads are safe
        def _serving_meshless(ep: EngineParams) -> bool:
            for name, _ in ep.algorithm_params_list:
                try:
                    cls = self._lookup(
                        self.algorithm_class_map, name, "Algorithm"
                    )
                except (KeyError, ValueError):
                    return False
                if getattr(cls, "MESH_SERVING", False):
                    return False
            return True

        collective_free = all(
            workflow._models_key(ep) in workflow.algorithms_cache
            and _serving_meshless(ep)
            for ep in engine_params_list
        )
        return _run_grid(
            engine_params_list,
            lambda ep: (ep, workflow.get_results(ep)),
            workflow_params,
            collective_free=collective_free,
        )
