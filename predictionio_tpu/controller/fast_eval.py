"""FastEvalEngine: eval-time stage memoization for grid search.

Capability parity with reference controller/FastEvalEngine.scala:309-343 and
FastEvalEngineWorkflow (:86-298): during ``batch_eval`` over a params grid,
stage results are cached keyed by the params *prefix* — data-source reads by
data-source params; prepared data by (datasource, preparator); trained
models by (datasource, preparator, algorithms); served eval results by the
full tuple — so a grid varying only algorithm params reads and prepares the
data once. A natural fit for the TPU runtime: the cached prepared data is
typically device-resident and stays in HBM across the sweep.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Callable, Dict, List, Sequence, Tuple

from predictionio_tpu.annotation import experimental
from predictionio_tpu.controller.engine import Engine, EngineParams
from predictionio_tpu.controller.params import Params, params_to_json


def _key_of(pairs: Sequence[Tuple[str, Params]]) -> str:
    return json.dumps(
        [[name, params_to_json(p)] for name, p in pairs], sort_keys=True, default=str
    )


@experimental  # reference FastEvalEngine.scala:282
class FastEvalEngineWorkflow:
    """Holds the per-stage caches (reference FastEvalEngineWorkflow:295-298)."""

    def __init__(self, engine: "FastEvalEngine", ctx, workflow_params):
        self.engine = engine
        self.ctx = ctx
        self.workflow_params = workflow_params
        self.data_source_cache: Dict[str, Any] = {}
        self.preparator_cache: Dict[str, Any] = {}
        self.algorithms_cache: Dict[str, Any] = {}
        self.serving_cache: Dict[str, Any] = {}
        # Concurrent grid variants sharing a params-prefix must compute the
        # cached stage exactly once: a per-(cache, key) build lock makes the
        # second variant wait for the first's result instead of duplicating
        # an expensive train/prepare (memoization is the whole point here).
        self._guard = threading.Lock()
        self._build_locks: Dict[Tuple[int, str], threading.Lock] = {}

    def _memo(self, cache: Dict[str, Any], key: str, build: Callable[[], Any]) -> Any:
        if key in cache:
            return cache[key]
        with self._guard:
            lock = self._build_locks.setdefault((id(cache), key), threading.Lock())
        with lock:
            if key not in cache:
                cache[key] = build()
        return cache[key]

    # --- stage getters (reference :86-278) ---

    def get_eval_sets(self, ds_pair: Tuple[str, Params]):
        def build():
            from predictionio_tpu.controller.base import doer

            cls = self.engine._lookup(
                self.engine.data_source_class_map, ds_pair[0], "DataSource"
            )
            return doer(cls, ds_pair[1]).read_eval(self.ctx)

        return self._memo(self.data_source_cache, _key_of([ds_pair]), build)

    def get_prepared(self, ds_pair, prep_pair):
        def build():
            from predictionio_tpu.controller.base import doer

            cls = self.engine._lookup(
                self.engine.preparator_class_map, prep_pair[0], "Preparator"
            )
            prep = doer(cls, prep_pair[1])
            eval_sets = self.get_eval_sets(ds_pair)
            return [
                (prep.prepare(self.ctx, td), ei, qa) for td, ei, qa in eval_sets
            ]

        return self._memo(
            self.preparator_cache, _key_of([ds_pair, prep_pair]), build
        )

    def get_models(self, ds_pair, prep_pair, algo_list):
        def build():
            from predictionio_tpu.controller.base import doer

            algos = [
                doer(
                    self.engine._lookup(
                        self.engine.algorithm_class_map, name, "Algorithm"
                    ),
                    p,
                )
                for name, p in algo_list
            ]
            prepared = self.get_prepared(ds_pair, prep_pair)
            return [
                [algo.train(self.ctx, pd) for algo in algos]
                for pd, _, _ in prepared
            ]

        return self._memo(
            self.algorithms_cache,
            _key_of([ds_pair, prep_pair] + list(algo_list)),
            build,
        )

    def get_results(self, engine_params: EngineParams):
        ds_pair = engine_params.data_source_params
        prep_pair = engine_params.preparator_params
        algo_list = list(engine_params.algorithm_params_list)
        serv_pair = engine_params.serving_params
        def build():
            from predictionio_tpu.controller.base import doer

            algos = [
                doer(
                    self.engine._lookup(
                        self.engine.algorithm_class_map, name, "Algorithm"
                    ),
                    p,
                )
                for name, p in algo_list
            ]
            serving = doer(
                self.engine._lookup(
                    self.engine.serving_class_map, serv_pair[0], "Serving"
                ),
                serv_pair[1],
            )
            prepared = self.get_prepared(ds_pair, prep_pair)
            fold_models = self.get_models(ds_pair, prep_pair, algo_list)
            out = []
            for (pd, eval_info, qa_pairs), models in zip(prepared, fold_models):
                qpa = Engine.serve_fold(algos, models, serving, qa_pairs)
                out.append((eval_info, qpa))
            return out

        return self._memo(
            self.serving_cache,
            _key_of([ds_pair, prep_pair] + algo_list + [serv_pair]),
            build,
        )


@experimental  # reference FastEvalEngine.scala:309
class FastEvalEngine(Engine):
    """Engine whose batch_eval memoizes shared params-prefixes
    (reference FastEvalEngine.scala:309-343)."""

    def batch_eval(
        self, ctx, engine_params_list: Sequence[EngineParams], workflow_params
    ):
        from predictionio_tpu.controller.engine import _run_grid

        workflow = FastEvalEngineWorkflow(self, ctx, workflow_params)
        return _run_grid(
            engine_params_list,
            lambda ep: (ep, workflow.get_results(ep)),
            workflow_params,
        )
