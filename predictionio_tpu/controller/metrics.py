"""Metric family for evaluation.

Capability parity with reference controller/Metric.scala: the Metric base
(:36-58 — header, calculate over an eval data set, ordering-based compare),
AverageMetric (:96), OptionAverageMetric (:121), StdevMetric (:148),
OptionStdevMetric (:173), SumMetric (:202), ZeroMetric (:231), and the
QPAMetric trait (:251). The reference computes one-pass stats with Spark
StatCounter over RDD unions (:60-94); here scores are computed on host from
the (Q, P, A) triples the engine eval produced — per-point math heavy
enough to matter (e.g. ranking metrics over device arrays) belongs inside
``calculate_point`` which is free to call jitted code.
"""

from __future__ import annotations

import math
from typing import Any, Generic, List, Optional, Sequence, Tuple, TypeVar

EI = TypeVar("EI")
Q = TypeVar("Q")
P = TypeVar("P")
A = TypeVar("A")
R = TypeVar("R")

EvalDataSet = Sequence[Tuple[EI, Sequence[Tuple[Q, P, A]]]]


class Metric(Generic[EI, Q, P, A, R]):
    """Base metric. ``compare`` uses natural ordering by default; override
    ``is_larger_better`` (or ``compare``) for inverted metrics."""

    is_larger_better: bool = True

    @property
    def header(self) -> str:
        return type(self).__name__

    def calculate(self, ctx, eval_data_set: EvalDataSet) -> R:
        raise NotImplementedError

    def compare(self, r0: R, r1: R) -> int:
        key0, key1 = self._key(r0), self._key(r1)
        if key0 == key1:
            return 0
        better = key0 > key1 if self.is_larger_better else key0 < key1
        return 1 if better else -1

    @staticmethod
    def _key(r):
        return (-math.inf if r is None else r)

    def __str__(self) -> str:
        return self.header


class QPAMetric(Metric[EI, Q, P, A, R]):
    """Marker for metrics defined point-wise over (Q, P, A) triples
    (reference QPAMetric trait, Metric.scala:251)."""

    def calculate_point(self, query: Q, predicted: P, actual: A) -> Any:
        raise NotImplementedError


def _all_points(eval_data_set: EvalDataSet):
    for _, qpa in eval_data_set:
        for q, p, a in qpa:
            yield q, p, a


class AverageMetric(QPAMetric[EI, Q, P, A, float]):
    """Mean of per-point scores across all folds (reference :96-120)."""

    def calculate(self, ctx, eval_data_set: EvalDataSet) -> float:
        scores = [
            float(self.calculate_point(q, p, a))
            for q, p, a in _all_points(eval_data_set)
        ]
        return sum(scores) / len(scores) if scores else float("nan")


class OptionAverageMetric(QPAMetric[EI, Q, P, A, float]):
    """Mean of per-point scores, None excluded (reference :121-147)."""

    def calculate(self, ctx, eval_data_set: EvalDataSet) -> float:
        scores = [
            float(s)
            for q, p, a in _all_points(eval_data_set)
            if (s := self.calculate_point(q, p, a)) is not None
        ]
        return sum(scores) / len(scores) if scores else float("nan")


def _stdev(scores: List[float]) -> float:
    # population stdev, matching Spark StatCounter.stdev
    if not scores:
        return float("nan")
    mean = sum(scores) / len(scores)
    return math.sqrt(sum((s - mean) ** 2 for s in scores) / len(scores))


class StdevMetric(QPAMetric[EI, Q, P, A, float]):
    """Population stdev of per-point scores (reference :148-172)."""

    is_larger_better = False

    def calculate(self, ctx, eval_data_set: EvalDataSet) -> float:
        return _stdev(
            [float(self.calculate_point(q, p, a)) for q, p, a in _all_points(eval_data_set)]
        )


class OptionStdevMetric(QPAMetric[EI, Q, P, A, float]):
    """Population stdev, None excluded (reference :173-201)."""

    is_larger_better = False

    def calculate(self, ctx, eval_data_set: EvalDataSet) -> float:
        return _stdev(
            [
                float(s)
                for q, p, a in _all_points(eval_data_set)
                if (s := self.calculate_point(q, p, a)) is not None
            ]
        )


class SumMetric(QPAMetric[EI, Q, P, A, float]):
    """Sum of per-point scores (reference :202-230)."""

    def calculate(self, ctx, eval_data_set: EvalDataSet) -> float:
        return float(
            sum(float(self.calculate_point(q, p, a)) for q, p, a in _all_points(eval_data_set))
        )


class ZeroMetric(Metric[EI, Q, P, A, float]):
    """Always returns 0 — placeholder metric (reference :231-249)."""

    def calculate(self, ctx, eval_data_set: EvalDataSet) -> float:
        return 0.0
