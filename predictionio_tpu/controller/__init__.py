"""DASE controller API — the engine developer's surface.

Capability parity with the reference's ``controller`` package
(core/src/main/scala/io/prediction/controller/): DataSource / Preparator /
Algorithm / Serving bases and variants, Engine + EngineParams + factories,
Params JSON construction, the Metric family, MetricEvaluator, Evaluation,
FastEvalEngine, and PersistentModel.
"""

from predictionio_tpu.controller.base import (
    AverageServing,
    BaseAlgorithm,
    BaseDataSource,
    BasePreparator,
    BaseServing,
    Controller,
    FirstServing,
    IdentityPreparator,
    LAlgorithm,
    LDataSource,
    LPreparator,
    LServing,
    P2LAlgorithm,
    PAlgorithm,
    PDataSource,
    PPreparator,
    SanityCheck,
    doer,
)
from predictionio_tpu.controller.engine import (
    BaseEngine,
    Deployment,
    Engine,
    EngineFactory,
    EngineParams,
    SimpleEngine,
    SimpleEngineParams,
    StopAfterPrepareInterruption,
    StopAfterReadInterruption,
    engine_params_from_file,
)
from predictionio_tpu.controller.evaluation import (
    BaseEvaluator,
    BaseEvaluatorResult,
    EngineParamsGenerator,
    Evaluation,
    MetricEvaluator,
    MetricEvaluatorResult,
    MetricScores,
)
from predictionio_tpu.controller.fast_eval import FastEvalEngine
from predictionio_tpu.controller.metrics import (
    AverageMetric,
    Metric,
    OptionAverageMetric,
    OptionStdevMetric,
    QPAMetric,
    StdevMetric,
    SumMetric,
    ZeroMetric,
)
from predictionio_tpu.controller.params import (
    EmptyParams,
    Params,
    ParamsError,
    params_from_json,
    params_to_json,
    params_to_json_string,
)
from predictionio_tpu.controller.persistent_model import (
    LocalFileSystemPersistentModel,
    PersistentModel,
    PersistentModelManifest,
)

__all__ = [name for name in dir() if not name.startswith("_")]
