"""Evaluation, BaseEvaluator, MetricEvaluator, EngineParamsGenerator.

Capability parity with reference controller/Evaluation.scala:34-122,
core/BaseEvaluator.scala:37-72, controller/MetricEvaluator.scala (grid
scoring :215-260, best-params pick :243-248, one-liner/HTML/JSON rendering
:72-107, best-variant engine.json output :188-210), and
controller/EngineParamsGenerator.scala:26-43.

The reference parallelizes the per-EngineParams metric computation with
Scala ``.par`` collections (:221-230); here the per-params scoring is a
host loop — each iteration's heavy math is already vectorized device
compute inside Metric.calculate / Engine.eval.
"""

from __future__ import annotations

import dataclasses
import html as _html
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

from predictionio_tpu.controller.engine import BaseEngine, EngineParams
from predictionio_tpu.controller.metrics import Metric, ZeroMetric


class BaseEvaluatorResult:
    """Result contract (reference BaseEvaluator.scala:54-72)."""

    no_save: bool = False

    def to_one_liner(self) -> str:
        return ""

    def to_html(self) -> str:
        return ""

    def to_json(self) -> str:
        return ""


class BaseEvaluator:
    """Evaluates engine outputs over a params grid
    (reference core/BaseEvaluator.scala:37)."""

    def evaluate_base(
        self,
        ctx,
        evaluation: "Evaluation",
        engine_eval_data_set: Sequence[Tuple[EngineParams, Any]],
        workflow_params,
    ) -> BaseEvaluatorResult:
        raise NotImplementedError


@dataclasses.dataclass
class MetricScores:
    score: Any
    other_scores: List[Any]


@dataclasses.dataclass
class MetricEvaluatorResult(BaseEvaluatorResult):
    """reference MetricEvaluatorResult (MetricEvaluator.scala:62-107)."""

    best_score: MetricScores = None
    best_engine_params: EngineParams = None
    best_idx: int = 0
    metric_header: str = ""
    other_metric_headers: List[str] = dataclasses.field(default_factory=list)
    engine_params_scores: List[Tuple[EngineParams, MetricScores]] = dataclasses.field(
        default_factory=list
    )

    def to_one_liner(self) -> str:
        return f"[{self.metric_header}] {self.best_score.score}"

    def to_json(self) -> str:
        return json.dumps(
            {
                "metricHeader": self.metric_header,
                "otherMetricHeaders": self.other_metric_headers,
                "bestIdx": self.best_idx,
                "bestScore": self.best_score.score,
                "bestOtherScores": self.best_score.other_scores,
                "bestEngineParams": self.best_engine_params.to_json(),
                "engineParamsScores": [
                    {
                        "engineParams": ep.to_json(),
                        "score": ms.score,
                        "otherScores": ms.other_scores,
                    }
                    for ep, ms in self.engine_params_scores
                ],
            },
            default=str,
        )

    def to_html(self) -> str:
        rows = "".join(
            "<tr><td>{}</td><td>{}</td><td><pre>{}</pre></td></tr>".format(
                _html.escape(str(ms.score)),
                _html.escape(str(ms.other_scores)),
                _html.escape(json.dumps(ep.to_json(), indent=2, default=str)),
            )
            for ep, ms in self.engine_params_scores
        )
        return (
            "<h2>Metric: {}</h2><p>Best score: {}</p>"
            "<table border=1><tr><th>{}</th><th>{}</th><th>Engine Params</th></tr>"
            "{}</table>".format(
                _html.escape(self.metric_header),
                _html.escape(str(self.best_score.score)),
                _html.escape(self.metric_header),
                _html.escape(str(self.other_metric_headers)),
                rows,
            )
        )


class MetricEvaluator(BaseEvaluator):
    """Default evaluator: score each EngineParams with a primary metric
    (+ optional others), pick the best (reference MetricEvaluator.scala)."""

    def __init__(
        self,
        metric: Metric,
        other_metrics: Sequence[Metric] = (),
        output_path: Optional[str] = None,
    ):
        self.metric = metric
        self.other_metrics = list(other_metrics)
        self.output_path = output_path

    def evaluate_base(
        self,
        ctx,
        evaluation: "Evaluation",
        engine_eval_data_set: Sequence[Tuple[EngineParams, Any]],
        workflow_params,
    ) -> MetricEvaluatorResult:
        if not engine_eval_data_set:
            raise ValueError("no engine params to evaluate")
        scores: List[Tuple[EngineParams, MetricScores]] = []
        for ep, eval_data_set in engine_eval_data_set:
            primary = self.metric.calculate(ctx, eval_data_set)
            others = [m.calculate(ctx, eval_data_set) for m in self.other_metrics]
            scores.append((ep, MetricScores(primary, others)))
        best_idx = 0
        for i in range(1, len(scores)):
            if self.metric.compare(scores[i][1].score, scores[best_idx][1].score) > 0:
                best_idx = i
        best_ep, best_ms = scores[best_idx]
        result = MetricEvaluatorResult(
            best_score=best_ms,
            best_engine_params=best_ep,
            best_idx=best_idx,
            metric_header=self.metric.header,
            other_metric_headers=[m.header for m in self.other_metrics],
            engine_params_scores=scores,
        )
        if self.output_path:
            # best-variant engine.json (reference saveEngineJson :188-210)
            with open(self.output_path, "w") as f:
                json.dump(best_ep.to_json(), f, indent=2, default=str)
        return result


class Evaluation:
    """Set-once (engine, evaluator) pair with metric sugar
    (reference controller/Evaluation.scala:34-122)."""

    def __init__(self):
        self._engine: Optional[BaseEngine] = None
        self._evaluator: Optional[BaseEvaluator] = None

    @property
    def engine(self) -> BaseEngine:
        if self._engine is None:
            raise ValueError("Evaluation's engine is not set")
        return self._engine

    @property
    def evaluator(self) -> BaseEvaluator:
        if self._evaluator is None:
            raise ValueError("Evaluation's evaluator is not set")
        return self._evaluator

    def _set_once(self, engine: BaseEngine, evaluator: BaseEvaluator) -> None:
        if self._engine is not None or self._evaluator is not None:
            raise ValueError("Evaluation can only be set once")
        self._engine = engine
        self._evaluator = evaluator

    # sugar (reference engineEvaluator= / engineMetric= / engineMetrics=)

    def set_engine_evaluator(self, engine: BaseEngine, evaluator: BaseEvaluator):
        self._set_once(engine, evaluator)
        return self

    def set_engine_metric(
        self, engine: BaseEngine, metric: Metric, output_path: Optional[str] = None
    ):
        self._set_once(engine, MetricEvaluator(metric, (), output_path))
        return self

    def set_engine_metrics(
        self,
        engine: BaseEngine,
        metric: Metric,
        other_metrics: Sequence[Metric] = (),
        output_path: Optional[str] = None,
    ):
        self._set_once(engine, MetricEvaluator(metric, other_metrics, output_path))
        return self


class EngineParamsGenerator:
    """Holds the params grid for tuning runs
    (reference controller/EngineParamsGenerator.scala:26-43)."""

    def __init__(self, engine_params_list: Optional[Sequence[EngineParams]] = None):
        self._list: Optional[List[EngineParams]] = (
            list(engine_params_list) if engine_params_list is not None else None
        )

    @property
    def engine_params_list(self) -> List[EngineParams]:
        if self._list is None:
            raise ValueError("EngineParamsGenerator's engineParamsList is not set")
        return self._list

    @engine_params_list.setter
    def engine_params_list(self, value: Sequence[EngineParams]) -> None:
        if self._list is not None:
            raise ValueError("engineParamsList can only be set once")
        self._list = list(value)
