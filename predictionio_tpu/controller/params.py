"""Params: typed controller parameters constructed from JSON.

Capability parity with the reference's Params marker trait
(core/src/main/scala/io/prediction/controller/Params.scala:22-31) and the
JSON->Params extraction machinery (workflow/JsonExtractor.scala:61-110,
WorkflowUtils.extractParams:131-161). The reference reflects on Scala
case-class constructors; here Params subclasses are Python dataclasses and
extraction maps JSON object fields onto dataclass fields with type-aware
coercion (nested dataclasses, Optional, lists, tuples).
"""

from __future__ import annotations

import dataclasses
import json
import types as _types
import typing
from typing import Any, Dict, Mapping, Optional, Type, TypeVar

T = TypeVar("T", bound="Params")


@dataclasses.dataclass(frozen=True)
class Params:
    """Base class for all controller parameters. Subclass as a (frozen or
    not) dataclass; fields define the JSON schema, exactly as the
    reference's case-class constructor args do."""


@dataclasses.dataclass(frozen=True)
class EmptyParams(Params):
    """No parameters (reference EmptyParams, Params.scala:29)."""


class ParamsError(ValueError):
    """Raised when JSON cannot be mapped onto a Params class."""


def _coerce(value: Any, annot: Any) -> Any:
    """Best-effort coercion of a JSON value to the annotated field type."""
    if annot is Any or annot is dataclasses.MISSING or annot is None:
        return value
    origin = typing.get_origin(annot)
    if origin is typing.Union or origin is _types.UnionType:  # Optional / X | Y
        args = [a for a in typing.get_args(annot) if a is not type(None)]
        if value is None:
            return None
        if len(args) == 1:
            return _coerce(value, args[0])
        return value
    if origin in (list, typing.List):
        (item,) = typing.get_args(annot) or (Any,)
        return [_coerce(v, item) for v in value]
    if origin in (tuple, typing.Tuple):
        args = typing.get_args(annot)
        if len(args) == 2 and args[1] is Ellipsis:
            return tuple(_coerce(v, args[0]) for v in value)
        return tuple(value)
    if origin in (dict, typing.Dict):
        kv = typing.get_args(annot)
        if len(kv) == 2:
            return {k: _coerce(v, kv[1]) for k, v in value.items()}
        return dict(value)
    if isinstance(annot, type):
        if dataclasses.is_dataclass(annot) and isinstance(value, Mapping):
            return params_from_json(value, annot)
        if annot is float and isinstance(value, int):
            return float(value)
        if annot is int and isinstance(value, float) and value.is_integer():
            return int(value)
        if annot is set:
            return set(value)
    return value


def params_from_json(obj: Optional[Mapping[str, Any]], params_cls: Type[T]) -> T:
    """Instantiate a Params dataclass from a JSON object.

    Unknown fields raise (the reference's json4s extraction is strict in
    the same way for missing required fields; unknown-field rejection is a
    deliberate tightening to catch engine.json typos early). Missing fields
    fall back to dataclass defaults; a missing non-defaulted field raises.
    """
    obj = dict(obj or {})
    if not dataclasses.is_dataclass(params_cls):
        raise ParamsError(
            f"{params_cls.__name__} must be a dataclass to be JSON-constructed"
        )
    hints = typing.get_type_hints(params_cls)
    fields = {f.name: f for f in dataclasses.fields(params_cls)}
    unknown = set(obj) - set(fields)
    if unknown:
        raise ParamsError(
            f"unknown parameter(s) {sorted(unknown)} for {params_cls.__name__}; "
            f"expected a subset of {sorted(fields)}"
        )
    kwargs: Dict[str, Any] = {}
    for name, f in fields.items():
        if name in obj:
            kwargs[name] = _coerce(obj[name], hints.get(name, Any))
        elif (
            f.default is dataclasses.MISSING
            and f.default_factory is dataclasses.MISSING  # type: ignore[misc]
        ):
            raise ParamsError(
                f"missing required parameter {name!r} for {params_cls.__name__}"
            )
    try:
        return params_cls(**kwargs)
    except TypeError as e:
        raise ParamsError(str(e)) from e


def params_to_json(params: Params) -> Dict[str, Any]:
    """Serialize a Params dataclass to a JSON-compatible dict
    (reference JsonExtractor.paramToJson:83-110). A Params subclass may
    override ``to_json()`` to control its wire form (e.g. the raw-dict
    fallback wrapper must round-trip transparently)."""
    custom = getattr(params, "to_json", None)
    if callable(custom):
        return custom()
    if not dataclasses.is_dataclass(params):
        raise ParamsError(f"{type(params).__name__} is not a dataclass")
    out = dataclasses.asdict(params)

    def clean(v):
        if isinstance(v, dict):
            return {k: clean(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [clean(x) for x in v]
        if isinstance(v, set):
            return sorted(clean(x) for x in v)
        return v

    return clean(out)


def params_to_json_string(params: Params) -> str:
    return json.dumps(params_to_json(params), sort_keys=True)
