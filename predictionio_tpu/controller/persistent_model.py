"""PersistentModel: manual model persistence contract.

Capability parity with reference controller/PersistentModel.scala:48-95 and
LocalFileSystemPersistentModel.scala:44-74. A model class opts into managing
its own persistence (e.g. writing factor shards as npz/orbax checkpoints)
instead of being pickled into the MODELDATA store; the workflow then stores
only a PersistentModelManifest and resolves the loader at deploy time
(reference SparkWorkflowUtils.getPersistentModel, WorkflowUtils.scala:349-383).
"""

from __future__ import annotations

import dataclasses
import importlib
import os

from predictionio_tpu.utils.fs import fs_basedir
import pickle
from typing import Any, Optional

from predictionio_tpu.controller.params import Params


@dataclasses.dataclass(frozen=True)
class PersistentModelManifest:
    """Stored in place of a manually-persisted model
    (reference workflow/PersistentModelManifest.scala:18)."""

    class_name: str


class PersistentModel:
    """Mixin: implement ``save``; provide a classmethod ``load``
    (the reference's companion-object PersistentModelLoader)."""

    def save(self, id: str, params: Params, ctx) -> bool:
        """Persist the model. Return False to fall back to default
        pickling (reference PersistentModel.scala:78-82)."""
        raise NotImplementedError

    @classmethod
    def load(cls, id: str, params: Params, ctx) -> "PersistentModel":
        raise NotImplementedError


def load_persistent_model(
    manifest: PersistentModelManifest, id: str, params: Params, ctx
) -> Any:
    """Resolve the model class from the manifest and call its loader.

    The manifest stores ``module.qualname``; qualname may itself contain
    dots (nested classes), so resolve by importing the longest importable
    module prefix and getattr-walking the remainder.
    """
    parts = manifest.class_name.split(".")
    module = None
    split_at = 0
    for i in range(len(parts) - 1, 0, -1):
        try:
            module = importlib.import_module(".".join(parts[:i]))
            split_at = i
            break
        except ImportError:
            continue
    if module is None:
        raise ImportError(
            f"cannot resolve persistent model class {manifest.class_name!r}"
        )
    cls: Any = module
    for part in parts[split_at:]:
        cls = getattr(cls, part)
    return cls.load(id, params, ctx)


def _local_model_dir() -> str:
    d = os.path.join(
        fs_basedir(),
        "pmodels",
    )
    os.makedirs(d, exist_ok=True)
    return d


class LocalFileSystemPersistentModel(PersistentModel):
    """Helper saving via pickle to the local FS
    (reference LocalFileSystemPersistentModel.scala:44-74; Utils.save/load
    controller/Utils.scala)."""

    def save(self, id: str, params: Params, ctx) -> bool:
        from predictionio_tpu.utils.serialize import to_host

        path = os.path.join(_local_model_dir(), f"{id}-{type(self).__name__}")
        with open(path, "wb") as f:
            pickle.dump(to_host(self), f, protocol=pickle.HIGHEST_PROTOCOL)
        return True

    @classmethod
    def load(cls, id: str, params: Params, ctx) -> "LocalFileSystemPersistentModel":
        path = os.path.join(_local_model_dir(), f"{id}-{cls.__name__}")
        with open(path, "rb") as f:
            return pickle.load(f)
