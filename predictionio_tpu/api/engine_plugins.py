"""Engine-server plugin framework.

Parity with the reference engine-server plugins
(core/src/main/scala/io/prediction/workflow/EngineServerPlugin.scala:22-40,
EngineServerPluginContext.scala:42-74, EngineServerPluginsActor.scala:28-46):
*output blockers* run synchronously over the outgoing prediction JSON and
may transform or replace it; *output sniffers* observe (engine instance,
query, prediction) triples asynchronously.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Sequence

from predictionio_tpu.api.plugin_base import AsyncNotifier, describe_plugins

logger = logging.getLogger(__name__)


class EngineServerPlugin:
    """Base plugin (reference EngineServerPlugin.scala:22-40)."""

    OUTPUT_BLOCKER = "outputblocker"
    OUTPUT_SNIFFER = "outputsniffer"

    plugin_name: str = "plugin"
    plugin_description: str = ""
    plugin_type: str = OUTPUT_SNIFFER

    def start(self, context: "EngineServerPluginContext") -> None:
        """Called once when the server starts."""

    def process(
        self, engine_instance, query_json: Any, result_json: Any, context
    ) -> Any:
        """Blockers return the (possibly transformed) result JSON;
        sniffers' return value is ignored."""
        return result_json

    def handle_rest(self, args: Sequence[str]) -> dict:
        return {}


class EngineServerPluginContext:
    """Registered plugins split by type, with per-plugin params from the
    ``plugins`` section of engine.json (reference
    EngineServerPluginContext.scala:42-74)."""

    def __init__(
        self,
        plugins: Sequence[EngineServerPlugin] = (),
        plugin_params: Optional[Dict[str, dict]] = None,
    ):
        self.output_blockers: Dict[str, EngineServerPlugin] = {}
        self.output_sniffers: Dict[str, EngineServerPlugin] = {}
        self.plugin_params: Dict[str, dict] = dict(plugin_params or {})
        for p in plugins:
            self.register(p)
        self._notifier = AsyncNotifier(self._deliver)

    @classmethod
    def discover(cls, plugin_params: Optional[Dict[str, dict]] = None):
        plugins: List[EngineServerPlugin] = []
        for sub in EngineServerPlugin.__subclasses__():
            try:
                plugins.append(sub())
            except Exception:
                logger.exception("plugin %s failed to instantiate", sub)
        ctx = cls(plugins, plugin_params)
        for p in plugins:
            p.start(ctx)
        return ctx

    def register(self, plugin: EngineServerPlugin) -> None:
        if plugin.plugin_type == EngineServerPlugin.OUTPUT_BLOCKER:
            self.output_blockers[plugin.plugin_name] = plugin
        else:
            self.output_sniffers[plugin.plugin_name] = plugin

    def describe(self) -> dict:
        """GET /plugins.json payload (reference CreateServer.scala:647-668)."""
        return {
            "plugins": {
                "outputblockers": describe_plugins(
                    self.output_blockers, self.plugin_params
                ),
                "outputsniffers": describe_plugins(
                    self.output_sniffers, self.plugin_params
                ),
            }
        }

    def run_blockers(self, engine_instance, query_json, result_json) -> Any:
        for p in self.output_blockers.values():
            result_json = p.process(engine_instance, query_json, result_json, self)
        return result_json

    def notify_sniffers(self, engine_instance, query_json, result_json) -> None:
        if not self.output_sniffers:
            return
        self._notifier.put((engine_instance, query_json, result_json))

    def _deliver(self, item: tuple) -> None:
        engine_instance, query_json, result_json = item
        for p in self.output_sniffers.values():
            try:
                p.process(engine_instance, query_json, result_json, self)
            except Exception:
                logger.exception("sniffer %s failed", p.plugin_name)
