"""Shared machinery for the two plugin frameworks (event server and
engine server): the async sniffer drain worker and plugin description
rendering. Both contexts split plugins into a synchronous "blocker" table
and an async "sniffer" table; only the process() arity differs.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Callable, Dict, Optional

logger = logging.getLogger(__name__)


class AsyncNotifier:
    """A single locked daemon worker draining notifications to a callback
    (the reference's PluginsActor mailbox)."""

    def __init__(self, deliver: Callable[[tuple], None]):
        self._deliver = deliver
        self._queue: "queue.Queue" = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def put(self, item: tuple) -> None:
        with self._lock:
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(target=self._drain, daemon=True)
                self._worker.start()
        self._queue.put(item)

    def _drain(self) -> None:
        while True:
            item = self._queue.get()
            try:
                self._deliver(item)
            except Exception:
                logger.exception("plugin notification delivery failed")


def describe_plugins(
    plugins: Dict[str, object],
    params: Optional[Dict[str, dict]] = None,
) -> Dict[str, dict]:
    """Render a plugin table for /plugins.json."""
    out = {}
    for name, p in plugins.items():
        entry = {
            "name": p.plugin_name,
            "description": p.plugin_description,
            "class": type(p).__module__ + "." + type(p).__qualname__,
        }
        if params is not None:
            entry["params"] = params.get(p.plugin_name, {})
        out[name] = entry
    return out
