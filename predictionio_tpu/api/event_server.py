"""The Event Server: REST event collection on :7070.

Capability parity with the reference EventServer
(data/src/main/scala/io/prediction/data/api/EventServer.scala:50-531):

  GET    /                      -> {"status": "alive"}
  GET    /plugins.json          -> registered plugin descriptions
  GET    /plugins/<type>/<name>/... -> plugin REST handler (auth)
  POST   /events.json           -> insert one event, 201 {"eventId"}
  POST   /batch/events.json     -> insert up to 50 events as ONE
                                   group-commit batch, 200 with a
                                   per-event status array (reference
                                   EventServer.scala:161-233)
  GET    /events.json           -> batch query (9 filters, default limit 20)
  GET    /events/<id>.json      -> one event or 404
  DELETE /events/<id>.json      -> {"message": "Found"} or 404
  GET    /stats.json            -> ingestion stats (requires stats=True)
  POST   /webhooks/<name>.json  -> JSON connector -> insert, 201
  GET    /webhooks/<name>.json  -> connector existence check
  POST   /webhooks/<name>       -> form connector -> insert, 201
  GET    /webhooks/<name>       -> connector existence check

Auth matches the reference (EventServer.scala:81-107): every data route
requires ?accessKey=...; an unknown key is 401, a missing key 401, an
invalid ?channel= name 400. The spray/akka actor stack is replaced by a
pure request core (`EventAPI.handle`) — unit-testable exactly like the
reference's spray-testkit route specs — plus a `ThreadingHTTPServer`
adapter (`EventServer`). Ingestion is purely host-side; the TPU only sees
event data later, as columnar batches from the store layer.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import json
import logging
import urllib.parse
import weakref
from typing import Any, Dict, Optional, Tuple

from predictionio_tpu.api.aio_http import TRANSPORTS, make_http_server

from predictionio_tpu.data.event import (
    Event,
    EventValidationError,
    parse_iso8601,
)
from predictionio_tpu.data.storage import Storage, get_storage
from predictionio_tpu.data.storage.base import (
    UNSET,
    PartialBatchError,
    StorageSaturatedError,
)
from predictionio_tpu.data.webhooks import (
    ConnectorException,
    to_event,
)
from predictionio_tpu.data.webhooks.example import (
    ExampleFormConnector,
    ExampleJsonConnector,
)
from predictionio_tpu.data.webhooks.mailchimp import MailChimpConnector
from predictionio_tpu.data.webhooks.segmentio import SegmentIOConnector
from predictionio_tpu.api.plugins import EventServerPlugin, EventServerPluginContext
from predictionio_tpu.api.stats import StatsTracker
from predictionio_tpu.utils import health as _health
from predictionio_tpu.utils import metrics as _metrics
from predictionio_tpu.utils import tracing as _tracing

logger = logging.getLogger(__name__)

# reference WebhooksConnectors.scala:26-34 (+ the example connectors the
# reference ships as copy-me templates, data/webhooks/example{json,form})
JSON_CONNECTORS = {
    "segmentio": SegmentIOConnector(),
    "examplejson": ExampleJsonConnector(),
}
FORM_CONNECTORS = {
    "mailchimp": MailChimpConnector(),
    "exampleform": ExampleFormConnector(),
}

DEFAULT_LIMIT = 20  # reference EventServer.scala:307


@dataclasses.dataclass
class EventServerConfig:
    """Reference EventServerConfig (EventServer.scala:496-500)."""

    ip: str = "localhost"
    port: int = 7070
    plugins: str = "plugins"
    stats: bool = False
    # bind with SO_REUSEPORT so several worker PROCESSES share the port
    # (kernel-balanced accepts) — the ingest scale-out past one
    # GIL-bound accept loop; requires multi-process-shared storage
    # (sqlite WAL file / gateway), NOT the in-memory backend
    reuse_port: bool = False
    # positive-result access-key cache TTL. Bounds how long a key
    # revoked by ANOTHER process keeps authenticating (same-process
    # deletes invalidate immediately via invalidate_access_key); 0
    # disables caching — every request reads the metadata store, the
    # reference's per-request behavior.
    auth_ttl_s: float = 5.0
    # REST transport: "async" = the event-loop frontend (api/aio_http.py)
    # — connections cost no OS threads; request handlers run on a
    # BOUNDED pool (handler_threads) because the insert path blocks
    # until its group-commit COMMIT acks. "threaded" = the stdlib
    # thread-per-connection fallback.
    transport: str = "async"
    # async-transport handler pool size: the ceiling on in-flight
    # (parked-on-COMMIT) requests. The group committer coalesces
    # everything queued within GROUP_COMMIT_MS, so a modest pool
    # saturates the write path; connections beyond it just queue.
    handler_threads: int = 16
    # background segment compaction (data/storage/segments.py): the
    # event server owns the write path, so it owns sealing cold row
    # ranges into mmap-scannable columnar segments too. A no-op on
    # backends without the tier (memory/http). False disables the
    # daemon (`pio eventserver --no-compact`); standalone compaction
    # stays available via `pio compact`.
    compact: bool = True
    compact_interval_s: float = 60.0
    # online feedback join (workflow/quality.py): committed feedback
    # `predict` events populate the prId→served-prediction table, and
    # committed events carrying a prId join against it, emitting
    # pio_online_attributed_total{version,outcome} + rank/time-to-
    # conversion histograms. Runs via the generic commit hook
    # (EventAPI.add_commit_observer); overhead is hard-gated <2% of
    # batch-ingest throughput by `bench.py --only quality`.
    attribution: bool = True

    def __post_init__(self):
        if self.transport not in TRANSPORTS:
            raise ValueError(
                f"unknown transport {self.transport!r} "
                f"(expected one of {TRANSPORTS})"
            )


def _saturated(e: StorageSaturatedError) -> Tuple[int, dict, str, dict]:
    """Deliberate backpressure: the storage write path refused admission
    (bounded group-commit queue full), so answer 503 + ``Retry-After``
    instead of parking the handler thread unboundedly. The transport
    layer counts it in ``pio_http_errors_total{status="503"}``."""
    retry_s = max(1, int(round(e.retry_after_s)))
    return (
        503,
        {"message": str(e)},
        "application/json",
        {"Retry-After": str(retry_s)},
    )


def _message(status: int, message: str) -> Tuple[int, dict]:
    return status, {"message": message}


# every live EventAPI, so the admin delete path can revoke a key from
# all in-process servers' auth caches immediately (ADVICE.md: the TTL
# alone left a same-process revocation authenticating for up to 5 s)
_LIVE_APIS: "weakref.WeakSet" = weakref.WeakSet()


def invalidate_access_key(key: Optional[str] = None) -> None:
    """Drop ``key`` (all keys when None) from every live in-process
    EventAPI's auth cache. Called by the access-key/app delete commands;
    cross-process servers still age revoked keys out at their TTL."""
    for api in list(_LIVE_APIS):
        api.invalidate_access_key(key)


class EventAPI:
    """Transport-independent request core for the event server."""

    def __init__(
        self,
        storage: Optional[Storage] = None,
        config: Optional[EventServerConfig] = None,
        plugin_context: Optional[EventServerPluginContext] = None,
    ):
        self.storage = storage or get_storage()
        self.config = config or EventServerConfig()
        self.plugin_context = plugin_context or EventServerPluginContext()
        self.stats = StatsTracker()
        self._events = self.storage.get_l_events()
        self._access_keys = self.storage.get_meta_data_access_keys()
        self._channels = self.storage.get_meta_data_channels()
        # access-key lookups hit the metadata store on EVERY request; on
        # a file-backed store that is a per-event SELECT contending with
        # the ingest writer (measured: most of the sqlite-vs-memory REST
        # throughput gap). Keys change rarely — a short TTL
        # (config.auth_ttl_s; 0 disables) bounds how long a key revoked
        # by another process keeps working (the reference re-reads per
        # request but against an in-JVM HBase client cache); same-process
        # deletes invalidate immediately (invalidate_access_key below).
        self._auth_cache: Dict[str, Tuple[float, Any]] = {}
        self._AUTH_TTL_S = float(self.config.auth_ttl_s)
        import time as _time

        self._started_monotonic = _time.monotonic()
        from predictionio_tpu.data.storage.segments import (
            CachedCompactionStatus,
        )

        self._compaction_status = CachedCompactionStatus(self.storage)
        # ingest bookkeeping in the process-global registry (the
        # /metrics exposition; per-route ingested-event counters beside
        # the storage tier's group-commit flush families)
        self._m_ingested = _metrics.get_registry().counter(
            "pio_events_ingested_total",
            "Events accepted by the event server, by route",
            labels=("route",),
        )
        # /readyz: the store must answer a cheap metadata read (TTL-
        # cached so an unauthenticated readiness poller cannot turn the
        # probe into a storage load); stalled-daemon checks are global
        self._ready_probes = (
            _health.TTLProbe("store", self._probe_store),
        )
        # the commit hook: observers run AFTER events commit, on the
        # ingest path, with the committed Event objects. The online
        # feedback join registers here; the per-user-cache tier's
        # change notifications (ROADMAP) will ride the same hook.
        self._commit_observers: list = []
        if self.config.attribution:
            from predictionio_tpu.workflow.quality import (
                attribution_observer,
            )

            self.add_commit_observer(attribution_observer())
        _LIVE_APIS.add(self)

    def add_commit_observer(self, fn) -> None:
        """Register ``fn(app_id, channel_id, events)`` to run after each
        successful insert/batch commit. Observers must be cheap (they
        sit on the ingest path) and must not raise — failures are
        logged and swallowed."""
        self._commit_observers.append(fn)

    def _notify_commit(self, app_id, channel_id, events) -> None:
        if not self._commit_observers or not events:
            return
        for obs in self._commit_observers:
            try:
                obs(app_id, channel_id, events)
            except Exception:
                logger.exception("commit observer failed")

    def _probe_store(self) -> None:
        self.storage.get_meta_data_apps().get_all()

    # --- auth (reference withAccessKey, EventServer.scala:81-107) ---

    def invalidate_access_key(self, key: Optional[str] = None) -> None:
        """Drop ``key`` (all keys when None) from the auth cache, so a
        just-revoked key stops authenticating NOW instead of at TTL
        expiry."""
        if key is None:
            self._auth_cache.clear()
        else:
            self._auth_cache.pop(key, None)

    def _lookup_access_key(self, key: str):
        import time as _time

        if self._AUTH_TTL_S <= 0:
            return self._access_keys.get(key)
        now = _time.monotonic()
        hit = self._auth_cache.get(key)
        if hit is not None and now - hit[0] < self._AUTH_TTL_S:
            return hit[1]
        access_key = self._access_keys.get(key)
        # only POSITIVE results cache: a just-created key must work
        # immediately, not 401 for a TTL (and unauthenticated floods of
        # random keys can't grow the cache — misses pay the store read,
        # exactly the pre-cache behavior)
        if access_key is not None:
            if len(self._auth_cache) > 10_000:
                self._auth_cache.clear()
            self._auth_cache[key] = (now, access_key)
        return access_key

    def _authenticate(
        self, query: Dict[str, str]
    ) -> Tuple[Optional[Tuple[int, Optional[int]]], Optional[Tuple[int, Any]]]:
        """Returns ((app_id, channel_id), None) or (None, error_response)."""
        key = query.get("accessKey")
        if not key:
            return None, _message(401, "Missing accessKey.")
        access_key = self._lookup_access_key(key)
        if access_key is None:
            return None, _message(401, "Invalid accessKey.")
        channel_name = query.get("channel")
        if channel_name is None:
            return (access_key.appid, None), None
        channels = self._channels.get_by_app_id(access_key.appid)
        for c in channels:
            if c.name == channel_name:
                return (access_key.appid, c.id), None
        return None, _message(400, f"Invalid channel '{channel_name}'.")

    # --- dispatch ---

    def handle(
        self,
        method: str,
        path: str,
        query: Optional[Dict[str, str]] = None,
        body: Optional[bytes] = None,
        form: Optional[Dict[str, str]] = None,
        headers: Optional[Dict[str, str]] = None,
    ) -> Tuple[int, Any]:
        """Route one request; returns (status, json-compatible payload)."""
        query = query or {}
        try:
            return self._route(method, path, query, body, form, headers)
        except Exception as e:  # reference Common.exceptionHandler
            logger.exception("internal error handling %s %s", method, path)
            return _message(500, str(e))

    def _route(
        self, method, path, query, body, form, headers=None
    ) -> Tuple[int, Any]:
        parts = [p for p in path.strip("/").split("/") if p]

        if not parts:
            if method == "GET":
                return 200, {"status": "alive"}
            return _message(405, "Method not allowed.")

        if path == "/plugins.json" and method == "GET":
            return 200, self.plugin_context.describe()

        if path == "/status.json" and method == "GET":
            return 200, self._status_json(query)

        if path == "/healthz" and method == "GET":
            # liveness: answers while the frontend runs handlers at all;
            # never consults storage or daemons (that's readiness)
            return 200, _health.liveness()

        if path == "/readyz" and method == "GET":
            # readiness: store reachable + no registered background
            # daemon (committers, compactor, continuous trainer) stalled
            # past its deadline — 503 tells the balancer to drain us
            ok, payload = _health.readiness(self._ready_probes)
            return (200 if ok else 503), payload

        if path == "/metrics" and method == "GET":
            # unauthenticated like status.json: process-level aggregates
            # only, the health-probe class of information
            return (
                200,
                _metrics.get_registry().render(),
                _metrics.render_content_type(),
            )

        if path == "/debug/traces.json" and method == "GET":
            # span dumps carry entity ids and timings — same class of
            # information the data routes gate behind access keys
            auth, err = self._authenticate(query)
            if err:
                return err
            from predictionio_tpu.api.http import traces_payload

            return traces_payload(query)

        if path == "/debug/profile":
            # on-demand profiler capture (utils/profiling.profile_route)
            # — device timelines expose workload structure, so it is
            # gated exactly like the data routes. A POST blocks for its
            # whole capture window, which is safe on BOTH transports:
            # async offloads every route to the bounded handler pool
            # (the capture parks one worker, same as a slow scan), and
            # threaded blocks its per-connection thread.
            auth, err = self._authenticate(query)
            if err:
                return err
            from predictionio_tpu.utils.profiling import profile_route

            return profile_route(method, query, True)

        if parts[0] == "plugins" and len(parts) >= 3 and method == "GET":
            auth, err = self._authenticate(query)
            if err:
                return err
            app_id, channel_id = auth
            plugin_type, plugin_name, args = parts[1], parts[2], parts[3:]
            table = (
                self.plugin_context.input_blockers
                if plugin_type == EventServerPlugin.INPUT_BLOCKER
                else self.plugin_context.input_sniffers
            )
            if plugin_name not in table:
                return _message(404, f"Plugin {plugin_name} not found.")
            return 200, table[plugin_name].handle_rest(app_id, channel_id, args)

        if path == "/events.json":
            auth, err = self._authenticate(query)
            if err:
                return err
            app_id, channel_id = auth
            if method == "POST":
                return self._post_event(app_id, channel_id, body, headers)
            if method == "GET":
                return self._find_events(app_id, channel_id, query)
            return _message(405, "Method not allowed.")

        if path == "/batch/events.json":
            auth, err = self._authenticate(query)
            if err:
                return err
            app_id, channel_id = auth
            if method != "POST":
                return _message(405, "Method not allowed.")
            return self._post_batch(app_id, channel_id, body, headers)

        if parts[0] == "events" and len(parts) == 2 and parts[1].endswith(".json"):
            auth, err = self._authenticate(query)
            if err:
                return err
            app_id, channel_id = auth
            event_id = urllib.parse.unquote(parts[1][: -len(".json")])
            if method == "GET":
                event = self._events.get(event_id, app_id, channel_id)
                if event is None:
                    return _message(404, "Not Found")
                return 200, event.to_json()
            if method == "DELETE":
                found = self._events.delete(event_id, app_id, channel_id)
                return (
                    (200, {"message": "Found"})
                    if found
                    else _message(404, "Not Found")
                )
            return _message(405, "Method not allowed.")

        if path == "/stats.json" and method == "GET":
            auth, err = self._authenticate(query)
            if err:
                return err
            app_id, _ = auth
            if not self.config.stats:
                return _message(
                    404,
                    "To see stats, launch Event Server with --stats argument.",
                )
            return 200, self.stats.get(app_id)

        if parts[0] == "webhooks" and len(parts) == 2:
            auth, err = self._authenticate(query)
            if err:
                return err
            app_id, channel_id = auth
            name = parts[1]
            if name.endswith(".json"):
                return self._webhook_json(
                    app_id, channel_id, name[: -len(".json")], method, body
                )
            return self._webhook_form(app_id, channel_id, name, method, form)

        return _message(404, "Not Found")

    def _status_json(self, query: Optional[Dict[str, str]] = None) -> dict:
        """Operational status (the engine server's status.json
        counterpart): uptime, transport, and segment-tier observability
        — segment count, compacted-event fraction, last-compaction
        timestamp (stats TTL-cached, ``CachedCompactionStatus``).

        The route itself stays unauthenticated (a health probe), but
        without a valid ``accessKey`` the compaction block is the
        cross-app AGGREGATE only — per-app names and counts are the
        same class of information the rest of the API gates behind
        keys. A valid key adds its own app's detail."""
        import time as _time

        per_app = self._compaction_status.get()
        # ingest totals are a read of the registry (same families the
        # /metrics route exposes), not a private tally
        ingested = {
            key[0]: int(child.value)
            for key, child in self._m_ingested.children()
        }
        out = {
            "status": "alive",
            "transport": self.config.transport,
            "uptimeSec": round(
                _time.monotonic() - self._started_monotonic, 3
            ),
            "eventsIngested": ingested,
            "compaction": {
                "apps": len(per_app),
                "segments": sum(s["segments"] for s in per_app.values()),
                "compactedEvents": sum(
                    s["segmentEvents"] for s in per_app.values()
                ),
                "lastCompactionMs": max(
                    (s["lastCompactionMs"] for s in per_app.values()),
                    default=0,
                ),
            },
        }
        if self.config.attribution:
            from predictionio_tpu.workflow.quality import get_attribution

            # the online feedback join (cross-app aggregate: version
            # labels are engine-instance ids, not app data)
            out["attribution"] = get_attribution().stats()
        key = (query or {}).get("accessKey")
        if key:
            access_key = self._lookup_access_key(key)
            if access_key is not None:
                app = self.storage.get_meta_data_apps().get(access_key.appid)
                s = per_app.get(app.name) if app else None
                if s is not None:
                    out["appCompaction"] = {
                        "app": app.name,
                        "segments": s["segments"],
                        "compactedEvents": s["segmentEvents"],
                        "compactedFraction": round(
                            s["compactedFraction"], 6
                        ),
                        "lastCompactionMs": s["lastCompactionMs"],
                    }
        return out

    # --- event handlers ---

    def _insert(
        self, app_id, channel_id, event: Event, route: str = "single"
    ) -> Tuple[int, Any]:
        event_id = self._events.insert(event, app_id, channel_id)
        self.plugin_context.notify_sniffers(app_id, channel_id, event)
        self._m_ingested.labels(route=route).inc()
        self._notify_commit(app_id, channel_id, (event,))
        result = (201, {"eventId": event_id})
        if self.config.stats:
            self.stats.bookkeeping(app_id, result[0], event)
        return result

    # reference EventServer.scala:161 ("Batch request must have less
    # than or equal to 50 events")
    MAX_BATCH_EVENTS = 50

    def _post_batch(
        self, app_id, channel_id, body, headers=None
    ) -> Tuple[int, Any]:
        """Reference batch route (EventServer.scala:161-233): a JSON
        array of up to 50 events, answered 200 with one status object
        per slot — 201 + eventId on success, 400/403 + message on a
        per-event failure (one bad event never fails its batchmates).
        All parseable, unblocked events of the request are handed to the
        store as ONE ``insert_batch`` — the storage tier's group-commit
        unit, so the whole slice is one transaction per shard instead of
        50 commits."""
        return self._traced_http(
            "http:POST /batch/events.json",
            headers,
            lambda: self._post_batch_inner(app_id, channel_id, body),
        )

    def _traced_http(self, name, headers, fn) -> Tuple[int, Any]:
        """Ingest-entry trace wrapper for CLIENT-SUPPLIED trace ids
        (``X-PIO-Trace-Id``): make the trace ambient under an
        ``insert`` span — the group-commit committer and the
        storage-gateway RPC client pick it up from there — and record
        the entry span when the handler returns. Untraced requests skip
        tracing entirely: per-event span recording would put the shared
        ring-buffer lock on the write hot path and flood the bounded
        ring, evicting the requests an operator deliberately traced
        (the storage gateway applies the same guard)."""
        import time as _time

        if not (headers and headers.get(_tracing.TRACE_HEADER.lower())):
            return fn()
        tctx, inbound = _tracing.from_headers(headers)
        t0 = _time.time()
        status = 500
        try:
            with _tracing.use(tctx), _tracing.span("insert"):
                result = fn()
            status = result[0]
            return result
        finally:
            _tracing.record_span(
                name, tctx.trace_id, span_id=tctx.span_id,
                parent_id=inbound, start_s=t0,
                duration_s=_time.time() - t0, attrs={"status": status},
            )

    def _post_batch_inner(self, app_id, channel_id, body) -> Tuple[int, Any]:
        try:
            payload = json.loads((body or b"").decode("utf-8"))
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            return _message(400, str(e))
        if not isinstance(payload, list):
            return _message(400, "Request body must be a JSON array.")
        if len(payload) > self.MAX_BATCH_EVENTS:
            return _message(
                400,
                "Batch request must have less than or equal to "
                f"{self.MAX_BATCH_EVENTS} events",
            )
        results: list = []
        pending: list = []  # (slot, event) surviving parse + blockers
        for item in payload:
            try:
                if not isinstance(item, dict):
                    raise EventValidationError(
                        "each batch entry must be a JSON object"
                    )
                event = Event.from_json(item)
            except EventValidationError as e:
                results.append({"status": 400, "message": str(e)})
                continue
            try:
                self.plugin_context.run_blockers(app_id, channel_id, event)
            except Exception as e:  # an input blocker rejected the event
                results.append({"status": 403, "message": str(e)})
                continue
            results.append(None)
            pending.append((len(results) - 1, event))
        if pending:
            try:
                event_ids = self._events.insert_batch(
                    [e for _, e in pending], app_id, channel_id
                )
                failed: frozenset = frozenset()
            except StorageSaturatedError as e:
                # NOTHING was admitted (the storage layer only raises
                # this when no slice was enqueued): the whole batch is
                # safe to retry after backoff (unlike PartialBatchError
                # below)
                return _saturated(e)
            except PartialBatchError as e:
                # some shard slices committed, others did not — report
                # per-event outcomes so the client retries ONLY the
                # failed slots (a blanket 500 would make it re-post the
                # committed slice under fresh ids). retry_after_s marks
                # the failures as capacity refusals: those slots answer
                # 503 (retry after backoff), not 500
                event_ids, failed = e.event_ids, e.failed_ids
                if e.retry_after_s is not None:
                    failed_result = {
                        "status": 503,
                        "message": (
                            "storage saturated; retry this event after "
                            f"~{max(1, int(round(e.retry_after_s)))}s"
                        ),
                    }
                else:
                    failed_result = {
                        "status": 500,
                        "message": "event failed to commit; retry this event",
                    }
            committed = []
            for (slot, event), event_id in zip(pending, event_ids):
                if event_id in failed:
                    results[slot] = dict(failed_result)
                    continue
                results[slot] = {"status": 201, "eventId": event_id}
                self._m_ingested.labels(route="batch").inc()
                committed.append(event)
                self.plugin_context.notify_sniffers(app_id, channel_id, event)
                if self.config.stats:
                    self.stats.bookkeeping(app_id, 201, event)
            self._notify_commit(app_id, channel_id, committed)
        return 200, results

    def _post_event(
        self, app_id, channel_id, body, headers=None
    ) -> Tuple[int, Any]:
        return self._traced_http(
            "http:POST /events.json",
            headers,
            lambda: self._post_event_inner(app_id, channel_id, body),
        )

    def _post_event_inner(self, app_id, channel_id, body) -> Tuple[int, Any]:
        try:
            payload = json.loads((body or b"").decode("utf-8"))
            event = Event.from_json(payload)
        except (json.JSONDecodeError, UnicodeDecodeError, EventValidationError) as e:
            return _message(400, str(e))
        try:
            self.plugin_context.run_blockers(app_id, channel_id, event)
        except Exception as e:  # an input blocker rejected the event
            return _message(403, str(e))
        try:
            return self._insert(app_id, channel_id, event)
        except StorageSaturatedError as e:
            return _saturated(e)

    def _find_events(self, app_id, channel_id, query) -> Tuple[int, Any]:
        try:
            start_time = (
                parse_iso8601(query["startTime"]) if "startTime" in query else None
            )
            until_time = (
                parse_iso8601(query["untilTime"]) if "untilTime" in query else None
            )
            limit = int(query.get("limit", DEFAULT_LIMIT))
            reversed_ = query.get("reversed", "false").lower() == "true"
        except (ValueError, TypeError) as e:
            return _message(400, str(e))
        event_name = query.get("event")
        events = list(
            self._events.find(
                app_id=app_id,
                channel_id=channel_id,
                start_time=start_time,
                until_time=until_time,
                entity_type=query.get("entityType"),
                entity_id=query.get("entityId"),
                event_names=[event_name] if event_name else None,
                target_entity_type=query.get("targetEntityType", UNSET),
                target_entity_id=query.get("targetEntityId", UNSET),
                limit=None if limit == -1 else limit,
                reversed=reversed_,
            )
        )
        if not events:
            return _message(404, "Not Found")
        return 200, [e.to_json() for e in events]

    # --- webhooks (reference api/Webhooks.scala:43-151) ---

    def _webhook_json(
        self, app_id, channel_id, web, method, body
    ) -> Tuple[int, Any]:
        connector = JSON_CONNECTORS.get(web)
        if connector is None:
            return _message(404, f"webhooks connection for {web} is not supported.")
        if method == "GET":
            return 200, {"message": "Ok"}
        if method != "POST":
            return _message(405, "Method not allowed.")
        try:
            payload = json.loads((body or b"").decode("utf-8"))
            event = to_event(connector, payload)
        except (
            json.JSONDecodeError,
            UnicodeDecodeError,
            ConnectorException,
            EventValidationError,
        ) as e:
            return _message(400, str(e))
        try:
            return self._insert(app_id, channel_id, event, route="webhook")
        except StorageSaturatedError as e:
            return _saturated(e)

    def _webhook_form(
        self, app_id, channel_id, web, method, form
    ) -> Tuple[int, Any]:
        connector = FORM_CONNECTORS.get(web)
        if connector is None:
            return _message(404, f"webhooks connection for {web} is not supported.")
        if method == "GET":
            return 200, {"message": "Ok"}
        if method != "POST":
            return _message(405, "Method not allowed.")
        try:
            event = to_event(connector, form or {})
        except (ConnectorException, EventValidationError) as e:
            return _message(400, str(e))
        try:
            return self._insert(app_id, channel_id, event, route="webhook")
        except StorageSaturatedError as e:
            return _saturated(e)


class EventServer:
    """HTTP wrapper (reference EventServerActor + Run, EventServer.scala:471-531).

    With the default async transport, every route is offloaded to a
    bounded handler pool and the event loop awaits the returned future:
    an idle keep-alive connection costs no thread, and the threads that
    do exist are parked exactly where the work is (the group-commit
    COMMIT wait), which is what the committer wants — many requests
    queued inside one flush window."""

    def __init__(
        self,
        storage: Optional[Storage] = None,
        config: Optional[EventServerConfig] = None,
        plugin_context: Optional[EventServerPluginContext] = None,
    ):
        self.config = config or EventServerConfig()
        self.api = EventAPI(storage, self.config, plugin_context)
        # background compactor: seals cold row ranges into columnar
        # segments while the server ingests (no-op for backends without
        # the tier). Owned here so shutdown stops it with the server.
        self.compactor = None
        if self.config.compact:
            from predictionio_tpu.data.storage.segments import (
                SegmentCompactor,
            )

            if SegmentCompactor.supported(self.api.storage):
                self.compactor = SegmentCompactor(
                    self.api.storage,
                    interval_s=self.config.compact_interval_s,
                )
        self._pool: Optional[concurrent.futures.ThreadPoolExecutor] = None
        if self.config.transport == "async":
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=max(1, self.config.handler_threads),
                thread_name_prefix="evhandler",
            )
            pool = self._pool

            def fn(method, path, query, body, form=None, headers=None):
                if path == "/healthz" and method == "GET":
                    # liveness answers INLINE on the loop (pure dict
                    # build, non-blocking): a handler pool saturated
                    # with parked COMMIT waits must not read as "dead"
                    return self.api.handle(
                        method, path, query, body, form, headers
                    )
                return pool.submit(
                    self.api.handle, method, path, query, body, form,
                    headers,
                )
        else:
            fn = self.api.handle
        self._http = make_http_server(
            fn, self.config.ip, self.config.port, "Event Server",
            reuse_port=self.config.reuse_port,
            transport=self.config.transport,
        )

    @property
    def port(self) -> int:
        return self._http.port

    def start(self) -> "EventServer":
        self._http.start()
        if self.compactor is not None:
            self.compactor.start()
        return self

    def serve_forever(self) -> None:
        if self.compactor is not None:
            self.compactor.start()
        self._http.serve_forever()

    def shutdown(self) -> None:
        if self.compactor is not None:
            self.compactor.close()
        self._http.shutdown()
        if self._pool is not None:
            # wait=False: a handler parked on a wedged COMMIT must not
            # hang undeploy (same contract as the batching executor)
            self._pool.shutdown(wait=False)


def create_event_server(
    config: Optional[EventServerConfig] = None,
    storage: Optional[Storage] = None,
) -> EventServer:
    """Reference EventServer.createEventServer (EventServer.scala:502-522).
    Plugins are auto-discovered at launch (the reference's ServiceLoader
    pass, EventServerPluginContext.scala:26-49)."""
    return EventServer(
        storage=storage,
        config=config,
        plugin_context=EventServerPluginContext.discover(),
    )
