"""REST API layer: event server, stats, plugins.

The reference implements these as spray/akka actor systems
(data/src/main/scala/io/prediction/data/api/); here each service is a pure
request-handling core (`EventAPI`) — directly unit-testable, mirroring the
reference's spray-testkit route tests — wrapped by an HTTP transport for
deployment: a single-threaded asyncio event loop by default
(api/aio_http.py; in-flight requests are awaited futures, not parked
threads) with the stdlib threading server as the ``transport='threaded'``
fallback (api/http.py). Ingestion is host-side work and never touches the
TPU; the store layer hands accumulated events to device-bound columnar
batches at training time.
"""

from predictionio_tpu.api.event_server import (  # noqa: F401
    EventAPI,
    EventServer,
    EventServerConfig,
)
from predictionio_tpu.api.stats import Stats, StatsTracker  # noqa: F401
