"""Event-server ingestion statistics.

Parity with the reference Stats/StatsActor
(data/src/main/scala/io/prediction/data/api/Stats.scala:40-79,
StatsActor.scala:34-74): per-app counters keyed by
(entityType, targetEntityType, event) and by HTTP status code, kept in
three windows — long-lived since server start, the current clock hour, and
the previous hour (rolled over lazily on update). The actor mailbox is
replaced by a lock; counting happens on the REST worker thread.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import threading
from typing import Dict, Optional, Tuple

from predictionio_tpu.data.event import Event, format_iso8601, utcnow

# (entityType, targetEntityType, event) — reference EntityTypesEvent
ETE = Tuple[str, Optional[str], str]


def _hour_floor(t: _dt.datetime) -> _dt.datetime:
    return t.replace(minute=0, second=0, microsecond=0)


class Stats:
    """One counting window (reference Stats.scala:48-79)."""

    def __init__(self, start_time: _dt.datetime):
        self.start_time = start_time
        self.end_time: Optional[_dt.datetime] = None
        self.status_code_count: Dict[Tuple[int, int], int] = {}
        self.ete_count: Dict[Tuple[int, ETE], int] = {}

    def cutoff(self, end_time: _dt.datetime) -> None:
        self.end_time = end_time

    def update(self, app_id: int, status_code: int, event: Event) -> None:
        sc_key = (app_id, status_code)
        self.status_code_count[sc_key] = self.status_code_count.get(sc_key, 0) + 1
        ete: ETE = (event.entity_type, event.target_entity_type, event.event)
        e_key = (app_id, ete)
        self.ete_count[e_key] = self.ete_count.get(e_key, 0) + 1

    def get(self, app_id: int) -> dict:
        """Snapshot for one app as JSON-compatible data
        (reference StatsSnapshot)."""
        return {
            "startTime": format_iso8601(self.start_time),
            "endTime": (
                format_iso8601(self.end_time) if self.end_time else None
            ),
            "basic": [
                {
                    "entityType": ete[0],
                    "targetEntityType": ete[1],
                    "event": ete[2],
                    "count": count,
                }
                for (aid, ete), count in sorted(
                    self.ete_count.items(),
                    key=lambda kv: (kv[0][0], kv[0][1][0], kv[0][1][1] or "", kv[0][1][2]),
                )
                if aid == app_id
            ],
            "statusCode": [
                {"code": code, "count": count}
                for (aid, code), count in sorted(self.status_code_count.items())
                if aid == app_id
            ],
        }


@dataclasses.dataclass
class _Windows:
    long_live: Stats
    hourly: Stats
    prev_hourly: Stats


class StatsTracker:
    """Thread-safe three-window tracker (reference StatsActor.scala:34-74)."""

    def __init__(self, now: Optional[_dt.datetime] = None):
        now = now or utcnow()
        hour = _hour_floor(now)
        prev = Stats(hour - _dt.timedelta(hours=1))
        prev.cutoff(hour)
        self._w = _Windows(Stats(now), Stats(hour), prev)
        self._lock = threading.Lock()

    def bookkeeping(
        self, app_id: int, status_code: int, event: Event,
        now: Optional[_dt.datetime] = None,
    ) -> None:
        now = now or utcnow()
        current = _hour_floor(now)
        with self._lock:
            if current != self._w.hourly.start_time:
                self._w.prev_hourly = self._w.hourly
                self._w.prev_hourly.cutoff(current)
                self._w.hourly = Stats(current)
            self._w.hourly.update(app_id, status_code, event)
            self._w.long_live.update(app_id, status_code, event)

    def get(self, app_id: int) -> dict:
        with self._lock:
            return {
                "time": format_iso8601(utcnow()),
                "currentHour": self._w.hourly.get(app_id),
                "prevHour": self._w.prev_hourly.get(app_id),
                "longLive": self._w.long_live.get(app_id),
            }
