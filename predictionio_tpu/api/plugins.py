"""Event-server plugin framework.

Parity with the reference plugin surface
(data/src/main/scala/io/prediction/data/api/EventServerPlugin.scala:20-33,
EventServerPluginContext.scala:26-49, PluginsActor.scala:26-52): plugins
are either *input blockers* (run synchronously on the ingestion path and
may reject an event by raising) or *input sniffers* (observe events
asynchronously). The reference discovers plugins with
``java.util.ServiceLoader``; the Python equivalent is explicit
registration on the context (or ``EventServerPluginContext.discover()``
over subclass registries).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence

from predictionio_tpu.api.plugin_base import AsyncNotifier, describe_plugins
from predictionio_tpu.data.event import Event

logger = logging.getLogger(__name__)


class EventServerPlugin:
    """Base plugin (reference EventServerPlugin.scala:20-33)."""

    INPUT_BLOCKER = "inputblocker"
    INPUT_SNIFFER = "inputsniffer"

    plugin_name: str = "plugin"
    plugin_description: str = ""
    plugin_type: str = INPUT_SNIFFER

    def process(
        self, app_id: int, channel_id: Optional[int], event: Event, context
    ) -> None:
        """Blockers raise to reject the event; sniffers observe."""

    def handle_rest(
        self, app_id: int, channel_id: Optional[int], args: Sequence[str]
    ) -> dict:
        """Serve GET /plugins/<type>/<name>/... (reference handleREST)."""
        return {}


class EventServerPluginContext:
    """Holds registered plugins split by type; sniffers run on a daemon
    worker thread (the reference's PluginsActor mailbox)."""

    def __init__(self, plugins: Sequence[EventServerPlugin] = ()):
        self.input_blockers: Dict[str, EventServerPlugin] = {}
        self.input_sniffers: Dict[str, EventServerPlugin] = {}
        for p in plugins:
            self.register(p)
        self._notifier = AsyncNotifier(self._deliver)

    @classmethod
    def discover(cls) -> "EventServerPluginContext":
        """Instantiate every concrete EventServerPlugin subclass — the
        Python stand-in for ServiceLoader discovery."""
        plugins: List[EventServerPlugin] = []
        for sub in EventServerPlugin.__subclasses__():
            try:
                plugins.append(sub())
            except Exception:  # abstract/partial subclasses are skipped
                logger.exception("plugin %s failed to instantiate", sub)
        return cls(plugins)

    def register(self, plugin: EventServerPlugin) -> None:
        if plugin.plugin_type == EventServerPlugin.INPUT_BLOCKER:
            self.input_blockers[plugin.plugin_name] = plugin
        else:
            self.input_sniffers[plugin.plugin_name] = plugin

    def describe(self) -> dict:
        """GET /plugins.json payload (reference EventServer.scala:122-143)."""
        return {
            "plugins": {
                "inputblockers": describe_plugins(self.input_blockers),
                "inputsniffers": describe_plugins(self.input_sniffers),
            }
        }

    # --- ingestion-path hooks ---

    def run_blockers(
        self, app_id: int, channel_id: Optional[int], event: Event
    ) -> None:
        for p in self.input_blockers.values():
            p.process(app_id, channel_id, event, self)

    def notify_sniffers(
        self, app_id: int, channel_id: Optional[int], event: Event
    ) -> None:
        if not self.input_sniffers:
            return
        self._notifier.put((app_id, channel_id, event))

    def _deliver(self, item: tuple) -> None:
        app_id, channel_id, event = item
        for p in self.input_sniffers.values():
            try:
                p.process(app_id, channel_id, event, self)
            except Exception:
                logger.exception("sniffer %s failed", p.plugin_name)
