"""Event-loop HTTP/1.1 frontend for the REST request cores.

The stdlib ``ThreadingHTTPServer`` adapter (api/http.py) spends one OS
thread per CONNECTION, and — because both hot request cores block (the
engine server's ``_BatchingExecutor.submit`` parks until its micro-batch
is served; the event server's insert parks until the group-commit
COMMIT) — one parked thread per in-flight REQUEST. At a few hundred
concurrent clients the thread scheduler, not the TPU, bounds throughput,
and the micro-batch collector never sees more than ~1 queued query per
2 ms window.

This module replaces that transport with a single-threaded ``asyncio``
selector event loop: thousands of keep-alive connections cost file
descriptors, not threads, and an in-flight request is just a pending
``concurrent.futures.Future`` the loop awaits. The request core decides
the handoff shape via its return value:

  * a ``(status, payload[, content_type[, headers]])`` tuple — answered
    inline (fast, non-blocking routes: status pages, plugin listings);
  * a ``concurrent.futures.Future`` resolving to that tuple — awaited
    without a thread (the engine server's ``QueryAPI.handle_nowait``
    query route, the event server's bounded handler-pool offload);
  * a coroutine — awaited on the loop.

Per connection, a reader coroutine parses pipelined requests (HTTP/1.1
Content-Length framing; chunked is refused exactly like the threaded
frontend) and a writer coroutine sends the responses strictly in request
order, so several requests from ONE connection can ride the same device
micro-batch. Keep-alive, TCP_NODELAY, bind retries, and SO_REUSEPORT
worker parity all match ``JsonHTTPServer``, which remains the threaded
fallback (``--transport threaded``).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import inspect
import json
import logging
import socket
import threading
import urllib.parse
from http.client import responses as _REASONS
from typing import Optional, Tuple

from predictionio_tpu.api.http import (
    MAX_BODY_BYTES,
    HandleFn,
    JsonHTTPServer,
    ReusePortUnavailable,
    accepts_headers,
    bind_with_retries,
    record_http_error,
    request_trace_id,
)
from predictionio_tpu.utils import metrics as _metrics

logger = logging.getLogger(__name__)

# the transports make_http_server accepts; ServerConfig and
# EventServerConfig validate against this same tuple
TRANSPORTS = ("async", "threaded")

# headers beyond this are a 431; it is also the StreamReader buffer limit,
# so a missing \r\n\r\n cannot grow the buffer without bound
MAX_HEADER_BYTES = 65536

# pipelined requests in flight per connection before the reader stops
# parsing (backpressure: responses go out strictly in request order, so
# unbounded read-ahead would buffer unbounded response state)
PIPELINE_DEPTH = 16

_CLOSE = object()  # writer sentinel: flush nothing further, close


class AsyncJsonHTTPServer:
    """Single-threaded asyncio HTTP/1.1 server around a request core.

    Interface parity with ``JsonHTTPServer``: ``start()`` serves from a
    daemon thread, ``serve_forever()`` serves in the caller's thread,
    ``shutdown()`` is thread-safe and may be called from a handler-side
    thread (the /stop route does), ``port`` reports the bound port.
    Bind retries and their tunables are shared with the threaded
    frontend (``JsonHTTPServer.BIND_RETRIES``) so operational overrides
    cover both transports.

    While serving, a monitor coroutine samples event-loop scheduling lag
    (how late a timer fires vs. when it asked to) into the
    ``pio_eventloop_lag_seconds{server=...}`` gauge every
    ``LAG_INTERVAL_S`` — the single-threaded frontend's one scarce
    resource is loop time, and a handler that blocks inline shows up
    here before it shows up as tail latency.
    """

    LAG_INTERVAL_S = 0.5

    def __init__(
        self,
        handle_fn: HandleFn,
        ip: str,
        port: int,
        name: str,
        reuse_port: bool = False,
    ):
        self.name = name
        self.ip = ip
        self.handle_fn = handle_fn
        self._pass_headers = accepts_headers(handle_fn)
        # bind synchronously so construction fails loudly (port conflict,
        # missing SO_REUSEPORT) and .port is known before the loop spins
        self._sock = self._bind(ip, port, reuse_port)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._stop_event: Optional[asyncio.Event] = None
        self._started = threading.Event()
        self._finished = threading.Event()
        self._shutdown_lock = threading.Lock()
        self._shutdown_requested = False
        self._conn_tasks: set = set()

    # --- bind (retry policy shared with the threaded frontend) ---

    def _bind(self, ip: str, port: int, reuse_port: bool) -> socket.socket:
        def attempt() -> socket.socket:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            try:
                sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                if reuse_port:
                    try:
                        sock.setsockopt(
                            socket.SOL_SOCKET, socket.SO_REUSEPORT, 1
                        )
                    except (AttributeError, OSError) as e:
                        raise ReusePortUnavailable(
                            "SO_REUSEPORT is unavailable on this platform; "
                            "multi-worker port sharing cannot work"
                        ) from e
                sock.bind((ip, port))
                # listen NOW (parity with TCPServer.server_activate):
                # a second bind of the same port must fail at
                # construction, not when the loop later starts serving
                sock.listen(128)
                sock.setblocking(False)
                return sock
            except BaseException:
                sock.close()
                raise

        return bind_with_retries(attempt, self.name, ip, port)

    @property
    def port(self) -> int:
        return self._sock.getsockname()[1]

    # --- lifecycle ---

    def start(self) -> "AsyncJsonHTTPServer":
        self._thread = threading.Thread(target=self._run_loop, daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=10) and not self._thread.is_alive():
            raise RuntimeError(f"{self.name} event loop failed to start")
        logger.info("%s listening on %s:%d", self.name, self.ip, self.port)
        return self

    def serve_forever(self) -> None:
        logger.info("%s listening on %s:%d", self.name, self.ip, self.port)
        self._run_loop()

    def shutdown(self) -> None:
        """Stop accepting, give in-flight responses a short grace, close.
        Callable from any thread, including threads spawned by handlers
        (the /stop timer); idempotent."""
        with self._shutdown_lock:
            if self._shutdown_requested:
                already = True
            else:
                self._shutdown_requested = True
                already = False
            loop = self._loop
        if not already and loop is not None and not loop.is_closed():
            try:
                loop.call_soon_threadsafe(self._signal_stop)
            except RuntimeError:
                pass  # loop finished between the check and the call
        if self._thread and self._thread is not threading.current_thread():
            self._thread.join(timeout=10)
        elif self._thread is None and loop is not None:
            # serve_forever caller owns the loop thread; wait for it to
            # unwind so the port is released when we return (loop None
            # means the server was never started: nothing to wait for,
            # just release the bound socket below)
            self._finished.wait(timeout=10)
        if self._sock.fileno() != -1:
            self._sock.close()

    def _signal_stop(self) -> None:
        if self._stop_event is not None:
            self._stop_event.set()

    def _run_loop(self) -> None:
        loop = asyncio.new_event_loop()
        self._loop = loop
        try:
            loop.run_until_complete(self._serve())
        finally:
            # cancel any straggler tasks so loop.close() is clean
            for task in asyncio.all_tasks(loop):
                task.cancel()
            try:
                loop.run_until_complete(loop.shutdown_asyncgens())
            except Exception:
                pass
            loop.close()
            self._finished.set()

    async def _monitor_loop_lag(self) -> None:
        """Sample scheduling lag: sleep LAG_INTERVAL_S and record how far
        past the deadline the wake-up landed. A loop wedged by an inline
        blocking call reports the stall as soon as it unwedges; a healthy
        loop reports ~0."""
        loop = asyncio.get_running_loop()
        gauge = _metrics.get_registry().gauge(
            "pio_eventloop_lag_seconds",
            "Asyncio event-loop scheduling lag (timer lateness), sampled",
            labels=("server",),
        ).labels(server=self.name)
        interval = self.LAG_INTERVAL_S
        while True:
            t0 = loop.time()
            await asyncio.sleep(interval)
            gauge.set(max(0.0, loop.time() - t0 - interval))

    async def _serve(self) -> None:
        self._stop_event = asyncio.Event()
        with self._shutdown_lock:
            if self._shutdown_requested:  # shutdown raced start
                self._stop_event.set()
        server = await asyncio.start_server(
            self._on_connection,
            sock=self._sock,
            backlog=128,  # parity with _Server.request_queue_size
            limit=MAX_HEADER_BYTES,
        )
        lag_task = asyncio.ensure_future(self._monitor_loop_lag())
        self._started.set()
        try:
            await self._stop_event.wait()
        finally:
            lag_task.cancel()
            server.close()
            await server.wait_closed()
            live = [t for t in self._conn_tasks if not t.done()]
            if live:
                # grace for in-flight responses (their backing futures
                # resolve as soon as the executor drains), then cancel
                await asyncio.wait(live, timeout=2.0)
                for t in live:
                    t.cancel()
                await asyncio.wait(live, timeout=2.0)

    # --- per-connection pipeline ---

    async def _on_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._conn_tasks.add(task)
        sock = writer.get_extra_info("socket")
        if sock is not None:
            try:
                # small keep-alive request/response pairs stall tens of
                # ms under Nagle + delayed ACK (same rationale as the
                # threaded frontend's disable_nagle_algorithm)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
        # responses leave strictly in request order: the reader enqueues
        # one entry per parsed request, the writer awaits/serializes each
        pending: asyncio.Queue = asyncio.Queue(maxsize=PIPELINE_DEPTH)
        writer_task = asyncio.ensure_future(
            self._write_responses(pending, writer)
        )
        cancelled = False
        try:
            while True:
                req = await self._read_request(reader)
                if req is None:  # clean EOF between requests
                    break
                if req[0] == "error":
                    _, status, message = req
                    await pending.put(
                        ((status, {"message": message}), False,
                         "(framing)", None)
                    )
                    break
                _, method, path, query, body, form, headers, keep_alive = req
                trace_id = request_trace_id(headers)
                try:
                    if self._pass_headers:
                        result = self.handle_fn(
                            method, path, query, body, form, headers=headers
                        )
                    else:
                        result = self.handle_fn(method, path, query, body, form)
                except Exception as e:
                    logger.exception(
                        "internal error handling %s %s", method, path,
                        extra=(
                            {"traceId": trace_id} if trace_id else None
                        ),
                    )
                    result = (500, {"message": str(e)})
                await pending.put((result, keep_alive, path, trace_id))
                if not keep_alive:
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass  # peer went away mid-request
        except asyncio.CancelledError:
            cancelled = True
            raise
        finally:
            if cancelled:
                writer_task.cancel()
            else:
                # the writer consumes every entry up to _CLOSE even on a
                # dead peer (discard mode), so this put cannot park
                await pending.put(_CLOSE)
                try:
                    await writer_task
                except asyncio.CancelledError:
                    pass
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError, asyncio.CancelledError):
                pass
            if task is not None:
                self._conn_tasks.discard(task)

    async def _read_request(self, reader: asyncio.StreamReader):
        """Parse one framed request. Returns None on clean EOF,
        ``("error", status, message)`` on an unrecoverable framing
        problem (the connection closes after the error response), else
        ``("request", method, path, query, body, form, headers,
        keep_alive)`` — ``headers`` with lower-cased keys."""
        try:
            head = await reader.readuntil(b"\r\n\r\n")
        except asyncio.IncompleteReadError as e:
            if not e.partial:
                return None
            return ("error", 400, "truncated request")
        except asyncio.LimitOverrunError:
            return ("error", 431, "request headers too large")
        try:
            lines = head.decode("latin-1").split("\r\n")
            method, target, version = lines[0].split()
        except ValueError:
            return ("error", 400, "malformed request line")
        if not version.startswith("HTTP/1."):
            return ("error", 505, "HTTP version not supported")
        headers = {}
        for line in lines[1:]:
            if not line:
                continue
            key, sep, value = line.partition(":")
            if not sep or line[0] in " \t":  # no obs-fold support
                return ("error", 400, "malformed header line")
            headers[key.strip().lower()] = value.strip()
        # under keep-alive an unread body would be parsed as the NEXT
        # request — refuse framings we can't read (threaded-frontend
        # parity: chunked is 501)
        if "chunked" in headers.get("transfer-encoding", "").lower():
            return ("error", 501, "chunked transfer encoding not supported")
        try:
            length = int(headers.get("content-length") or 0)
        except ValueError:
            return ("error", 400, "invalid Content-Length")
        if length < 0:
            return ("error", 400, "invalid Content-Length")
        if length > MAX_BODY_BYTES:
            # refuse BEFORE reading: a hostile Content-Length must not
            # make the loop buffer gigabytes
            return ("error", 413, "request body too large")
        body = await reader.readexactly(length) if length > 0 else b""
        parsed = urllib.parse.urlsplit(target)
        query = dict(urllib.parse.parse_qsl(parsed.query))
        form = None
        ctype = headers.get("content-type", "").split(";")[0].strip()
        if ctype == "application/x-www-form-urlencoded":
            try:
                form = dict(
                    urllib.parse.parse_qsl(body.decode("utf-8"))
                )
            except UnicodeDecodeError:
                form = {}
        connection = headers.get("connection", "").lower()
        if version == "HTTP/1.1":
            keep_alive = "close" not in connection
        else:  # HTTP/1.0 defaults to one request per connection
            keep_alive = "keep-alive" in connection
        return (
            "request", method, parsed.path, query, body, form, headers,
            keep_alive,
        )

    async def _write_responses(
        self, pending: asyncio.Queue, writer: asyncio.StreamWriter
    ) -> None:
        # NEVER return before _CLOSE: the queue is bounded, so a writer
        # that stopped consuming would park the reader (and its
        # finally-clause _CLOSE put) forever on a full queue — leaking
        # the connection task and socket. After a write failure (or a
        # Connection: close response) we switch to discarding: remaining
        # entries are drained, their deferred work cancelled if possible.
        discarding = False
        while True:
            item = await pending.get()
            if item is _CLOSE:
                return
            result, keep_alive, route, trace_id = item
            if discarding:
                if isinstance(result, concurrent.futures.Future):
                    # best effort: an uncollected query still queued in
                    # the batching executor is dropped from its batch
                    result.cancel()
                continue
            try:
                if isinstance(result, concurrent.futures.Future):
                    # the future-based handoff: the in-flight request is
                    # this queue entry, not a parked OS thread
                    result = await asyncio.wrap_future(result)
                elif inspect.isawaitable(result):
                    result = await result
            except asyncio.CancelledError:
                raise
            except Exception as e:
                logger.exception(
                    "deferred handler failed",
                    extra={"traceId": trace_id} if trace_id else None,
                )
                result = (500, {"message": str(e)})
            status = None
            try:
                # rendering is inside the invariant too: a payload
                # json.dumps can't encode (or a malformed handler tuple)
                # must produce a 500, not kill the writer and wedge the
                # reader on the bounded queue
                head, data = self._render(result, keep_alive)
                status = result[0]
            except Exception as e:
                logger.exception("unrenderable handler result %r", result)
                head, data = self._render(
                    (500, {"message": str(e)}), keep_alive
                )
                status = 500
            record_http_error(self.name, route, status, trace_id)
            try:
                writer.write(head + data)
                await writer.drain()
            except (ConnectionError, OSError):
                discarding = True  # peer went away; drain to _CLOSE
            if not keep_alive:
                discarding = True  # discard pipelined leftovers

    @staticmethod
    def _render(result, keep_alive: bool) -> Tuple[bytes, bytes]:
        status, payload = result[0], result[1]
        out_type = result[2] if len(result) > 2 else "application/json"
        # optional 4th element: extra response headers (e.g. the 503
        # backpressure path's Retry-After) — same contract as the
        # threaded transport (api/http.py)
        extra = result[3] if len(result) > 3 and result[3] else {}
        if out_type == "application/json" and not isinstance(payload, str):
            data = json.dumps(payload).encode("utf-8")
        else:
            # str payloads go verbatim (pre-rendered JSON, HTML, text)
            data = str(payload).encode("utf-8")
        reason = _REASONS.get(status, "Unknown")
        conn_header = "" if keep_alive else "Connection: close\r\n"
        extra_headers = "".join(
            f"{k}: {v}\r\n" for k, v in extra.items()
        )
        # handlers may return a fully-qualified content type (the
        # Prometheus exposition carries its own charset parameter) —
        # only bare types get the default charset appended
        if "charset=" not in out_type:
            out_type = f"{out_type}; charset=utf-8"
        head = (
            f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: {out_type}\r\n"
            f"Content-Length: {len(data)}\r\n"
            f"{extra_headers}{conn_header}\r\n"
        ).encode("latin-1")
        return head, data


def make_http_server(
    handle_fn: HandleFn,
    ip: str,
    port: int,
    name: str,
    reuse_port: bool = False,
    transport: str = "async",
):
    """Transport selector shared by the REST servers: ``async`` is the
    event-loop frontend above, ``threaded`` the stdlib thread-per-
    connection fallback. The caller supplies a transport-appropriate
    ``handle_fn`` (the threaded frontend cannot await a Future)."""
    if transport == "async":
        return AsyncJsonHTTPServer(
            handle_fn, ip, port, name, reuse_port=reuse_port
        )
    if transport == "threaded":
        return JsonHTTPServer(
            handle_fn, ip, port, name, reuse_port=reuse_port
        )
    raise ValueError(
        f"unknown transport {transport!r} (expected one of {TRANSPORTS})"
    )
