"""Shared JSON-over-HTTP server adapter for the REST frontends.

Both the Event Server and the engine (query) server are a pure request
core — ``handle(method, path, query, body, form)`` returning
``(status, payload)`` or ``(status, payload, content_type)`` — wrapped by
this stdlib ThreadingHTTPServer adapter. The adapter owns transport
concerns: URL/query parsing, Content-Length body reads, form decoding,
JSON rendering, the background serve thread, and shutdown (including
shutdown initiated from a handler thread, as /stop does).
"""

from __future__ import annotations

import inspect
import json
import logging
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional, Tuple

logger = logging.getLogger(__name__)

# (method, path, query, body, form[, headers]) ->
# (status, payload[, content_type])
HandleFn = Callable[..., Tuple]


def request_trace_id(headers) -> Optional[str]:
    """The client's ``X-PIO-Trace-Id`` (lower-cased header dict),
    sanitized for log/label use — or None. Transport-layer error
    accounting runs OUTSIDE any ``tracing.use`` block, so the id is
    plumbed explicitly."""
    if not headers:
        return None
    from predictionio_tpu.utils import tracing as _tracing

    raw = headers.get(_tracing.TRACE_HEADER.lower()) or ""
    # the tracing layer's own sanitizer, so the id on transport-layer
    # error logs is byte-identical to the id its spans record under
    # (the documented traceId join key)
    return _tracing._sanitize(raw) or None


def record_http_error(
    server: str, route: str, status, trace_id: Optional[str] = None
) -> None:
    """Transport-layer error accounting, shared by BOTH frontends: every
    5xx response (and framing-level 4xxs, which never reach a handler —
    ``route`` is ``"(framing)"`` there) increments
    ``pio_http_errors_total{server,route,status}``, and 5xxs emit a
    structured error log carrying the request's trace id so the failure
    joins against /debug/traces.json. Before this counter, an unhandled
    handler exception 500'd with no accounting at all — invisible to
    /metrics, visible only to the client. Route label cardinality is
    bounded in practice: 4xxs on arbitrary fuzzed paths are NOT counted
    (they'd mint a label per path), only framing errors and 5xxs, which
    occur on real routes."""
    if not isinstance(status, int):
        return
    if route in ("/healthz", "/readyz"):
        # a readiness 503 is deliberate backpressure, not an error — a
        # draining worker polled every second must not spam the error
        # log or inflate the error counter
        return
    framing = route == "(framing)"
    if status < 500 and not (framing and status >= 400):
        return
    from predictionio_tpu.utils import metrics as _metrics

    _metrics.get_registry().counter(
        "pio_http_errors_total",
        "HTTP error responses recorded at the transport layer",
        labels=("server", "route", "status"),
    ).labels(server=server, route=route[:64], status=str(status)).inc()
    if status >= 500:
        logger.error(
            "%s: %s answered %d",
            server, route, status,
            extra={"traceId": trace_id} if trace_id else None,
        )


def traces_payload(query) -> Tuple[int, dict]:
    """The shared ``GET /debug/traces.json`` body builder (all three
    servers route here after their own auth gate). Supports the full
    dump, a ``traceId`` filter, and the incremental ``since=<seq>``
    cursor: the response always carries ``seq`` — the process's span
    high-water mark — which a consumer (the telemetry collector) feeds
    back as the next ``since`` so it never re-downloads the ring."""
    from predictionio_tpu.utils import tracing as _tracing

    q = query or {}
    trace_id = q.get("traceId") or None
    raw_since = q.get("since")
    if raw_since in (None, ""):
        return 200, {
            "spans": _tracing.dump(trace_id),
            "seq": _tracing.high_water(),
        }
    try:
        since = int(raw_since)
    except (TypeError, ValueError):
        return 400, {"message": f"invalid since cursor {raw_since!r}"}
    spans, hwm = _tracing.dump_since(since, trace_id=trace_id)
    return 200, {"spans": spans, "seq": hwm}


def accepts_headers(fn: Callable) -> bool:
    """Whether a request core takes the optional ``headers`` kwarg (the
    lower-cased request-header dict both transports can supply). Probed
    once at server construction so older 5-arg cores — and test
    doubles — keep working unchanged."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    for p in sig.parameters.values():
        if p.kind == inspect.Parameter.VAR_KEYWORD:
            return True
        if p.name == "headers":
            return True
    return False

# request-body ceiling shared by both transports (threaded here, the
# event loop in api/aio_http.py): a hostile Content-Length must not make
# a frontend buffer gigabytes. Largest legitimate body is a 50-event
# batch post — a few hundred KB.
MAX_BODY_BYTES = 16 * 1024 * 1024


class _Server(ThreadingHTTPServer):
    # the stdlib default backlog (5) drops connections under concurrent
    # load — a burst of clients gets RSTs before threads even spawn
    request_queue_size = 128


class ReusePortUnavailable(OSError):
    """SO_REUSEPORT missing on this platform — permanent, never retried
    (a plain bind OSError is treated as a transient port conflict)."""


class _ReusePortServer(_Server):
    allow_reuse_port = True  # honored on Python 3.11+

    def server_bind(self):
        import socket as _socket

        try:
            self.socket.setsockopt(
                _socket.SOL_SOCKET, _socket.SO_REUSEPORT, 1
            )
        except (AttributeError, OSError) as e:
            raise ReusePortUnavailable(
                "SO_REUSEPORT is unavailable on this platform; "
                "multi-worker port sharing cannot work"
            ) from e
        super().server_bind()


class _Handler(BaseHTTPRequestHandler):
    handle_fn: HandleFn  # bound by JsonHTTPServer
    pass_headers = False  # bound by JsonHTTPServer (accepts_headers)
    server_name = "HTTP"  # bound by JsonHTTPServer (error accounting)

    # HTTP/1.1 keep-alive: every response carries Content-Length, so
    # persistent connections are safe and spare concurrent clients a
    # TCP handshake per request
    protocol_version = "HTTP/1.1"
    # small request/response pairs on persistent connections stall for
    # tens of ms under Nagle + delayed ACK; serving latency is the product
    disable_nagle_algorithm = True

    def _dispatch(self, method: str) -> None:
        parsed = urllib.parse.urlsplit(self.path)
        query = dict(urllib.parse.parse_qsl(parsed.query))
        # under keep-alive, any request body we fail to consume would be
        # parsed as the NEXT request on the connection — refuse framings
        # we can't read and drop the connection when length is unknowable
        if "chunked" in (self.headers.get("Transfer-Encoding") or "").lower():
            self.close_connection = True
            record_http_error(self.server_name, "(framing)", 501)
            self.send_error(501, "chunked transfer encoding not supported")
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            self.close_connection = True
            record_http_error(self.server_name, "(framing)", 400)
            self.send_error(400, "invalid Content-Length")
            return
        if length > MAX_BODY_BYTES:
            # refuse BEFORE reading (the async frontend does the same)
            self.close_connection = True
            record_http_error(self.server_name, "(framing)", 413)
            self.send_error(413, "request body too large")
            return
        body = self.rfile.read(length) if length > 0 else b""
        # form-encoded bodies are parsed as a convenience, but the raw body
        # is kept too: clients (curl -d) often post JSON without setting
        # Content-Type, which defaults to form-urlencoded
        form = None
        ctype = (self.headers.get("Content-Type") or "").split(";")[0].strip()
        if ctype == "application/x-www-form-urlencoded":
            try:
                form = dict(urllib.parse.parse_qsl(body.decode("utf-8")))
            except UnicodeDecodeError:
                form = {}
        headers = {k.lower(): v for k, v in self.headers.items()}
        trace_id = request_trace_id(headers)
        try:
            if self.pass_headers:
                result = self.handle_fn(
                    method, parsed.path, query, body, form, headers=headers
                )
            else:
                result = self.handle_fn(method, parsed.path, query, body, form)
        except Exception as e:
            # request cores catch internally; this is the transport-layer
            # backstop so a raising core still answers (and is counted)
            # instead of silently dropping the connection
            logger.exception(
                "internal error handling %s %s", method, parsed.path,
                extra={"traceId": trace_id} if trace_id else None,
            )
            result = (500, {"message": str(e)})
        status, payload = result[0], result[1]
        record_http_error(self.server_name, parsed.path, status, trace_id)
        out_type = result[2] if len(result) > 2 else "application/json"
        # optional 4th element: extra response headers (e.g. the 503
        # backpressure path's Retry-After); same contract as the async
        # transport (api/aio_http.py)
        extra = result[3] if len(result) > 3 and result[3] else {}
        if out_type == "application/json" and not isinstance(payload, str):
            data = json.dumps(payload).encode("utf-8")
        else:
            # str payloads are sent verbatim (pre-rendered JSON, HTML, text)
            data = str(payload).encode("utf-8")
        self.send_response(status)
        # fully-qualified content types (the Prometheus exposition's
        # "; version=0.0.4; charset=utf-8") pass through verbatim
        if "charset=" not in out_type:
            out_type = f"{out_type}; charset=utf-8"
        self.send_header("Content-Type", out_type)
        self.send_header("Content-Length", str(len(data)))
        for k, v in extra.items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802
        self._dispatch("GET")

    def do_POST(self):  # noqa: N802
        self._dispatch("POST")

    def do_DELETE(self):  # noqa: N802
        self._dispatch("DELETE")

    def log_message(self, fmt, *args):  # route access logs through logging
        logger.debug("%s - %s", self.address_string(), fmt % args)


def bind_with_retries(attempt_fn: Callable, name: str, ip: str, port: int):
    """Shared bind policy for BOTH transports (this threaded server and
    the event-loop frontend in api/aio_http.py): run ``attempt_fn``
    (which binds and returns a server or socket) up to
    ``JsonHTTPServer.BIND_RETRIES`` times, ``BIND_RETRY_DELAY_S`` apart
    (reference CreateServer.scala:347-357 retries the spray bind 3x,
    1s apart — covers the undeploy-then-redeploy race where the old
    server's port lingers in TIME_WAIT). ``ReusePortUnavailable`` is
    permanent and never retried; a plain OSError is treated as a
    transient port conflict. The tunables stay class attributes on
    JsonHTTPServer (read at call time) so operational overrides cover
    both transports."""
    last_error: Optional[OSError] = None
    for attempt in range(JsonHTTPServer.BIND_RETRIES):
        try:
            return attempt_fn()
        except ReusePortUnavailable:
            raise  # permanent: retrying cannot make the option appear
        except OSError as e:
            last_error = e
            logger.warning(
                "%s bind to %s:%d failed (%s); retry %d/%d",
                name, ip, port, e, attempt + 1,
                JsonHTTPServer.BIND_RETRIES,
            )
            time.sleep(JsonHTTPServer.BIND_RETRY_DELAY_S)
    raise last_error


class JsonHTTPServer:
    """Threaded HTTP server around a request-core callable.

    Binding retries via ``bind_with_retries`` above.
    """

    BIND_RETRIES = 3
    BIND_RETRY_DELAY_S = 1.0

    def __init__(
        self,
        handle_fn: HandleFn,
        ip: str,
        port: int,
        name: str,
        reuse_port: bool = False,
    ):
        self.name = name
        self.ip = ip
        handler = type(
            "BoundHandler",
            (_Handler,),
            {
                "handle_fn": staticmethod(handle_fn),
                "pass_headers": accepts_headers(handle_fn),
                "server_name": name,
            },
        )
        # SO_REUSEPORT (``reuse_port``): several server PROCESSES bind the
        # same port and the kernel load-balances accepted connections —
        # the scale-out path past one GIL-bound accept loop (pio
        # eventserver --workers N). The storage behind the workers must
        # be multi-process-shared (sqlite WAL file or the gateway).
        # Set via setsockopt directly (socketserver's allow_reuse_port
        # attribute only exists on Python 3.11+, silently ignored
        # before) and fail LOUDLY where the platform lacks the option —
        # a worker that silently bound without it would steal the port
        # from its siblings.
        server_cls = _ReusePortServer if reuse_port else _Server
        self.httpd = bind_with_retries(
            lambda: server_cls((ip, port), handler), name, ip, port
        )
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self) -> "JsonHTTPServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()
        logger.info("%s listening on %s:%d", self.name, self.ip, self.port)
        return self

    def serve_forever(self) -> None:
        logger.info("%s listening on %s:%d", self.name, self.ip, self.port)
        self.httpd.serve_forever()

    def shutdown(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread and self._thread is not threading.current_thread():
            self._thread.join(timeout=5)
