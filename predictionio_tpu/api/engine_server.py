"""The engine (query) server: deployed-model REST serving.

Capability parity with the reference CreateServer
(core/src/main/scala/io/prediction/workflow/CreateServer.scala):

  GET  /               -> HTML status page           (:444-471)
  GET  /status.json    -> the same data as JSON (addition)
  POST /queries.json   -> the serving hot path        (:473-624)
  GET  /reload         -> hot-swap to latest trained instance (:626-632)
  GET  /stop           -> undeploy                    (:634-642)
  GET  /plugins.json   -> plugin descriptions         (:647-668)
  GET  /plugins/<type>/<name>/... -> plugin REST      (:670-691)

Deploy path parity: load the EngineInstance + its pickled models from
MODELDATA, ``engine.prepare_deploy`` (re-train sharded models / resolve
PersistentModel manifests), instantiate algorithms + serving via doer
(reference createServerActorWithEngine :197-250). The feedback loop posts
``predict`` events (entityType ``pio_pr``, fresh 64-char prId) back to the
Event Server (:509-579), and per-request bookkeeping tracks
requestCount / avg / last serving seconds (:586-593).

TPU-first divergence (deliberate): where the reference predicts per
request, sequentially per algorithm (:497-500, "TODO: Parallelize"),
queries here flow through a **micro-batching executor** — concurrent
requests are coalesced for up to ``batch_window_ms`` and served as ONE
batched device predict (`BaseAlgorithm.batch_predict`, e.g. a single
[B, k] x [k, n_items] MXU matmul + top_k for the recommendation engine),
so throughput scales with batch size instead of request count.
"""

from __future__ import annotations

import collections
import concurrent.futures
import copy
import dataclasses
import datetime as _dt
import html
import itertools
import json
import logging
import queue
import secrets
import string
import threading
import time
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from predictionio_tpu.api.engine_plugins import (
    EngineServerPlugin,
    EngineServerPluginContext,
)
from predictionio_tpu.api.aio_http import TRANSPORTS, make_http_server
from predictionio_tpu.controller.engine import Engine, EngineParams
from predictionio_tpu.data.storage import Storage, get_storage
from predictionio_tpu.utils import compilation_cache as _cc
from predictionio_tpu.utils import device_ledger as _ledger
from predictionio_tpu.utils import health as _health
from predictionio_tpu.utils import metrics as _metrics
from predictionio_tpu.utils import tracing as _tracing
from predictionio_tpu.utils.serialize import loads_model
from predictionio_tpu.workflow import experiment as _experiment
from predictionio_tpu.workflow import quality as _quality
from predictionio_tpu.workflow.context import WorkflowContext
from predictionio_tpu.workflow.workflow_params import WorkflowParams

logger = logging.getLogger(__name__)


def _version_of(deployed) -> str:
    """The model-version label of a deployed engine: the persisted
    round's engine instance id (test doubles without one label as
    'unknown')."""
    inst = getattr(deployed, "engine_instance", None)
    return str(getattr(inst, "id", None) or "unknown")

_ALPHANUMERIC = string.ascii_letters + string.digits

# byte -> alphanumeric translation table: one 64-byte CSPRNG read per
# prId instead of 64 secrets.choice draws (each a fresh urandom-backed
# randbelow) on the feedback hot path. The %62 fold weights the first
# 256%62=8 characters 5/256 vs 4/256 — ~0.04 bit of entropy per char
# below uniform, irrelevant for a 64-char correlation id.
_PR_ID_TABLE = bytes(
    ord(_ALPHANUMERIC[b % len(_ALPHANUMERIC)]) for b in range(256)
)


def _gen_pr_id() -> str:
    """64-char alphanumeric prId (reference CreateServer.scala:525)."""
    return secrets.token_bytes(64).translate(_PR_ID_TABLE).decode("ascii")


@dataclasses.dataclass
class ServerConfig:
    """Reference ServerConfig (CreateServer.scala:80-96)."""

    ip: str = "localhost"
    port: int = 8000
    engine_instance_id: Optional[str] = None
    feedback: bool = False
    event_server_ip: str = "localhost"
    event_server_port: int = 7070
    access_key: Optional[str] = None
    batch: str = ""
    # micro-batching knobs (TPU addition)
    batch_window_ms: float = 2.0
    max_batch: int = 128
    # Daily self upgrade check (reference CreateServer.scala:253-260 runs
    # UpgradeCheckRunner every 1 day): best-effort, on a background
    # thread, never blocks serving; status.json reports the last result.
    # 0 disables. The first check waits initial_delay so short-lived
    # servers (tests, benches) never place the outbound call at all.
    upgrade_check_interval_s: float = 86400.0
    upgrade_check_initial_delay_s: float = 10.0
    # Batches allowed in flight at once: 2 = double-buffering, so batch
    # k+1's device dispatch overlaps batch k's result fetch. CONTRACT:
    # depth > 1 means serve_batch (supplement -> batch_predict -> serve)
    # runs CONCURRENTLY on the deployed engine, so controller code must
    # not mutate shared state without locking. The default is 1 — the
    # reference serves strictly serially (CreateServer.scala:473-624),
    # and a user engine with mutable predict-time state (a cache dict, a
    # lazily-built index) is legal under that API and would silently race
    # at depth 2. The packaged templates are pure: deploy them with
    # `--pipeline-depth 2` to overlap device dispatch with result fetch.
    pipeline_depth: int = 1
    # REST transport: "async" = the event-loop frontend (api/aio_http.py,
    # in-flight queries are queue entries awaited as futures — the
    # collector can fill max_batch-sized device batches under load);
    # "threaded" = the stdlib thread-per-connection fallback.
    transport: str = "async"
    # feedback posts queue here when the event server lags; beyond this
    # the OLDEST pending post is dropped (and counted in status.json's
    # feedbackQueueDropped) — a down event server must not grow the
    # queue without bound
    feedback_queue_max: int = 4096
    # bind with SO_REUSEPORT so several engine-server PROCESSES share
    # one port (the `pio deploy --workers` fleet; the kernel balances
    # accepted connections across workers)
    reuse_port: bool = False
    # comma-separated jax device indices this server's prepared serving
    # state pins to (e.g. "0" for one chip per SO_REUSEPORT worker,
    # "0,1" for a 2-device mesh slice). None = the full default mesh.
    # The pinned mesh is what prepare_serving row-shards the resident
    # item factors over (ops/retrieval.py).
    serving_devices: Optional[str] = None
    # prediction capture (workflow/quality.py): every Nth served query
    # is recorded into the bounded process-global capture ring —
    # (query, result ids/scores, version, trace id) — dumped at the
    # gated GET /debug/predictions.json and replayable via `pio
    # replay`. 1 = every query, 0 disables capture entirely.
    capture_sample: int = 1
    # how many displaced DeployedEngines a /reload swap keeps prepared
    # (warm, factors resident) in the server's LRU — the reference's
    # multi-variant admin tier, and the promotion pipeline's instant-
    # rollback store. Evicted entries drain (last in-flight batch
    # resolves) and then release their device buffers. 0 = drain +
    # release immediately on swap.
    retained_states: int = 1

    def __post_init__(self):
        if self.feedback and not self.access_key:
            raise ValueError(
                "feedback loop requires access_key "
                "(reference CreateServer.scala:139-143)"
            )
        if self.transport not in TRANSPORTS:
            raise ValueError(
                f"unknown transport {self.transport!r} "
                f"(expected one of {TRANSPORTS})"
            )


def _mesh_from_device_spec(spec: str):
    """A 1-D data mesh over the named jax device indices ("0" or
    "0,2,3"): each `pio deploy --workers` worker pins its prepared
    serving state to its own device or mesh slice."""
    import jax

    from predictionio_tpu.parallel.mesh import make_mesh

    idxs = [int(p) for p in str(spec).split(",") if p.strip() != ""]
    devs = jax.devices()
    bad = [i for i in idxs if not 0 <= i < len(devs)]
    if not idxs or bad:
        raise ValueError(
            f"serving_devices {spec!r} names invalid device indices "
            f"{bad} (have {len(devs)} devices)"
        )
    return make_mesh({"data": len(idxs)}, [devs[i] for i in idxs])


class DeployedEngine:
    """Immutable serving state for one engine instance: instantiated
    algorithms + serving + deployable models."""

    def __init__(
        self,
        engine: Engine,
        engine_params: EngineParams,
        engine_instance,
        models: List[Any],
        ledger_scope: Optional["_ledger.LedgerScope"] = None,
    ):
        self.engine = engine
        self.engine_params = engine_params
        self.engine_instance = engine_instance
        _, _, self.algorithms, self.serving = engine.make_components(engine_params)
        self.models = models
        if len(self.models) != len(self.algorithms):
            raise ValueError(
                f"{len(self.models)} models for {len(self.algorithms)} algorithms"
            )
        # HBM residency ledger scope: device buffers registered during
        # this instance's prepare/warm are grouped under its engine-
        # instance id, so release() can assert THEY reached zero — even
        # with a same-version twin resident (the bare-/reload case).
        # from_storage hands in the scope that already covers
        # prepare_deploy; direct construction gets a fresh one.
        self._ledger_scope = ledger_scope or _ledger.get_ledger().scope(
            str(getattr(engine_instance, "id", None) or "unknown")
        )
        # compile serving executables before taking traffic (cold compiles
        # cost seconds and would land on the first unlucky requests);
        # persist them so the NEXT deploy of this engine skips the
        # compiles entirely
        from predictionio_tpu.utils.compilation_cache import (
            ensure_compilation_cache,
        )

        ensure_compilation_cache()
        with self._ledger_scope.activate():
            for algo, model in zip(self.algorithms, self.models):
                algo.warm(model)
        # in-flight batch accounting: the promotion pipeline's drain
        # stage waits on this before freeing the displaced instance's
        # device-resident serving state (release_serving). The condition
        # also serializes release() against new serve_batch entrants, so
        # a straggler that races past a swap either runs on the intact
        # device state or — after release — on the algorithms' host
        # fallback path, never on half-freed buffers.
        self._inflight = 0
        self._inflight_cond = threading.Condition()
        self._released = False

    @classmethod
    def from_storage(
        cls,
        engine: Engine,
        storage: Optional[Storage] = None,
        engine_instance_id: Optional[str] = None,
        engine_id: Optional[str] = None,
        engine_version: Optional[str] = None,
        engine_variant: Optional[str] = None,
        ctx: Optional[WorkflowContext] = None,
        workflow_params: Optional[WorkflowParams] = None,
    ) -> "DeployedEngine":
        """Reference createServerActorWithEngine (CreateServer.scala:197-250):
        resolve the instance (given id, or latest COMPLETED — scoped to
        (engine_id, engine_version, engine_variant) when given, as the
        reference Console.deploy does via getLatestCompleted), deserialize
        its models, prepare_deploy."""
        storage = storage or get_storage()
        ctx = ctx or WorkflowContext(mode="Serving", storage=storage)
        instances = storage.get_meta_data_engine_instances()
        if engine_instance_id is not None:
            instance = instances.get(engine_instance_id)
            if instance is None:
                raise ValueError(
                    f"engine instance {engine_instance_id!r} does not exist"
                )
        elif engine_id is not None:
            instance = instances.get_latest_completed(
                engine_id, engine_version or "", engine_variant or ""
            )
            if instance is None:
                raise ValueError(
                    f"no COMPLETED engine instance for engine {engine_id!r} "
                    f"version {engine_version!r} variant {engine_variant!r}; "
                    "run train first"
                )
        else:
            completed = [
                i for i in instances.get_all() if i.status == "COMPLETED"
            ]
            if not completed:
                raise ValueError(
                    "no COMPLETED engine instance found; run train first"
                )
            instance = max(completed, key=lambda i: i.start_time)
        engine_params = engine.engine_instance_to_engine_params(instance)
        blob = storage.get_model_data_models().get(instance.id)
        if blob is None:
            raise ValueError(
                f"no persisted models for engine instance {instance.id!r}"
            )
        persisted = loads_model(blob.models)
        # the ledger scope opens BEFORE prepare_deploy: prepare_serving
        # parks the resident factors/masks on device in there, and those
        # registrations must carry this instance's owner label
        scope = _ledger.get_ledger().scope(str(instance.id))
        with scope.activate():
            models = engine.prepare_deploy(
                ctx,
                engine_params,
                instance.id,
                persisted,
                workflow_params or WorkflowParams(),
            )
        return cls(
            engine, engine_params, instance, models, ledger_scope=scope
        )

    # --- the serving pipeline over one coalesced batch ---

    def serve_batch(self, queries: Sequence[Any]) -> List[Any]:
        """supplement each -> ONE batch_predict per algorithm -> serve each
        with its original query (reference Engine.scala:769-810 eval path
        applies the same supplement/batch/serve order).

        May be called concurrently (up to ServerConfig.pipeline_depth
        batches in flight): algorithms/serving with mutable predict-time
        state must lock it or deploy with pipeline_depth=1."""
        with self._inflight_cond:
            self._inflight += 1
        try:
            supplemented = [self.serving.supplement(q) for q in queries]
            indexed = list(enumerate(supplemented))
            per_algo: List[Dict[int, Any]] = [
                dict(algo.batch_predict(model, indexed))
                for algo, model in zip(self.algorithms, self.models)
            ]
            return [
                self.serving.serve(q, [pa[i] for pa in per_algo])
                for i, q in enumerate(queries)
            ]
        finally:
            with self._inflight_cond:
                self._inflight -= 1
                self._inflight_cond.notify_all()

    # --- drain/release: the promotion pipeline's displaced-instance
    # lifecycle (free resident device factors only after the last
    # in-flight batch resolves) ---

    @property
    def inflight(self) -> int:
        with self._inflight_cond:
            return self._inflight

    @property
    def released(self) -> bool:
        return self._released

    def drain(self, timeout_s: float, on_progress=None) -> bool:
        """Wait (bounded) for every in-flight serve_batch to resolve.
        ``on_progress`` fires whenever the in-flight count moves — the
        promotion pipeline feeds it the watchdog heartbeat's ``beat``,
        so a drain that is MAKING progress never reads as stalled while
        a wedged one degrades /readyz once the deadline passes."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._inflight_cond:
            last = self._inflight
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._inflight_cond.wait(min(0.2, remaining))
                if self._inflight != last:
                    last = self._inflight
                    if on_progress is not None:
                        on_progress()
        return True

    def release(self, timeout_s: float = 0.0) -> bool:
        """Free the device-resident serving state (each algorithm's
        ``release_serving``) once nothing is in flight; returns whether
        it released. The hooks run UNDER the in-flight condition, so a
        serve_batch racing in behind the release observes the nulled
        device state (and takes the host fallback path) — never a
        half-freed buffer. A straggler that keeps the state wedged past
        ``timeout_s`` blocks the release: its buffers are freed by
        refcount when it finally resolves, never underneath it."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._inflight_cond:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._inflight_cond.wait(min(0.2, remaining))
            if self._released:
                return True
            self._released = True
            for algo, model in zip(self.algorithms, self.models):
                try:
                    algo.release_serving(model)
                except Exception:
                    logger.exception(
                        "release_serving failed for %s", type(algo).__name__
                    )
        # the monitored release invariant (the PR 13 leak class): every
        # device buffer this instance registered during prepare/warm
        # must be back to zero now — nonzero counts in
        # pio_device_ledger_leaks_total and logs, instead of silently
        # pinning HBM until the process dies. (A straggler that raced
        # past the swap rebuilds serving state OUTSIDE this scope — the
        # transient shows up as component bytes and drift, never as a
        # false leak here.)
        self._ledger_scope.check_released()
        return True

    def ledger_bytes(self) -> int:
        """Device bytes currently registered under this instance's
        ledger scope (tests + status detail)."""
        return self._ledger_scope.bytes()


class _BatchingExecutor:
    """Coalesces concurrent requests into device-sized batches.

    Requests enqueue (query, future); one collector thread drains the
    queue — waiting up to window_ms after the first arrival — and hands
    each batch to a serve pool holding up to ``pipeline_depth`` batches
    in flight. ``submit_nowait`` returns the
    ``concurrent.futures.Future`` directly: the event-loop frontend
    awaits it, so an in-flight query is a queue entry, not a parked OS
    thread, and the collector can actually accumulate ``max_batch``-
    sized device batches under load. ``submit`` is the blocking wrapper
    the threaded transport (and in-process callers) use.

    The default depth is 1: strictly serial serving, the reference's
    contract (CreateServer.scala:473-624), safe for engines with mutable
    predict-time state. Depth 2 (opt-in, see ServerConfig.pipeline_depth)
    double-buffers: while batch k's result fetch is crossing
    host<->device (or, on a relay rig, the network), batch k+1 already
    dispatched and batch k+2 accumulates behind the semaphore — the
    device never idles waiting on a fetch.
    """

    _STOP = object()  # collector-thread shutdown sentinel

    def __init__(self, window_ms: float, max_batch: int, pipeline_depth: int = 1):
        self.window_ms = window_ms
        self.max_batch = max_batch
        self.pipeline_depth = max(1, pipeline_depth)
        self._queue: "queue.Queue" = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._closed = False
        self._inflight = threading.Semaphore(self.pipeline_depth)
        self._serve_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self.pipeline_depth, thread_name_prefix="serve"
        )
        # collector batch-size accounting (served-group granularity, the
        # actual device batch): proves micro-batches coalesce under load.
        # The instrument is the process-global registry's mergeable
        # histogram (the /metrics family), labeled by the MODEL VERSION
        # the batch was served from — a /reload swap's fill profile is
        # diffable per version straight off /metrics. stats() reports
        # the all-versions delta since THIS executor was constructed.
        self._m_batch_fill = _metrics.get_registry().histogram(
            "pio_serving_batch_fill",
            "Queries per served micro-batch (the device batch size), "
            "by model version",
            labels=("version",),
            buckets=_metrics.BATCH_SIZE_BUCKETS,
        )
        self._m_batch_bases = {
            key[0]: child.snapshot()
            for key, child in self._m_batch_fill.children()
        }
        # watchdog: a serve_batch wedged in a stuck device/relay call
        # degrades /readyz once it overruns the deadline (executors of
        # one process share the heartbeat — either stalling is a
        # process-level routing signal); idle executors never stall
        self._hb = _health.heartbeat("serving-executor", deadline_s=120.0)

    def submit_nowait(
        self,
        deployed: DeployedEngine,
        query: Any,
        trace: Optional["_tracing.TraceContext"] = None,
    ) -> "concurrent.futures.Future":
        """Enqueue one query; the returned future resolves to its
        prediction (or raises its per-query error) once the micro-batch
        it rides is served. ``trace`` (the request's trace id + the http
        span id) rides the queue entry so the executor can record the
        batch/predict spans under the request's trace."""
        fut: "concurrent.futures.Future" = concurrent.futures.Future()
        tinfo = None
        if trace is not None:
            # the batch span id is minted NOW so the predict span can
            # parent on it even though both are recorded at serve time
            tinfo = (trace, _tracing.new_span_id(), time.time())
        # the closed-check and the enqueue share the lock with close()'s
        # sentinel post, so a request can never land behind _STOP in the
        # queue (its future would never resolve)
        with self._lock:
            if self._closed:
                raise RuntimeError("server is shutting down")
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(target=self._run, daemon=True)
                self._worker.start()
            self._queue.put((deployed, query, fut, tinfo))
        return fut

    def submit(self, deployed: DeployedEngine, query: Any) -> Any:
        return self.submit_nowait(deployed, query).result()

    def stats(self) -> Dict[str, Any]:
        """Served-batch accounting since this executor was constructed
        (merged across model versions): count, mean fill, bucketed size
        histogram (keys are the registry histogram's bucket upper
        bounds)."""
        snaps = []
        for key, child in self._m_batch_fill.children():
            snap = child.snapshot()
            base = self._m_batch_bases.get(key[0])
            if base is not None:
                snap = snap.delta(base)
            snaps.append(snap)
        if snaps:
            snap = _metrics.merge_snapshots(snaps)
        else:
            bounds = self._m_batch_fill.bounds
            snap = _metrics.HistogramSnapshot(
                bounds, (0,) * (len(bounds) + 1), 0.0, 0
            )
        # counts has one +Inf overflow slot beyond the finite bounds: a
        # batch larger than the last bound (max_batch is user-settable
        # past 1024) must not vanish from the histogram view
        hist = {
            int(bound): c
            for bound, c in zip(snap.bounds, snap.counts)
            if c
        }
        out = {
            "batches": snap.count,
            "queries": int(snap.sum),
            "batch_fill_mean": (snap.sum / snap.count) if snap.count else 0.0,
            "batch_size_histogram": hist,
        }
        if snap.counts[-1]:
            out["batch_size_overflow"] = snap.counts[-1]
        return out

    def close(self) -> None:
        """Stop the collector thread and release the serve-pool workers
        (a stopped/undeployed server must not leak threads for the
        process lifetime). In-flight batches finish; later submits fail."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            worker = self._worker
            self._queue.put(self._STOP)
        if worker is not None and worker.is_alive():
            worker.join(timeout=10.0)
        # wait=False so a wedged serve_batch (a stuck device/relay call)
        # cannot hang THIS call forever, mirroring the bounded collector
        # join above. The guarantee is only that close() returns: a truly
        # wedged batch still blocks its request threads (their slots
        # never resolve) and, since pool workers are non-daemon, still
        # blocks interpreter exit — same as the reference's in-flight
        # Futures on undeploy.
        self._serve_pool.shutdown(wait=False)

    def _run(self) -> None:
        while True:
            first = self._queue.get()
            if first is self._STOP:
                return
            batch = [first]
            deadline = time.monotonic() + self.window_ms / 1000.0
            while len(batch) < self.max_batch:
                timeout = deadline - time.monotonic()
                if timeout <= 0:
                    break
                try:
                    item = self._queue.get(timeout=timeout)
                except queue.Empty:
                    break
                if item is self._STOP:
                    self._queue.put(item)  # re-post for the outer loop
                    break
                batch.append(item)
            # group by deployed engine (a reload may be in flight)
            groups: Dict[int, List[tuple]] = {}
            for item in batch:
                groups.setdefault(id(item[0]), []).append(item)
            for items in groups.values():
                # a future the transport cancelled (client gone before
                # its batch formed) is dropped here; marking the rest
                # RUNNING pins them against late cancellation
                items = [
                    it for it in items
                    if it[2].set_running_or_notify_cancel()
                ]
                if not items:
                    continue
                self._m_batch_fill.labels(
                    version=_version_of(items[0][0])
                ).observe(len(items))
                # blocks while pipeline_depth batches are in flight — the
                # next batch keeps accumulating in self._queue meanwhile
                self._inflight.acquire()
                try:
                    self._serve_pool.submit(
                        self._serve_and_release, items[0][0], items
                    )
                except RuntimeError as e:
                    # pool shut down mid-close (a >join-timeout batch was
                    # in flight): fail these futures instead of leaving
                    # their waiters pending forever
                    self._inflight.release()
                    for _, _, f, _ in items:
                        f.set_exception(
                            RuntimeError(f"server is shutting down: {e}")
                        )

    def _serve_and_release(self, dep: DeployedEngine, items) -> None:
        t0 = time.time()
        outcomes: List[tuple] = []
        # the batch runs under a serving compile_site (any executable
        # compile inside is a COLD compile: counted per site, span-
        # recorded, and drained below onto the predict span) and under
        # the first traced item's ambient trace, so a compile span
        # chains into the request's trace tree
        batch_trace = next(
            (t[0] for _, _, _, t in items if t is not None), None
        )
        compile_events: List[dict] = []
        try:
            with self._hb.busy(), _cc.compile_site("serving"), \
                    _tracing.use(batch_trace):
                try:
                    self._serve_isolating(dep, items, outcomes)
                finally:
                    compile_events = _cc.drain_compile_events()
        finally:
            self._inflight.release()
            t1 = time.time()
            for _, _, _, tinfo in items:
                if tinfo is None:
                    continue
                trace, batch_span_id, enqueued = tinfo
                # predict: the device serve_batch call (incl. bisect
                # retries); batch: queue wait + serve, the executor's
                # whole share of the request
                predict_attrs: Dict[str, Any] = {"batch_size": len(items)}
                if compile_events:
                    predict_attrs["cold_compiles"] = compile_events
                _tracing.record_span(
                    "predict", trace.trace_id, parent_id=batch_span_id,
                    start_s=t0, duration_s=t1 - t0,
                    attrs=predict_attrs,
                )
                _tracing.record_span(
                    "batch", trace.trace_id, span_id=batch_span_id,
                    parent_id=trace.span_id, start_s=enqueued,
                    duration_s=t1 - enqueued,
                )
            # futures resolve strictly AFTER the batch/predict spans are
            # recorded: a client that got its response may immediately
            # read /debug/traces.json and must find the whole chain
            for f, exc, result in outcomes:
                if exc is not None:
                    f.set_exception(exc)
                else:
                    f.set_result(result)

    def _serve_isolating(
        self, dep: DeployedEngine, items, outcomes: List[tuple]
    ) -> None:
        """Serve a batch; on failure bisect it so the poison query is
        located in O(log n) batched calls and its batchmates still get
        batched service (a serial per-query retry would multiply every
        innocent's latency by the batch size). Outcomes are collected as
        (future, exception, result) rather than resolved here so the
        caller controls when waiters wake."""
        try:
            results = dep.serve_batch([q for _, q, _, _ in items])
            for (_, _, f, _), r in zip(items, results):
                outcomes.append((f, None, r))
        except Exception as e:
            if len(items) == 1:
                outcomes.append((items[0][2], e, None))
                return
            mid = len(items) // 2
            self._serve_isolating(dep, items[:mid], outcomes)
            self._serve_isolating(dep, items[mid:], outcomes)


class QueryAPI:
    """Transport-independent request core for the engine server."""

    def __init__(
        self,
        deployed: DeployedEngine,
        config: Optional[ServerConfig] = None,
        plugin_context: Optional[EngineServerPluginContext] = None,
        reload_fn=None,
        stop_fn=None,
        experiment_start_fn=None,
        experiment_stop_fn=None,
    ):
        self.deployed = deployed
        self.config = config or ServerConfig()
        self.plugin_context = plugin_context or EngineServerPluginContext()
        self._reload_fn = reload_fn
        self._stop_fn = stop_fn
        self._experiment_start_fn = experiment_start_fn
        self._experiment_stop_fn = experiment_stop_fn
        # active experiment (sticky multi-variant serving). Reads on the
        # hot path take one reference snapshot — no lock: CPython
        # attribute assignment is atomic, and routing itself is a pure
        # hash of (salt, user_key), so workers need no shared state.
        self._experiment: Optional[_experiment.ActiveExperiment] = None
        self._executor = _BatchingExecutor(
            self.config.batch_window_ms,
            self.config.max_batch,
            self.config.pipeline_depth,
        )
        # non-query routes under the async transport run here, not on
        # the event loop: /plugins/... executes third-party handle_rest
        # code of unknown cost, and one blocking call inline on the
        # single-threaded loop would stall every connection
        self._route_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="qroutes"
        )
        self.server_start_time = _dt.datetime.now(_dt.timezone.utc)
        # upgrade-check fields only; every serving stat lives in the
        # process-global metrics registry (per-child locks, no shared
        # hot-path lock)
        self._stats_lock = threading.Lock()
        # serving instruments: process-global families (the /metrics
        # exposition), read as deltas against construction-time
        # snapshots for this instance's status.json. The mergeable
        # log-bucket histogram replaces the old 512-sample reservoir —
        # a reservoir cannot aggregate across SO_REUSEPORT workers;
        # bucket vectors add.
        reg = _metrics.get_registry()
        # per-VERSION attribution: every serving family carries the
        # model version (the deployed engine instance id), so a /reload
        # swap's latency and quality are diffable per version off one
        # /metrics scrape. Requests record under the version of the
        # DeployedEngine snapshot that actually served them, so the two
        # versions' sample windows around a swap are disjoint.
        self._m_latency_fam = reg.histogram(
            "pio_serving_latency_seconds",
            "End-to-end /queries.json serving latency, by model version",
            labels=("version",),
            buckets=_metrics.LATENCY_BUCKETS_S,
        )
        self._m_requests_fam = reg.counter(
            "pio_serving_requests_total",
            "Completed /queries.json requests, by model version",
            labels=("version",),
        )
        self._m_last_fam = reg.gauge(
            "pio_serving_last_seconds",
            "Latency of the most recent served query, by model version",
            labels=("version",),
        )
        self._m_model_info = reg.gauge(
            "pio_model_info",
            "1 for the model version this server is actively serving, "
            "0 for versions it swapped out",
            labels=("engine", "version"),
        )
        self._m_feedback_dropped = reg.counter(
            "pio_feedback_queue_dropped_total",
            "Feedback posts dropped because the bounded queue was full",
        )
        # experimentation plane: per-arm allocation counts plus the
        # experiment's presence/split, federable off the same scrape as
        # every per-version family (the variant id IS the version label
        # on those)
        self._m_exp_requests = reg.counter(
            "pio_experiment_requests_total",
            "Queries served per experiment arm (variant = the arm's "
            "engine instance id)",
            labels=("experiment", "variant"),
        )
        self._m_exp_info = reg.gauge(
            "pio_experiment_info",
            "Traffic split fraction per experiment arm while the "
            "experiment runs; 0 once it stops",
            labels=("experiment", "variant"),
        )
        # per-instance "since this server deployed" views: snapshot every
        # pre-existing version child now (the families are process-global
        # and other servers may have populated them); versions this
        # server binds later enter the tables at bind time (zero for
        # fresh children)
        self._lat_bases: Dict[str, _metrics.HistogramSnapshot] = {
            vid: child.snapshot()
            for (vid,), child in self._m_latency_fam.children()
        }
        self._req_bases: Dict[str, float] = {
            vid: child.value
            for (vid,), child in self._m_requests_fam.children()
        }
        self._feedback_dropped_base = self._m_feedback_dropped.snapshot()
        self._capture_count = itertools.count(1)
        self._bind_version_metrics(deployed)
        # /readyz: a deployed model with its serving components is the
        # engine server's one hard readiness requirement; daemon-stall
        # checks (executor, feedback drainer, continuous trainer) are
        # global. ttl 0: the check is attribute reads, no caching needed.
        self._ready_probes = (
            _health.TTLProbe("model", self._probe_model, ttl_s=0.0),
        )
        # feedback posts drain on ONE daemon worker (not a thread per
        # request — that would throttle the micro-batched hot path). The
        # queue is BOUNDED (config.feedback_queue_max): a down event
        # server drops the oldest pending post instead of growing the
        # queue without limit; drops are counted for status.json.
        self._feedback_queue: "queue.Queue" = queue.Queue(
            maxsize=max(1, self.config.feedback_queue_max)
        )
        self._feedback_worker: Optional[threading.Thread] = None
        self._feedback_lock = threading.Lock()
        self._feedback_closed = False
        # daily upgrade self-check (reference CreateServer.scala:253-260)
        self._upgrade_status: Optional[str] = None
        self._upgrade_checked_at: Optional[str] = None
        self._upgrade_stop = threading.Event()
        if self.config.upgrade_check_interval_s > 0:
            threading.Thread(
                target=self._upgrade_check_loop, daemon=True
            ).start()

    def _bind_version_metrics(self, deployed) -> None:
        """Point the current-version instrument handles at ``deployed``'s
        model version and flip ``pio_model_info`` — called at
        construction and by :meth:`bind_deployed` on every /reload swap.
        """
        vid = _version_of(deployed)
        inst = getattr(deployed, "engine_instance", None)
        engine_label = str(
            getattr(inst, "engine_id", None)
            or getattr(inst, "engine_factory", None)
            or "unknown"
        )
        self._m_latency = self._m_latency_fam.labels(version=vid)
        self._m_requests = self._m_requests_fam.labels(version=vid)
        self._m_last = self._m_last_fam.labels(version=vid)
        if vid not in self._lat_bases:
            self._lat_bases[vid] = self._m_latency.snapshot()
        if vid not in self._req_bases:
            self._req_bases[vid] = self._m_requests.value
        # compat handles for the current version's "since deployed" view
        self._lat_base = self._lat_bases[vid]
        self._requests_base = self._req_bases[vid]
        self._m_model_info.labels(engine=engine_label, version=vid).set(1)
        self._active_model_label = (engine_label, vid)

    def bind_deployed(self, deployed) -> None:
        """Swap the serving snapshot (the /reload path): queries in
        flight keep the old DeployedEngine and keep recording under its
        version label; new queries record under the new one — the two
        versions' sample windows are disjoint by construction."""
        old_label = getattr(self, "_active_model_label", None)
        self.deployed = deployed
        self._bind_version_metrics(deployed)
        if old_label is not None and old_label != self._active_model_label:
            self._m_model_info.labels(
                engine=old_label[0], version=old_label[1]
            ).set(0)

    # --- experimentation plane (sticky multi-variant serving) ---

    def set_experiment(self, active: "_experiment.ActiveExperiment") -> None:
        """Bind an :class:`ActiveExperiment`: subsequent queries route
        by the sticky allocation hash to the arm's own DeployedEngine
        (so every per-version family is per-variant for free)."""
        for vid, frac in zip(active.spec.variants, active.spec.split):
            self._m_exp_info.labels(
                experiment=active.spec.name, variant=vid
            ).set(frac)
        self._experiment = active

    def clear_experiment(self) -> Optional["_experiment.ActiveExperiment"]:
        """Unbind the running experiment (allocation stops immediately;
        in-flight queries finish on the arm that served them). Returns
        the displaced ActiveExperiment so the server can retire its
        engines."""
        active = self._experiment
        self._experiment = None
        if active is not None:
            for vid in active.spec.variants:
                self._m_exp_info.labels(
                    experiment=active.spec.name, variant=vid
                ).set(0)
        return active

    def experiment_status(self) -> Optional[Dict[str, Any]]:
        active = self._experiment
        if active is None:
            return None
        status = active.status()
        requests = {}
        for (exp, vid), child in self._m_exp_requests.children():
            if exp == active.spec.name:
                requests[vid] = child.value
        status["requests"] = requests
        return status

    def _serving_totals(self) -> Tuple["_metrics.HistogramSnapshot", int]:
        """Latency histogram + request count summed across every model
        version this server served, as deltas against the construction/
        bind-time bases — the status.json 'since this server deployed'
        view over the labeled process-global families."""
        snaps = []
        for (vid,), child in self._m_latency_fam.children():
            snap = child.snapshot()
            base = self._lat_bases.get(vid)
            if base is not None:
                snap = snap.delta(base)
            snaps.append(snap)
        if snaps:
            lat = _metrics.merge_snapshots(snaps)
        else:
            bounds = self._m_latency_fam.bounds
            lat = _metrics.HistogramSnapshot(
                bounds, (0,) * (len(bounds) + 1), 0.0, 0
            )
        requests = 0
        for (vid,), child in self._m_requests_fam.children():
            requests += int(child.value - self._req_bases.get(vid, 0.0))
        return lat, requests

    def _upgrade_check_loop(self) -> None:
        from predictionio_tpu.tools.upgrade import check_for_upgrade

        if self._upgrade_stop.wait(self.config.upgrade_check_initial_delay_s):
            return
        while not self._upgrade_stop.is_set():
            status = check_for_upgrade()
            with self._stats_lock:
                self._upgrade_status = status
                self._upgrade_checked_at = _dt.datetime.now(
                    _dt.timezone.utc
                ).isoformat()
            logger.info("upgrade check: %s", status)
            self._upgrade_stop.wait(self.config.upgrade_check_interval_s)

    _FEEDBACK_STOP = object()

    def close(self) -> None:
        """Release serving resources (the batching executor's collector,
        serve-pool, feedback, and upgrade-check threads) when the server
        stops or undeploys."""
        self._upgrade_stop.set()
        self._executor.close()
        # wait=False: an in-flight route (e.g. /stop itself, whose timer
        # invoked this close) must not deadlock the teardown
        self._route_pool.shutdown(wait=False)
        with self._feedback_lock:
            self._feedback_closed = True
            worker = self._feedback_worker
            # the queue is bounded now: drain pending posts (they are
            # best-effort and the server is stopping) so the sentinel
            # put cannot hit a full queue. Producers hold
            # _feedback_lock too and check _feedback_closed first, so
            # nothing can refill the queue between the drain and the
            # sentinel put.
            try:
                while True:
                    self._feedback_queue.get_nowait()
            except queue.Empty:
                pass
            self._feedback_queue.put_nowait(self._FEEDBACK_STOP)
        if worker is not None and worker.is_alive():
            worker.join(timeout=10.0)

    def _enqueue_feedback(self, item) -> None:
        """Bounded, drop-oldest enqueue: when the event server lags or
        is down, the newest prediction wins a slot and the oldest
        pending post is counted dropped — memory stays bounded. Holds
        _feedback_lock so it serializes with close()'s drain+sentinel
        (an enqueue can neither land after the stop sentinel nor drop
        it)."""
        with self._feedback_lock:
            if self._feedback_closed:
                return  # feedback is best-effort; server is stopping
            while True:
                try:
                    self._feedback_queue.put_nowait(item)
                    return
                except queue.Full:
                    try:
                        self._feedback_queue.get_nowait()
                    except queue.Empty:
                        continue  # the worker drained it; retry the put
                    self._m_feedback_dropped.inc()

    def _ensure_feedback_worker(self) -> None:
        with self._feedback_lock:
            if self._feedback_closed:
                return  # feedback is best-effort; server is stopping
            if self._feedback_worker is None or not self._feedback_worker.is_alive():
                self._feedback_worker = threading.Thread(
                    target=self._drain_feedback, daemon=True
                )
                self._feedback_worker.start()

    def _drain_feedback(self) -> None:
        # watchdog (busy only around the post: an empty queue is idle,
        # not stalled); the urlopen timeout bounds each unit at 10 s
        hb = _health.heartbeat("feedback-drainer", deadline_s=60.0)
        while True:
            item = self._feedback_queue.get()
            if item is self._FEEDBACK_STOP:
                return
            url, data, tinfo = item if len(item) == 3 else (*item, None)
            with hb.busy():
                if tinfo is None:
                    self._post_feedback(url, data)
                    continue
                # propagate the serving trace onto the feedback POST and
                # record the hop: the event server's ingest spans parent
                # on this feedback-post span, which parents on the
                # request's http span
                trace_id, parent_span = tinfo
                span_id = _tracing.new_span_id()
                t0 = time.time()
                try:
                    self._post_feedback(
                        url, data,
                        headers={
                            _tracing.TRACE_HEADER: trace_id,
                            _tracing.PARENT_HEADER: span_id,
                        },
                    )
                finally:
                    _tracing.record_span(
                        "feedback-post", trace_id, span_id=span_id,
                        parent_id=parent_span, start_s=t0,
                        duration_s=time.time() - t0,
                    )

    def _post_feedback(self, url, data, headers=None) -> None:
        try:
            req = urllib.request.Request(
                url,
                data=json.dumps(data).encode("utf-8"),
                headers={
                    "Content-Type": "application/json",
                    **(headers or {}),
                },
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                if resp.status != 201:
                    logger.error(
                        "Feedback event failed. Status code: %d. Data: %s",
                        resp.status, json.dumps(data),
                    )
        except Exception as e:
            logger.error("Feedback event failed: %s", e)

    # --- dispatch ---

    def handle(
        self,
        method: str,
        path: str,
        query: Optional[Dict[str, str]] = None,
        body: Optional[bytes] = None,
        headers: Optional[Dict[str, str]] = None,
    ) -> Tuple[int, Any, str]:
        """Returns (status, payload, content_type)."""
        try:
            return self._route(method, path, query or {}, body, headers)
        except Exception as e:
            logger.exception("internal error handling %s %s", method, path)
            return 500, {"message": str(e)}, "application/json"

    def handle_nowait(
        self,
        method: str,
        path: str,
        query: Optional[Dict[str, str]] = None,
        body: Optional[bytes] = None,
        form: Optional[Dict[str, str]] = None,
        headers: Optional[Dict[str, str]] = None,
    ) -> Union[Tuple[int, Any, str], "concurrent.futures.Future"]:
        """Transport-facing dispatch for the event-loop frontend
        (api/aio_http.py): the /queries.json hot path returns a
        ``concurrent.futures.Future`` resolving to a
        (status, payload, content_type) tuple, so an in-flight query is
        a micro-batch queue entry — not a parked OS thread; every other
        route is offloaded to a small pool (plugin handle_rest code has
        unknown cost and must not run inline on the loop) whose future
        the loop awaits the same way. Parse errors answer inline."""
        if path == "/queries.json" and method == "POST":
            try:
                return self._handle_query_nowait(body, headers)
            except Exception as e:
                logger.exception(
                    "internal error handling POST /queries.json"
                )
                return 500, {"message": str(e)}, "application/json"
        if path == "/healthz" and method == "GET":
            # liveness inline on the loop (non-blocking dict build): a
            # route pool wedged by third-party plugin code must not make
            # the orchestrator restart an otherwise-serving process
            return 200, _health.liveness(), "application/json"
        try:
            return self._route_pool.submit(
                self.handle, method, path, query, body, headers
            )
        except RuntimeError:  # pool shut down: server is stopping
            return (
                503, {"message": "server is shutting down"},
                "application/json",
            )

    def _probe_model(self) -> None:
        dep = self.deployed
        if dep is None or not dep.models or not dep.algorithms:
            raise RuntimeError("no model deployed")

    def _route(
        self, method, path, query, body, headers=None
    ) -> Tuple[int, Any, str]:
        parts = [p for p in path.strip("/").split("/") if p]
        if not parts and method == "GET":
            return 200, self._status_html(), "text/html"
        if path == "/healthz" and method == "GET":
            return 200, _health.liveness(), "application/json"
        if path == "/readyz" and method == "GET":
            ok, payload = _health.readiness(self._ready_probes)
            return (200 if ok else 503), payload, "application/json"
        if path == "/status.json" and method == "GET":
            return 200, self._status_json(), "application/json"
        if path == "/metrics" and method == "GET":
            # refresh the pull-style device gauges on the way out: the
            # ledger-vs-memory_stats drift and the persistent
            # executable-cache size are point-in-time reads (cheap; a
            # handful of stat calls), so scrape time is the right time
            try:
                _ledger.get_ledger().reconcile()
                _cc.persistent_cache_stats()
            except Exception:
                logger.debug(
                    "device-gauge refresh failed", exc_info=True
                )
            return (
                200,
                _metrics.get_registry().render(),
                _metrics.render_content_type(),
            )
        if path == "/debug/traces.json" and method == "GET":
            return self._debug_traces(query)
        if path == "/debug/profile":
            return self._debug_profile(method, query)
        if path == "/debug/predictions.json" and method == "GET":
            return self._debug_predictions(query)
        if path == "/queries.json" and method == "POST":
            return self._handle_query(body, headers)
        if path == "/experiment.json" and method in ("GET", "POST"):
            # like /reload this is an operator surface: under the async
            # transport it runs on the route pool, so a start (which may
            # read + warm variant states from storage) never blocks the
            # event loop. When an access key is configured it is
            # required, matching the other mutating surfaces.
            return self._experiment_route(method, query, body)
        if path == "/reload" and method in ("GET", "POST"):
            # synchronous: the promotion pipeline (and any fleet
            # orchestrator) needs the success/failure verdict in the
            # response, and under the async transport this runs on the
            # route pool, never the event loop. ``engineInstanceId``
            # pins the target version so an SO_REUSEPORT fleet converges
            # on ONE instance instead of racing "latest"; omitted, the
            # reference's latest-COMPLETED semantics apply.
            if self._reload_fn is None:
                return 200, "Reloading... (no reload hook)", "text/plain"
            target_id = query.get("engineInstanceId") or None
            try:
                new_id = self._reload_fn(target_id)
            except Exception as e:
                # the swap never happened: the old snapshot keeps
                # serving, and the 500 names the cause (store down,
                # corrupt/missing instance) instead of a silent log line
                logger.exception("reload failed; keeping current instance")
                return (
                    500,
                    {
                        "message": (
                            f"reload failed ({type(e).__name__}: {e}); "
                            "still serving engine instance "
                            f"{_version_of(self.deployed)}"
                        )
                    },
                    "application/json",
                )
            return (
                200,
                f"Reloading... now serving engine instance {new_id}",
                "text/plain",
            )
        if path == "/stop" and method == "GET":
            if self._stop_fn is not None:
                t = threading.Timer(1.0, self._stop_fn)
                t.daemon = True
                t.start()
            return 200, "Shutting down...", "text/plain"
        if path == "/plugins.json" and method == "GET":
            return 200, self.plugin_context.describe(), "application/json"
        if parts and parts[0] == "plugins" and len(parts) >= 3 and method == "GET":
            plugin_type, plugin_name, args = parts[1], parts[2], parts[3:]
            table = (
                self.plugin_context.output_blockers
                if plugin_type == EngineServerPlugin.OUTPUT_BLOCKER
                else self.plugin_context.output_sniffers
            )
            if plugin_name not in table:
                return 404, {"message": f"Plugin {plugin_name} not found."}, "application/json"
            return 200, table[plugin_name].handle_rest(args), "application/json"
        return 404, {"message": "Not Found"}, "application/json"

    # --- experimentation surface ---

    def _experiment_route(
        self, method: str, query: Dict[str, str], body: Optional[bytes]
    ) -> Tuple[int, Any, str]:
        if self.config.access_key and not secrets.compare_digest(
            query.get("accessKey", ""), self.config.access_key
        ):
            return (
                401, {"message": "Invalid accessKey."}, "application/json"
            )
        if method == "GET":
            return (
                200,
                {"experiment": self.experiment_status()},
                "application/json",
            )
        try:
            payload = json.loads((body or b"").decode("utf-8"))
            if not isinstance(payload, dict):
                raise ValueError("body must be a JSON object")
        except Exception as e:
            return 400, {"message": str(e)}, "application/json"
        if payload.get("stop"):
            if self._experiment_stop_fn is None:
                return (
                    501,
                    {"message": "no experiment hook on this server"},
                    "application/json",
                )
            winner = payload.get("winner")
            report = self._experiment_stop_fn(
                winner=str(winner) if winner else None
            )
            return 200, report, "application/json"
        if self._experiment_start_fn is None:
            return (
                501,
                {"message": "no experiment hook on this server"},
                "application/json",
            )
        try:
            spec = _experiment.ExperimentSpec.from_json(
                payload.get("spec") or payload
            )
            status = self._experiment_start_fn(spec)
        except ValueError as e:
            return 400, {"message": str(e)}, "application/json"
        except Exception as e:
            logger.exception("experiment start failed")
            return 500, {"message": str(e)}, "application/json"
        return 200, status, "application/json"

    # --- debug span dump (access-key gated when a key is configured) ---

    def _debug_traces(self, query: Dict[str, str]) -> Tuple[int, Any, str]:
        if self.config.access_key and not secrets.compare_digest(
            query.get("accessKey", ""), self.config.access_key
        ):
            return (
                401, {"message": "Invalid accessKey."}, "application/json"
            )
        from predictionio_tpu.api.http import traces_payload

        status, payload = traces_payload(query)
        return status, payload, "application/json"

    def _debug_profile(
        self, method: str, query: Dict[str, str]
    ) -> Tuple[int, Any, str]:
        """On-demand profiler capture (utils/profiling.profile_route):
        ``POST ?seconds=N`` runs one bounded jax.profiler capture and
        returns the zipped trace base64-encoded; ``GET`` is status.
        Device timelines expose workload structure, so the endpoint —
        like /debug/predictions.json — REQUIRES a configured access
        key. Under the async transport this runs on the route pool, so
        a capture never blocks the event loop or the serving hot path."""
        if not self.config.access_key:
            return (
                403,
                {
                    "message": "profile capture requires a configured "
                    "access key (deploy with --accesskey)."
                },
                "application/json",
            )
        from predictionio_tpu.utils.profiling import profile_route

        status, payload = profile_route(
            method,
            query,
            secrets.compare_digest(
                query.get("accessKey", ""), self.config.access_key
            ),
        )
        return status, payload, "application/json"

    def _debug_predictions(self, query: Dict[str, str]) -> Tuple[int, Any, str]:
        """The capture-ring dump. The payload is directly persistable as
        a capture file for ``pio replay`` (workflow/quality.py documents
        the record format). Unlike the span dump (opt-in trace ids, no
        bodies), these records hold full query/result payloads — so the
        endpoint REQUIRES a configured access key; a keyless deployment
        keeps capturing (shadow scoring reads the ring in-process) but
        refuses to serve it."""
        if not self.config.access_key:
            return (
                403,
                {
                    "message": "predictions dump requires a configured "
                    "access key (deploy with --accesskey)."
                },
                "application/json",
            )
        if not secrets.compare_digest(
            query.get("accessKey", ""), self.config.access_key
        ):
            return (
                401, {"message": "Invalid accessKey."}, "application/json"
            )
        limit = None
        if query.get("limit"):
            try:
                limit = int(query["limit"])
            except ValueError:
                return 400, {"message": "invalid limit"}, "application/json"
        return (
            200,
            {
                "predictions": _quality.get_capture().dump(
                    limit=limit,
                    version=query.get("version") or None,
                    variant=query.get("variant") or None,
                )
            },
            "application/json",
        )

    # --- the hot path (reference CreateServer.scala:473-624) ---

    def _handle_query(
        self, body: Optional[bytes], headers=None
    ) -> Tuple[int, Any, str]:
        result = self._handle_query_nowait(body, headers)
        if isinstance(result, concurrent.futures.Future):
            return result.result()
        return result

    def _handle_query_nowait(
        self, body: Optional[bytes], headers=None
    ) -> Union[Tuple[int, Any, str], "concurrent.futures.Future"]:
        """Parse + enqueue; the returned future completes (via the
        serve-pool thread that resolves the prediction, so feedback,
        plugins, and bookkeeping stay off the event loop) when the
        query's micro-batch is served. Parse errors answer inline."""
        serving_start = time.perf_counter()
        deployed = self.deployed  # snapshot against concurrent reload
        algorithms = deployed.algorithms
        query_time = _dt.datetime.now(_dt.timezone.utc)
        # spans are recorded only for CLIENT-SUPPLIED trace ids
        # (X-PIO-Trace-Id): minting + ring-buffer appends for every
        # request would add a shared-lock touch to the hot path (the
        # acceptance criterion forbids exactly that) and untraced
        # traffic would evict the deliberately-traced requests from the
        # bounded span ring — the same flood guard the storage gateway
        # applies. tctx.span_id is the http span, recorded at finish.
        if headers and headers.get(_tracing.TRACE_HEADER.lower()):
            tctx, inbound_parent = _tracing.from_headers(headers)
        else:
            tctx, inbound_parent = None, None
        active = self._experiment  # snapshot: stop mid-request is safe
        experiment = None
        try:
            query_json = json.loads((body or b"").decode("utf-8"))
            if active is not None:
                # sticky allocation: a pure hash of (salt, user_key) —
                # per-request, stateless, so every SO_REUSEPORT worker
                # and every restart assigns this user the same arm. The
                # chosen arm's DeployedEngine replaces the snapshot, so
                # batching, metrics, feedback, and capture all see the
                # variant as "the" deployed engine.
                _, deployed = active.route(query_json)
                algorithms = deployed.algorithms
                experiment = active.spec.name
            query = algorithms[0].query_from_json(query_json)
        except Exception as e:
            logger.error("query %r is invalid: %s", body, e)
            return 400, {"message": str(e)}, "application/json"

        prediction_fut = self._executor.submit_nowait(
            deployed, query, trace=tctx
        )
        out: "concurrent.futures.Future" = concurrent.futures.Future()

        def _finish(f: "concurrent.futures.Future") -> None:
            try:
                result = self._finish_query(
                    deployed, query, query_json, f.result(), query_time,
                    serving_start, tctx, inbound_parent,
                    experiment=experiment,
                )
            except concurrent.futures.CancelledError:
                return  # request was cancelled before its batch formed
            except Exception as e:
                logger.exception(
                    "internal error handling POST /queries.json"
                )
                result = (500, {"message": str(e)}, "application/json")
            try:
                out.set_result(result)
            except concurrent.futures.InvalidStateError:
                pass  # the transport cancelled the request (client gone)

        prediction_fut.add_done_callback(_finish)

        def _propagate_cancel(f: "concurrent.futures.Future") -> None:
            if f.cancelled():
                # client went away: if the query has not been picked up
                # into a batch yet, drop it from the collector entirely
                prediction_fut.cancel()

        out.add_done_callback(_propagate_cancel)
        return out

    def _finish_query(
        self, deployed, query, query_json, prediction, query_time,
        serving_start, tctx=None, inbound_parent=None, experiment=None,
    ) -> Tuple[int, Any, str]:
        prediction_json = deployed.algorithms[0].result_to_json(prediction)
        # the capture baseline is the RAW model output (pre-stamp,
        # pre-plugin): `pio replay` re-runs exactly the model path, so a
        # self-replay against the same instance is byte-comparable. The
        # sampling draw is an atomic itertools counter (done callbacks
        # run on concurrent batch threads), and the snapshot is a deep
        # copy — a plugin blocker may mutate the response's nested
        # structures in place and must not corrupt the capture.
        do_capture = self.config.capture_sample > 0 and (
            next(self._capture_count) % self.config.capture_sample == 0
        )
        raw_json = copy.deepcopy(prediction_json) if do_capture else None
        version = _version_of(deployed)
        # per-version attribution: stamp the model version onto every
        # served prediction, so clients (and the feedback event) can
        # name the exact persisted round that produced it
        if isinstance(prediction_json, dict):
            prediction_json = dict(prediction_json, modelVersion=version)
            if experiment is not None:
                # stamp the arm onto the response BEFORE the feedback
                # post, so the prId attribution record carries it too
                prediction_json["experiment"] = experiment
                prediction_json["variant"] = version

        pr_id = None
        if self.config.feedback:
            prediction_json, pr_id = self._feedback(
                deployed, query, query_json, prediction, prediction_json,
                query_time, tctx,
            )

        prediction_json = self.plugin_context.run_blockers(
            deployed.engine_instance, query_json, prediction_json
        )
        self.plugin_context.notify_sniffers(
            deployed.engine_instance, query_json, prediction_json
        )

        elapsed = time.perf_counter() - serving_start
        # registry bookkeeping: per-child locks only, no shared hot-path
        # lock. The children are the SERVING deployed's version — during
        # a /reload swap, in-flight queries still record under the old
        # version while new ones record under the new.
        self._m_latency_fam.labels(version=version).observe(elapsed)
        self._m_requests_fam.labels(version=version).inc()
        self._m_last_fam.labels(version=version).set(elapsed)
        if experiment is not None:
            self._m_exp_requests.labels(
                experiment=experiment, variant=version
            ).inc()
        if do_capture:
            _quality.get_capture().record(
                version=version,
                query_json=query_json,
                result_json=raw_json,
                pr_id=pr_id,
                trace_id=tctx.trace_id if tctx is not None else None,
                latency_s=elapsed,
                experiment=experiment,
                variant=version if experiment is not None else None,
            )
        if tctx is not None:
            _tracing.record_span(
                "http:/queries.json", tctx.trace_id, span_id=tctx.span_id,
                parent_id=inbound_parent, duration_s=elapsed,
            )
        return 200, prediction_json, "application/json"

    # --- feedback loop (reference CreateServer.scala:509-579) ---

    def _feedback(
        self, deployed, query, query_json, prediction, prediction_json,
        query_time, tctx=None,
    ):
        org = getattr(prediction, "pr_id", None)
        new_pr_id = org if org else _gen_pr_id()
        data = {
            "event": "predict",
            "eventTime": query_time.isoformat().replace("+00:00", "Z"),
            "entityType": "pio_pr",
            "entityId": new_pr_id,
            "properties": {
                "engineInstanceId": deployed.engine_instance.id,
                "query": query_json,
                "prediction": prediction_json,
            },
        }
        query_pr_id = getattr(query, "pr_id", None)
        if query_pr_id is not None:
            data["prId"] = query_pr_id

        url = (
            f"http://{self.config.event_server_ip}:"
            f"{self.config.event_server_port}/events.json?"
            + urllib.parse.urlencode({"accessKey": self.config.access_key})
        )
        # traced requests carry (trace id, http span id) onto the queue
        # so the drainer's POST propagates X-PIO-Trace-Id — the ingest
        # span chain joins the serving trace instead of dead-ending here
        tinfo = (tctx.trace_id, tctx.span_id) if tctx is not None else None
        self._enqueue_feedback((url, data, tinfo))
        self._ensure_feedback_worker()

        # inject the fresh prId into the response: it is the attribution
        # join key the client must echo on subsequent events (reference
        # CreateServer.scala:525 returns it the same way)
        if isinstance(prediction_json, dict):
            prediction_json = dict(prediction_json, prId=new_pr_id)
        return prediction_json, new_pr_id

    # --- status page (reference CreateServer.scala:444-471 html.index) ---

    def _status_json(self) -> dict:
        """status.json is now a READ of the metrics registry (deltas
        against construction-time snapshots — 'since this server
        deployed'), not a walk of N private lock-guarded tallies. The
        p50/p99 keys survive, estimated by bucket interpolation from the
        mergeable log-bucket histogram that replaced the reservoir."""
        from predictionio_tpu.ops.streaming import pack_cache_stats
        from predictionio_tpu.workflow.continuous import (
            continuous_round_stats,
        )
        from predictionio_tpu.workflow.promotion import promotion_stats

        inst = self.deployed.engine_instance
        batch_stats = self._executor.stats()
        lat, requests = self._serving_totals()
        with self._stats_lock:
            upgrade_status = self._upgrade_status
            upgrade_checked = self._upgrade_checked_at
        return {
            "status": "alive",
            "engineInstanceId": inst.id,
            # the model-version label every serving metric carries
            # (pio_model_info flips on /reload)
            "modelVersion": _version_of(self.deployed),
            "predictionCapture": _quality.get_capture().stats(),
            "engineFactory": inst.engine_factory,
            "startTime": self.server_start_time.isoformat(),
            "algorithms": [type(a).__name__ for a in self.deployed.algorithms],
            "algorithmsParams": [
                repr(a.params) for a in self.deployed.algorithms
            ],
            # active residency precision per algorithm for THIS deployed
            # version (quantized retrieval tier, ops/retrieval.py);
            # None = no quantization-aware serving state
            "servingPrecision": [
                a.serving_precision(m)
                for a, m in zip(
                    self.deployed.algorithms, self.deployed.models
                )
            ],
            "serving": type(self.deployed.serving).__name__,
            "feedback": self.config.feedback,
            "eventServerIp": self.config.event_server_ip,
            "eventServerPort": self.config.event_server_port,
            "requestCount": requests,
            "avgServingSec": (lat.sum / lat.count) if lat.count else 0.0,
            "lastServingSec": self._m_last.value,
            # bucket-interpolated latency percentiles from the mergeable
            # log-bucket histogram (quantile_from_buckets)
            "p50ServingSec": lat.quantile(0.50),
            "p99ServingSec": lat.quantile(0.99),
            # collector batch accounting: does micro-batching engage?
            "batchFillMean": round(batch_stats["batch_fill_mean"], 3),
            "batchSizeHistogram": batch_stats["batch_size_histogram"],
            # bounded feedback queue (drop-oldest when the event
            # server lags; see ServerConfig.feedback_queue_max)
            "feedbackQueueDropped": int(
                self._m_feedback_dropped.value
                - self._feedback_dropped_base
            ),
            # training-side registry families surfaced for the serving
            # process (continuous retrain + hot-swap runs in-process)
            "packCache": pack_cache_stats(),
            "continuousRounds": continuous_round_stats(),
            # promotion-pipeline outcomes (workflow/promotion.py): the
            # in-process view of pio_promotion_total
            "promotion": promotion_stats(),
            # HBM residency ledger detail: per-device, per-component
            # registered bytes (the `pio top` detail view's source)
            "deviceLedger": {
                "totalBytes": _ledger.get_ledger().total_bytes(),
                "breakdown": _ledger.get_ledger().breakdown(),
            },
            # daily self-check (reference CreateServer.scala:253-260)
            "upgradeStatus": upgrade_status,
            "upgradeLastChecked": upgrade_checked,
        }

    def _status_html(self) -> str:
        s = self._status_json()
        rows = "".join(
            f"<tr><th>{html.escape(str(k))}</th>"
            f"<td>{html.escape(json.dumps(v))}</td></tr>"
            for k, v in s.items()
        )
        return (
            "<!DOCTYPE html><html><head><title>"
            f"Engine Server at {self.config.ip}:{self.config.port}"
            "</title></head><body><h1>PredictionIO-TPU Engine Server</h1>"
            f"<table>{rows}</table></body></html>"
        )


class EngineServer:
    """The MasterActor equivalent (reference CreateServer.scala:262-384):
    binds the HTTP frontend (event-loop by default, thread-per-connection
    via ``ServerConfig.transport='threaded'``), hot-swaps serving state
    on /reload, undeploys on /stop.

    A swap retires the displaced DeployedEngine into a small LRU of
    prepared serving states (``ServerConfig.retained_states`` — the
    reference's multi-variant admin tier): a rollback ``/reload`` back
    to a retained instance is one reference flip, no store read, no
    recompile. Evicted entries drain behind the in-flight batch
    boundary and then free their device-resident factors, on a
    background thread watched by the ``serving-drain`` heartbeat."""

    # bounded drain of evicted serving states; a drain wedged past the
    # heartbeat deadline degrades /readyz (utils/health.py semantics)
    DRAIN_TIMEOUT_S = 60.0
    DRAIN_DEADLINE_S = 120.0

    def __init__(
        self,
        engine: Engine,
        config: Optional[ServerConfig] = None,
        storage: Optional[Storage] = None,
        plugin_context: Optional[EngineServerPluginContext] = None,
        deployed: Optional[DeployedEngine] = None,
    ):
        self.engine = engine
        self.config = config or ServerConfig()
        self.storage = storage or get_storage()
        # deploy-time serving context: pins the prepared serving state
        # (resident sharded factors) to this worker's device slice, and
        # is REUSED by /reload so a hot model swap re-uploads onto the
        # same devices
        self._serving_ctx: Optional[WorkflowContext] = None
        if self.config.serving_devices:
            self._serving_ctx = WorkflowContext(
                mode="Serving",
                storage=self.storage,
                mesh=_mesh_from_device_spec(self.config.serving_devices),
            )
        if deployed is None:
            deployed = DeployedEngine.from_storage(
                engine,
                self.storage,
                self.config.engine_instance_id,
                ctx=self._serving_ctx,
            )
        # displaced-but-retained serving states, newest last (the
        # rollback store); guarded by its own lock — reload may be
        # driven concurrently from the route pool and a promotion loop
        self._retained: (
            "collections.OrderedDict[str, DeployedEngine]"
        ) = collections.OrderedDict()
        self._retained_lock = threading.Lock()
        # serializes the read-bind-retire sequence: reload may be driven
        # concurrently from the route pool and a promotion loop, and two
        # racing swaps reading the same api.deployed would displace one
        # fresh snapshot without ever retiring (draining/releasing) it
        self._swap_lock = threading.Lock()
        self.api = QueryAPI(
            deployed,
            self.config,
            plugin_context,
            reload_fn=self.reload,
            stop_fn=self.shutdown,
            experiment_start_fn=self.start_experiment,
            experiment_stop_fn=self.stop_experiment,
        )

        def handle(method, path, query, body, form=None, headers=None):
            return self.api.handle(method, path, query, body, headers)

        def handle_nowait(method, path, query, body, form=None, headers=None):
            return self.api.handle_nowait(
                method, path, query, body, form, headers
            )

        # the event loop awaits the query route's future; the threaded
        # frontend cannot await, so it gets the blocking dispatch
        fn = (
            handle_nowait if self.config.transport == "async" else handle
        )
        self._http = make_http_server(
            fn, self.config.ip, self.config.port, "Engine Server",
            reuse_port=self.config.reuse_port,
            transport=self.config.transport,
        )

    @property
    def port(self) -> int:
        return self._http.port

    def start(self) -> "EngineServer":
        self._http.start()
        return self

    def serve_forever(self) -> None:
        self._http.serve_forever()

    def shutdown(self) -> None:
        self._http.shutdown()
        # a still-running experiment's non-live arms are owned by the
        # ActiveExperiment, not the retained LRU — retire them first so
        # their device buffers are released below, not leaked
        active = self.api.clear_experiment()
        if active is not None:
            with self._retained_lock:
                for vid, dep in active.engines.items():
                    if dep is not self.api.deployed:
                        self._retained.setdefault(vid, dep)
        self.api.close()
        # free the retained rollback states' device buffers AND the
        # actively deployed instance's — tests and operators cycle many
        # servers per process, and a down server keeping factors
        # resident is exactly the residency the device ledger flags.
        # The active release waits out in-flight batches (bounded);
        # release() itself asserts the ledger invariant.
        with self._retained_lock:
            retained = list(self._retained.values())
            self._retained.clear()
        for dep in retained:
            dep.release(timeout_s=1.0)
        self.api.deployed.release(timeout_s=1.0)

    def retained_versions(self) -> List[str]:
        """The engine-instance ids of the retained (instant-rollback)
        serving states, oldest first."""
        with self._retained_lock:
            return list(self._retained)

    def swap_deployed(self, fresh: DeployedEngine) -> DeployedEngine:
        """Atomically swap ``fresh`` in behind the in-flight batch
        boundary (bind_deployed re-points the per-version metrics +
        pio_model_info; queries in flight keep the old snapshot) and
        retire the displaced DeployedEngine into the retained LRU.
        Returns the displaced engine — the promotion pipeline drains it
        explicitly; LRU evictees drain + release in the background."""
        with self._swap_lock:
            old = self.api.deployed
            self.api.bind_deployed(fresh)
            self._retire(old)
        return old

    def _retire(self, old: DeployedEngine) -> None:
        evicted: List[DeployedEngine] = []
        with self._retained_lock:
            # a bare /reload re-deploys a fresh copy of the same instance
            # id: the previously retained copy it displaces must still
            # drain+release, not silently drop to GC with its resident
            # buffers unaccounted
            displaced_twin = self._retained.pop(old.engine_instance.id, None)
            if displaced_twin is not None and displaced_twin is not old:
                evicted.append(displaced_twin)
            self._retained[old.engine_instance.id] = old
            while len(self._retained) > max(0, self.config.retained_states):
                evicted.append(self._retained.popitem(last=False)[1])
        for dep in evicted:
            threading.Thread(
                target=self._drain_and_release, args=(dep,), daemon=True,
                name="serving-drain",
            ).start()

    def _drain_and_release(self, dep: DeployedEngine) -> None:
        """Background eviction: wait for the last in-flight batch, then
        free the device-resident serving state. Watched by the
        ``serving-drain`` heartbeat — a wedged drain degrades /readyz
        instead of silently leaking HBM."""
        hb = _health.heartbeat(
            "serving-drain", deadline_s=self.DRAIN_DEADLINE_S
        )
        with hb.busy():
            drained = dep.drain(self.DRAIN_TIMEOUT_S, on_progress=hb.beat)
            released = dep.release(timeout_s=1.0)
        if not (drained and released):
            logger.warning(
                "evicted serving state %s did not drain cleanly "
                "(drained=%s released=%s); buffers free by refcount when "
                "the straggler batch resolves",
                dep.engine_instance.id, drained, released,
            )

    def reload(self, engine_instance_id: Optional[str] = None) -> str:
        """Swap serving state (reference MasterActor ReloadServer,
        CreateServer.scala:322-343). With ``engine_instance_id`` the
        swap is pinned to that exact instance (the promotion / fleet-
        convergence contract; a retained LRU hit swaps without touching
        storage); without it, the latest COMPLETED instance of the same
        engine is resolved — the reference's semantics. Returns the now-
        serving instance id; raises on failure with the old snapshot
        still serving (the /reload route turns that into a 500)."""
        current = self.api.deployed
        current_id = current.engine_instance.id
        if engine_instance_id is not None and engine_instance_id == current_id:
            return current_id  # idempotent: fleet-converge nudges are free
        fresh: Optional[DeployedEngine] = None
        if engine_instance_id is not None:
            with self._retained_lock:
                fresh = self._retained.pop(engine_instance_id, None)
        if fresh is None:
            inst = current.engine_instance
            fresh = DeployedEngine.from_storage(
                self.engine,
                self.storage,
                engine_instance_id=engine_instance_id,
                engine_id=(
                    inst.engine_id if engine_instance_id is None else None
                ),
                engine_version=(
                    inst.engine_version
                    if engine_instance_id is None
                    else None
                ),
                engine_variant=(
                    inst.engine_variant
                    if engine_instance_id is None
                    else None
                ),
                ctx=self._serving_ctx,
            )
        # NOTE: a bare /reload (no pinned id) that resolves "latest" to
        # the instance already serving still swaps in the fresh copy —
        # the reference ReloadServer's unconditional re-deploy, and the
        # residency regression gate in tests/test_retrieval.py. Only
        # PINNED reloads short-circuit (above): that is what makes the
        # fleet-convergence nudges free.
        new_id = fresh.engine_instance.id
        self.swap_deployed(fresh)
        logger.info("reloaded engine instance %s", new_id)
        return new_id

    # --- experimentation plane ---

    def start_experiment(self, spec) -> Dict[str, Any]:
        """Deploy every arm of ``spec`` warm and bind the experiment
        into the QueryAPI. Arms resolve in order: the live instance is
        reused as-is; a retained-LRU hit is popped out warm (the PR 13
        machinery — no store read, no recompile); anything else builds
        from storage onto the serving device slice. Idempotent per spec:
        re-posting the same experiment (a fleet-converge nudge or a
        restart) is a no-op."""
        with self._swap_lock:
            current = self.api._experiment
            if current is not None:
                if current.spec == spec:
                    return self.api.experiment_status()
                raise ValueError(
                    f"experiment {current.spec.name!r} is already running"
                )
            live = self.api.deployed
            live_id = live.engine_instance.id
            engines: Dict[str, DeployedEngine] = {}
            created: List[DeployedEngine] = []
            try:
                for vid in spec.variants:
                    if vid == live_id:
                        engines[vid] = live
                        continue
                    with self._retained_lock:
                        dep = self._retained.pop(vid, None)
                    if dep is None:
                        dep = DeployedEngine.from_storage(
                            self.engine,
                            self.storage,
                            engine_instance_id=vid,
                            ctx=self._serving_ctx,
                        )
                    engines[vid] = dep
                    created.append(dep)
            except Exception:
                # partial deploy must not leak device state
                for dep in created:
                    dep.release(timeout_s=1.0)
                raise
            self.api.set_experiment(
                _experiment.ActiveExperiment(spec, engines)
            )
            logger.info(
                "experiment %s started: variants=%s split=%s",
                spec.name, spec.variants, spec.split,
            )
            return self.api.experiment_status()

    def stop_experiment(
        self, winner: Optional[str] = None
    ) -> Dict[str, Any]:
        """Unbind the experiment. The winner (and, on a plain stop, every
        non-live arm) retires into the retained LRU — warm for the
        promotion pipeline's pinned ``/reload``; losing arms skip the
        LRU and go straight onto the background drain+release path, so
        their device state lands at a ledger-zero release."""
        with self._swap_lock:
            active = self.api.clear_experiment()
            if active is None:
                return {"stopped": False, "experiment": None}
            live_id = self.api.deployed.engine_instance.id
            drained: List[str] = []
            retained: List[str] = []
            for vid, dep in active.engines.items():
                if dep is self.api.deployed:
                    continue
                if winner is not None and vid != winner:
                    drained.append(vid)
                    threading.Thread(
                        target=self._drain_and_release, args=(dep,),
                        daemon=True, name="serving-drain",
                    ).start()
                else:
                    retained.append(vid)
                    self._retire(dep)
            logger.info(
                "experiment %s stopped: winner=%s drained=%s retained=%s",
                active.spec.name, winner, drained, retained,
            )
            return {
                "stopped": True,
                "experiment": active.spec.name,
                "winner": winner,
                "live": live_id,
                "drained": drained,
                "retained": retained,
            }


def create_server(
    engine: Engine,
    config: Optional[ServerConfig] = None,
    storage: Optional[Storage] = None,
) -> EngineServer:
    """Reference CreateServer.main (CreateServer.scala:110-195). Plugins
    are auto-discovered at launch (the reference's ServiceLoader pass,
    EngineServerPluginContext.scala:42-74)."""
    return EngineServer(
        engine,
        config,
        storage,
        plugin_context=EngineServerPluginContext.discover(),
    )
