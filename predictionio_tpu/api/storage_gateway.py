"""Storage gateway — the server half of the client-server storage backend.

The reference's production storage is client-server: HBase regionservers
for events (hbase/StorageClient.scala:40), PostgreSQL/MySQL over JDBC
(jdbc/StorageClient.scala), Elasticsearch over its transport protocol
(elasticsearch/StorageClient.scala:31-45). This gateway plays that role
for the TPU framework: one process owns the physical store (any embedded
backend — sqlite for durability, memory for tests) and exposes every DAO
trait over HTTP, so event servers, trainers, engine servers, and CLIs on
other hosts share a single storage service through the ``http`` client
backend (data/storage/http.py).

Protocol: POST /rpc with ``{"dao": <repo>, "method": <name>,
"args": {...}}`` -> ``{"result": ...}`` or ``{"error", "type"}``.
DAO methods, argument names, and record layouts mirror
data/storage/base.py one-to-one (the wire format lives in
data/storage/wire.py). An optional shared secret
(``--secret`` / PIO_STORAGE_SOURCES_<NAME>_SECRET on clients) gates every
request, playing the access-key role the event server has
(EventServer.scala:81-107).

Run via ``pio storagegateway [--port 7077]`` or programmatically with
``StorageGatewayServer(storage).start()``.
"""

from __future__ import annotations

import concurrent.futures
import hmac
import logging
import time
from typing import Any, Dict, Optional

from predictionio_tpu.api.aio_http import TRANSPORTS, make_http_server
from predictionio_tpu.data.storage import Storage, get_storage
from predictionio_tpu.data.storage.base import (
    PartialBatchError,
    StorageError,
    StorageSaturatedError,
)
from predictionio_tpu.data.storage import wire
from predictionio_tpu.utils import health as _health
from predictionio_tpu.utils import metrics as _metrics
from predictionio_tpu.utils import tracing as _tracing

logger = logging.getLogger(__name__)

DEFAULT_PORT = 7077  # beside the reference's 7070/7071 tools ports

# dao name on the wire -> (Storage accessor, record kind for rows,
# base.py trait whose public methods define the RPC surface)
_DAOS = {
    "levents": ("get_l_events", None, None),
    "apps": ("get_meta_data_apps", "app", "Apps"),
    "access_keys": ("get_meta_data_access_keys", "access_key", "AccessKeys"),
    "channels": ("get_meta_data_channels", "channel", "Channels"),
    "engine_manifests": (
        "get_meta_data_engine_manifests", "engine_manifest", "EngineManifests",
    ),
    "engine_instances": (
        "get_meta_data_engine_instances", "engine_instance", "EngineInstances",
    ),
    "evaluation_instances": (
        "get_meta_data_evaluation_instances",
        "evaluation_instance",
        "EvaluationInstances",
    ),
    "models": ("get_model_data_models", "model", "Models"),
}


def _trait_methods(trait_name: str) -> frozenset:
    """Public methods declared on the base.py trait — the RPC surface.

    Dispatching against the trait (not the backend instance) keeps the
    wire protocol pinned to data/storage/base.py: extra public helpers a
    concrete DAO happens to grow are NOT remotely callable.
    """
    from predictionio_tpu.data.storage import base as _base

    trait = getattr(_base, trait_name)
    return frozenset(
        m
        for m in vars(trait)
        if not m.startswith("_") and callable(getattr(trait, m, None))
    )


_TRAIT_ALLOWLIST: Dict[str, frozenset] = {}

# the levents RPC surface (_call_levents dispatch) — metric labels are
# validated against this so client-supplied strings can't mint
# unbounded label sets in the process-global registry
_LEVENTS_METHODS = frozenset(
    {
        "init", "remove", "insert", "write", "insert_batch", "get",
        "delete", "find", "aggregate_properties", "insert_columns",
        "insert_columns_v2", "find_columns_native",
        "aggregate_properties_of_entity",
        # chunked/delta scan surface (cluster tier + remote delta
        # training): materialized batches + opaque cursor/fingerprint
        "scan_columns", "scan_columns_delta", "store_fingerprint",
    }
)


def _rpc_metric_labels(dao: str, method: str) -> "tuple[str, str]":
    """Label values for one RPC, collapsed to ``invalid`` unless they
    name a real dao/method: labels come from the CLIENT, and a fuzzer
    minting a fresh (dao, method) pair per request would otherwise grow
    a new counter + histogram child in the registry forever."""
    if dao not in _DAOS:
        return "invalid", "invalid"
    if dao == "levents":
        return dao, (method if method in _LEVENTS_METHODS else "invalid")
    trait = _DAOS[dao][2]
    if trait not in _TRAIT_ALLOWLIST:
        _TRAIT_ALLOWLIST[trait] = _trait_methods(trait)
    return dao, (method if method in _TRAIT_ALLOWLIST[trait] else "invalid")

class StorageGatewayCore:
    """Transport-independent RPC core (same pattern as QueryAPI)."""

    def __init__(self, storage: Optional[Storage] = None, secret: str = ""):
        self.storage = storage or get_storage()
        self.secret = secret
        # per-method RPC observability (the gateway exposed NO stats
        # before this): request counter by outcome + latency histogram,
        # labeled (dao, method) — the RPC surface is a fixed allowlist,
        # so cardinality is bounded by the base.py traits
        reg = _metrics.get_registry()
        self._m_rpcs = reg.counter(
            "pio_gateway_rpc_total",
            "Storage-gateway RPCs by dao, method, and outcome",
            labels=("dao", "method", "outcome"),
        )
        self._m_rpc_seconds = reg.histogram(
            "pio_gateway_rpc_seconds",
            "Storage-gateway RPC handling latency",
            labels=("dao", "method"),
            buckets=_metrics.LATENCY_BUCKETS_S,
        )
        # /readyz: the owned store must answer a cheap metadata read
        # (TTL-cached against readiness-poller load); the gateway also
        # inherits the process's daemon-stall checks — its sqlite
        # committers register their own heartbeats
        self._ready_probes = (
            _health.TTLProbe("store", self._probe_store),
        )

    def _probe_store(self) -> None:
        self.storage.get_meta_data_apps().get_all()

    # --- request entry ---

    def handle(self, method, path, query, body, form, headers=None):
        import json

        if path == "/status" and method == "GET":
            return 200, {"status": "alive", "daos": sorted(_DAOS)}
        if path == "/healthz" and method == "GET":
            return 200, _health.liveness()
        if path == "/readyz" and method == "GET":
            ok, payload = _health.readiness(self._ready_probes)
            return (200 if ok else 503), payload
        if path == "/metrics" and method == "GET":
            return (
                200,
                _metrics.get_registry().render(),
                _metrics.render_content_type(),
            )
        if path == "/debug/traces.json" and method == "GET":
            # gated exactly like /rpc: whoever holds the shared secret
            # may read spans (which carry dao/method names and timings)
            if self.secret and not hmac.compare_digest(
                (query or {}).get("secret", ""), self.secret
            ):
                return 401, {"error": "invalid or missing secret"}
            from predictionio_tpu.api.http import traces_payload

            return traces_payload(query)
        if path == "/debug/profile":
            # on-demand profiler capture, gated by the same shared
            # secret as the span dump (utils/profiling.profile_route)
            from predictionio_tpu.utils.profiling import profile_route

            return profile_route(
                method,
                query,
                not self.secret
                or hmac.compare_digest(
                    (query or {}).get("secret", ""), self.secret
                ),
            )
        if path != "/rpc" or method != "POST":
            return 404, {"error": f"unknown route {method} {path}"}
        try:
            payload = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            return 400, {"error": f"invalid JSON body: {e}"}
        if not isinstance(payload, dict):
            return 400, {"error": "body must be a JSON object"}
        if self.secret:
            # in-body secret (request lines get logged; bodies don't),
            # constant-time comparison
            given = payload.get("secret") or ""
            if not hmac.compare_digest(str(given), self.secret):
                return 401, {"error": "invalid or missing secret"}
        dao = str(payload.get("dao", ""))
        rpc_method = str(payload.get("method", ""))
        # RPC trace hop: the client's X-PIO-Trace-Id/-Parent-Span
        # headers chain this process's span (and, through the ambient
        # context, any group-commit flush it causes) under the caller's
        t0 = time.perf_counter()
        # only traced CALLERS get spans here: minting a fresh trace per
        # RPC would flood the bounded ring during training scans
        # (thousands of untraced RPCs) and evict the interesting chains
        traced = bool(
            headers and headers.get(_tracing.TRACE_HEADER.lower())
        )
        tctx, inbound = _tracing.from_headers(headers)
        outcome = "error"
        try:
            # ambient context = this RPC's entry span, so a group-commit
            # flush the call triggers chains under it
            with _tracing.use(tctx if traced else None):
                result = self.call(dao, rpc_method, payload.get("args") or {})
            outcome = "ok"
            return 200, {"result": result}
        except PartialBatchError as e:
            # carry the per-event outcome across the wire — the client
            # re-raises a PartialBatchError so the event server's
            # per-slot retry contract holds through the gateway too
            return 400, {
                "error": str(e),
                "type": "PartialBatchError",
                "event_ids": list(e.event_ids),
                "failed_ids": sorted(e.failed_ids),
                "retry_after_s": e.retry_after_s,
            }
        except StorageSaturatedError as e:
            # deliberate backpressure, not a backend fault: the typed
            # refusal crosses the wire so an event server fronted by
            # this gateway still answers 503 + Retry-After end to end
            return 503, {
                "error": str(e),
                "type": "StorageSaturatedError",
                "retry_after_s": e.retry_after_s,
            }
        except StorageError as e:
            return 400, {"error": str(e), "type": "StorageError"}
        except (KeyError, TypeError, ValueError) as e:
            return 400, {"error": str(e), "type": type(e).__name__}
        except Exception as e:  # backend bug — surface, don't hide
            logger.exception("gateway RPC failed")
            return 500, {"error": str(e), "type": type(e).__name__}
        finally:
            elapsed = time.perf_counter() - t0
            ldao, lmethod = _rpc_metric_labels(dao, rpc_method)
            self._m_rpcs.labels(
                dao=ldao, method=lmethod, outcome=outcome
            ).inc()
            self._m_rpc_seconds.labels(dao=ldao, method=lmethod).observe(
                elapsed
            )
            if traced:
                _tracing.record_span(
                    f"rpc:{dao}.{rpc_method}", tctx.trace_id,
                    span_id=tctx.span_id, parent_id=inbound,
                    duration_s=elapsed, attrs={"outcome": outcome},
                )

    # --- dispatch ---

    def call(self, dao: str, method: str, args: Dict[str, Any]) -> Any:
        if dao not in _DAOS:
            raise KeyError(f"unknown dao {dao!r}")
        accessor, kind, trait = _DAOS[dao]
        target = getattr(self.storage, accessor)()
        if dao == "levents":
            return self._call_levents(target, method, args)
        if trait not in _TRAIT_ALLOWLIST:
            _TRAIT_ALLOWLIST[trait] = _trait_methods(trait)
        if method not in _TRAIT_ALLOWLIST[trait]:
            raise KeyError(f"unknown {kind} method {method!r}")
        return self._call_metadata(target, kind, method, args)

    def _call_levents(self, le, method: str, args: Dict[str, Any]) -> Any:
        a = dict(args)
        if method in ("init", "remove"):
            return getattr(le, method)(a["app_id"], a.get("channel_id"))
        if method == "insert":
            ev = wire.event_from_wire(a["event"])
            return le.insert(ev, a["app_id"], a.get("channel_id"))
        if method == "write":
            evs = [wire.event_from_wire(e) for e in a["events"]]
            return le.write(evs, a["app_id"], a.get("channel_id"))
        if method == "insert_batch":
            evs = [wire.event_from_wire(e) for e in a["events"]]
            return le.insert_batch(evs, a["app_id"], a.get("channel_id"))
        if method == "get":
            ev = le.get(a["event_id"], a["app_id"], a.get("channel_id"))
            return None if ev is None else wire.event_to_wire(ev)
        if method == "delete":
            return le.delete(a["event_id"], a["app_id"], a.get("channel_id"))
        if method == "find":
            from predictionio_tpu.data.storage.base import UNSET

            kwargs: Dict[str, Any] = {
                "app_id": a["app_id"],
                "channel_id": a.get("channel_id"),
                "start_time": wire.opt_dt_from_wire(a.get("start_time")),
                "until_time": wire.opt_dt_from_wire(a.get("until_time")),
                "entity_type": a.get("entity_type"),
                "entity_id": a.get("entity_id"),
                "event_names": a.get("event_names"),
                "limit": a.get("limit"),
                "reversed": a.get("reversed", False),
            }
            for f in ("target_entity_type", "target_entity_id"):
                v = a.get(f, wire.UNSET_WIRE)
                kwargs[f] = UNSET if v == wire.UNSET_WIRE else v
            return [wire.event_to_wire(e) for e in le.find(**kwargs)]
        if method == "aggregate_properties":
            out = le.aggregate_properties(
                app_id=a["app_id"],
                entity_type=a["entity_type"],
                channel_id=a.get("channel_id"),
                start_time=wire.opt_dt_from_wire(a.get("start_time")),
                until_time=wire.opt_dt_from_wire(a.get("until_time")),
                required=a.get("required"),
            )
            # the fold happens HERE, next to the store: the wire carries
            # one PropertyMap per entity, not the full event history
            # (reference LEventAggregator.scala:39-136 semantics)
            return {
                k: wire.property_map_to_wire(v) for k, v in out.items()
            }
        if method in ("insert_columns", "insert_columns_v2"):
            # bulk columnar import: dictionaries as JSON strings, codes
            # and values as packed base64 (data/storage/columnar.py)
            from predictionio_tpu.data.storage import columnar as col

            import numpy as np

            return le.insert_columns_encoded(
                a["app_id"],
                a.get("channel_id"),
                event=a["event"],
                entity_type=a["entity_type"],
                target_entity_type=a["target_entity_type"],
                entity_names=a["entity_names"],
                entity_codes=col.array_from_b64(a["entity_codes"], np.int32),
                target_names=a["target_names"],
                target_codes=col.array_from_b64(a["target_codes"], np.int32),
                values=col.array_from_b64(a["values"], np.float32),
                value_property=a.get("value_property", "rating"),
                event_time=wire.opt_dt_from_wire(a.get("event_time")),
                event_times_ms=(
                    None
                    if a.get("event_times_ms") is None
                    else col.array_from_b64(a["event_times_ms"], np.int64)
                ),
            )
        if method == "find_columns_native":
            from predictionio_tpu.data.storage import columnar as col
            from predictionio_tpu.data.storage.base import UNSET

            tet = a.get("target_entity_type", wire.UNSET_WIRE)
            cols = le.find_columns_native(
                a["app_id"],
                a.get("channel_id"),
                value_spec=col.spec_from_wire(a.get("value_spec")),
                start_time=wire.opt_dt_from_wire(a.get("start_time")),
                until_time=wire.opt_dt_from_wire(a.get("until_time")),
                entity_type=a.get("entity_type"),
                target_entity_type=UNSET if tet == wire.UNSET_WIRE else tet,
                event_names=a.get("event_names"),
            )
            return None if cols is None else col.columnar_to_wire(cols)
        if method == "store_fingerprint":
            return wire.opaque_to_wire(
                le.store_fingerprint(a["app_id"], a.get("channel_id"))
            )
        if method in ("scan_columns", "scan_columns_delta"):
            return self._scan_columns(le, method, a)
        if method == "aggregate_properties_of_entity":
            pm = le.aggregate_properties_of_entity(
                app_id=a["app_id"],
                entity_type=a["entity_type"],
                entity_id=a["entity_id"],
                channel_id=a.get("channel_id"),
                start_time=wire.opt_dt_from_wire(a.get("start_time")),
                until_time=wire.opt_dt_from_wire(a.get("until_time")),
            )
            return None if pm is None else wire.property_map_to_wire(pm)
        raise KeyError(f"unknown levents method {method!r}")

    @staticmethod
    def _scan_columns(le, method: str, a: Dict[str, Any]) -> Any:
        """Materialized chunked/delta scan for remote consumers: the
        backend's ``stream_columns_native``/``stream_columns_delta``
        exhausted into ONE wire payload — packed code/value columns in
        the stream's shared code space, the post-scan ``names`` array,
        and the opaque delta cursor + pre-scan fingerprint (tagged
        codec, wire.opaque_to_wire) that make remote delta training and
        the cluster tier's per-node cursors possible. ``{"invalid":
        true}`` = the backend declined the delta (full-repack fallback);
        a backend with no chunked path at all raises KeyError so old
        clients keep their find_columns_native fallback."""
        import numpy as np

        from predictionio_tpu.data.storage import columnar as col
        from predictionio_tpu.data.storage.base import UNSET

        tet = a.get("target_entity_type", wire.UNSET_WIRE)
        kwargs = dict(
            value_spec=col.spec_from_wire(a.get("value_spec")),
            start_time=wire.opt_dt_from_wire(a.get("start_time")),
            until_time=wire.opt_dt_from_wire(a.get("until_time")),
            entity_type=a.get("entity_type"),
            target_entity_type=UNSET if tet == wire.UNSET_WIRE else tet,
            event_names=a.get("event_names"),
        )
        if a.get("batch_rows"):
            kwargs["batch_rows"] = int(a["batch_rows"])
        if method == "scan_columns_delta":
            stream = le.stream_columns_delta(
                a["app_id"], a.get("channel_id"),
                cursor=wire.opaque_from_wire(a["cursor"]), **kwargs,
            )
            if stream is None:
                return {"invalid": True}
        else:
            stream = le.stream_columns_native(
                a["app_id"], a.get("channel_id"), **kwargs
            )
            if stream is None:
                # no chunked path on this backend: the one-batch wrap
                # (pre-scan fingerprint, no cursor) keeps the RPC total
                fp = le.store_fingerprint(a["app_id"], a.get("channel_id"))
                cols = le.find_columns_native(
                    a["app_id"], a.get("channel_id"), **kwargs
                )
                if cols is None:
                    return {"invalid": True}
                from predictionio_tpu.data.storage.columnar import (
                    ColumnarStream,
                )

                stream = ColumnarStream.from_columnar(cols, fingerprint=fp)
        e_parts, t_parts, v_parts = [], [], []
        for e_codes, t_codes, values in stream:
            e_parts.append(np.asarray(e_codes, np.int64))
            t_parts.append(np.asarray(t_codes, np.int64))
            v_parts.append(np.asarray(values, np.float32))
        names = stream.names  # valid only after exhaustion
        cat = np.concatenate
        empty_i = np.empty(0, np.int64)
        return {
            "names": [str(n) for n in np.asarray(names)],
            "e_codes": col.array_to_b64(
                cat(e_parts) if e_parts else empty_i
            ),
            "t_codes": col.array_to_b64(
                cat(t_parts) if t_parts else empty_i
            ),
            "values": col.array_to_b64(
                cat(v_parts) if v_parts else np.empty(0, np.float32)
            ),
            "cursor": wire.opaque_to_wire(stream.cursor),
            "fingerprint": wire.opaque_to_wire(stream.fingerprint),
        }

    def _call_metadata(self, dao, kind: str, method: str, args: Dict[str, Any]) -> Any:
        a = dict(args)
        if "record" in a:
            a["record"] = wire.record_from_wire(kind, a["record"])
        record = a.pop("record", None)
        fn = getattr(dao, method, None)  # allowlisted against the trait in call()
        if fn is None:
            raise KeyError(f"unknown {kind} method {method!r}")
        out = fn(record, **a) if record is not None else fn(**a)
        # serialize records/record lists; scalars pass through
        if isinstance(out, list):
            return [
                wire.record_to_wire(x) if _is_record(x) else x for x in out
            ]
        return wire.record_to_wire(out) if _is_record(out) else out


def _is_record(x: Any) -> bool:
    import dataclasses

    return dataclasses.is_dataclass(x) and not isinstance(x, type)


_LOOPBACK_IPS = ("localhost", "127.0.0.1", "::1")


class StorageGatewayServer:
    """Defaults to loopback: the gateway exposes read/write access to ALL
    storage, so a non-loopback bind without a shared secret must be an
    explicit opt-in (``allow_insecure=True``), not a constructor default.
    The CLI path (`pio storagegateway`) opts in after printing a warning.

    Rides the shared transport selector (api/aio_http.py): ``async``
    (default) is the event-loop frontend — RPC handlers block on the
    store (group-commit COMMIT waits, scans), so they run on a bounded
    pool whose future the loop awaits, exactly the event server's
    shape; ``threaded`` is the stdlib thread-per-connection fallback.
    Both serve ``GET /metrics``.
    """

    HANDLER_THREADS = 16

    def __init__(
        self,
        storage: Optional[Storage] = None,
        ip: str = "localhost",
        port: int = DEFAULT_PORT,
        secret: str = "",
        allow_insecure: bool = False,
        transport: str = "async",
    ):
        if not secret and not allow_insecure and ip not in _LOOPBACK_IPS:
            raise ValueError(
                f"refusing to bind {ip!r} without a secret: pass secret=... "
                "or allow_insecure=True to expose unauthenticated storage "
                "on a non-loopback interface"
            )
        if transport not in TRANSPORTS:
            raise ValueError(
                f"unknown transport {transport!r} "
                f"(expected one of {TRANSPORTS})"
            )
        self.core = StorageGatewayCore(storage, secret=secret)
        self.transport = transport
        self._pool: Optional[concurrent.futures.ThreadPoolExecutor] = None
        if transport == "async":
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=self.HANDLER_THREADS,
                thread_name_prefix="gwhandler",
            )
            pool = self._pool
            core = self.core

            def fn(method, path, query, body, form=None, headers=None):
                if path == "/healthz" and method == "GET":
                    # liveness inline on the loop: a handler pool full
                    # of parked COMMIT waits must not read as "dead"
                    return core.handle(
                        method, path, query, body, form, headers
                    )
                return pool.submit(
                    core.handle, method, path, query, body, form, headers
                )
        else:
            fn = self.core.handle
        self._http = make_http_server(
            fn, ip, port, "StorageGateway", transport=transport
        )

    @property
    def port(self) -> int:
        return self._http.port

    def start(self) -> "StorageGatewayServer":
        self._http.start()
        return self

    def serve_forever(self) -> None:
        self._http.serve_forever()

    def shutdown(self) -> None:
        self._http.shutdown()
        if self._pool is not None:
            # wait=False: a handler parked on a wedged COMMIT must not
            # hang shutdown (same contract as the event server's pool)
            self._pool.shutdown(wait=False)
