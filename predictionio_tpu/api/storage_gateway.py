"""Storage gateway — the server half of the client-server storage backend.

The reference's production storage is client-server: HBase regionservers
for events (hbase/StorageClient.scala:40), PostgreSQL/MySQL over JDBC
(jdbc/StorageClient.scala), Elasticsearch over its transport protocol
(elasticsearch/StorageClient.scala:31-45). This gateway plays that role
for the TPU framework: one process owns the physical store (any embedded
backend — sqlite for durability, memory for tests) and exposes every DAO
trait over HTTP, so event servers, trainers, engine servers, and CLIs on
other hosts share a single storage service through the ``http`` client
backend (data/storage/http.py).

Protocol: POST /rpc with ``{"dao": <repo>, "method": <name>,
"args": {...}}`` -> ``{"result": ...}`` or ``{"error", "type"}``.
DAO methods, argument names, and record layouts mirror
data/storage/base.py one-to-one (the wire format lives in
data/storage/wire.py). An optional shared secret
(``--secret`` / PIO_STORAGE_SOURCES_<NAME>_SECRET on clients) gates every
request, playing the access-key role the event server has
(EventServer.scala:81-107).

Run via ``pio storagegateway [--port 7077]`` or programmatically with
``StorageGatewayServer(storage).start()``.
"""

from __future__ import annotations

import hmac
import logging
from typing import Any, Dict, Optional

from predictionio_tpu.api.http import JsonHTTPServer
from predictionio_tpu.data.storage import Storage, get_storage
from predictionio_tpu.data.storage.base import PartialBatchError, StorageError
from predictionio_tpu.data.storage import wire

logger = logging.getLogger(__name__)

DEFAULT_PORT = 7077  # beside the reference's 7070/7071 tools ports

# dao name on the wire -> (Storage accessor, record kind for rows,
# base.py trait whose public methods define the RPC surface)
_DAOS = {
    "levents": ("get_l_events", None, None),
    "apps": ("get_meta_data_apps", "app", "Apps"),
    "access_keys": ("get_meta_data_access_keys", "access_key", "AccessKeys"),
    "channels": ("get_meta_data_channels", "channel", "Channels"),
    "engine_manifests": (
        "get_meta_data_engine_manifests", "engine_manifest", "EngineManifests",
    ),
    "engine_instances": (
        "get_meta_data_engine_instances", "engine_instance", "EngineInstances",
    ),
    "evaluation_instances": (
        "get_meta_data_evaluation_instances",
        "evaluation_instance",
        "EvaluationInstances",
    ),
    "models": ("get_model_data_models", "model", "Models"),
}


def _trait_methods(trait_name: str) -> frozenset:
    """Public methods declared on the base.py trait — the RPC surface.

    Dispatching against the trait (not the backend instance) keeps the
    wire protocol pinned to data/storage/base.py: extra public helpers a
    concrete DAO happens to grow are NOT remotely callable.
    """
    from predictionio_tpu.data.storage import base as _base

    trait = getattr(_base, trait_name)
    return frozenset(
        m
        for m in vars(trait)
        if not m.startswith("_") and callable(getattr(trait, m, None))
    )


_TRAIT_ALLOWLIST: Dict[str, frozenset] = {}

class StorageGatewayCore:
    """Transport-independent RPC core (same pattern as QueryAPI)."""

    def __init__(self, storage: Optional[Storage] = None, secret: str = ""):
        self.storage = storage or get_storage()
        self.secret = secret

    # --- request entry ---

    def handle(self, method, path, query, body, form):
        import json

        if path == "/status" and method == "GET":
            return 200, {"status": "alive", "daos": sorted(_DAOS)}
        if path != "/rpc" or method != "POST":
            return 404, {"error": f"unknown route {method} {path}"}
        try:
            payload = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            return 400, {"error": f"invalid JSON body: {e}"}
        if not isinstance(payload, dict):
            return 400, {"error": "body must be a JSON object"}
        if self.secret:
            # in-body secret (request lines get logged; bodies don't),
            # constant-time comparison
            given = payload.get("secret") or ""
            if not hmac.compare_digest(str(given), self.secret):
                return 401, {"error": "invalid or missing secret"}
        try:
            result = self.call(
                payload.get("dao", ""),
                payload.get("method", ""),
                payload.get("args") or {},
            )
            return 200, {"result": result}
        except PartialBatchError as e:
            # carry the per-event outcome across the wire — the client
            # re-raises a PartialBatchError so the event server's
            # per-slot retry contract holds through the gateway too
            return 400, {
                "error": str(e),
                "type": "PartialBatchError",
                "event_ids": list(e.event_ids),
                "failed_ids": sorted(e.failed_ids),
            }
        except StorageError as e:
            return 400, {"error": str(e), "type": "StorageError"}
        except (KeyError, TypeError, ValueError) as e:
            return 400, {"error": str(e), "type": type(e).__name__}
        except Exception as e:  # backend bug — surface, don't hide
            logger.exception("gateway RPC failed")
            return 500, {"error": str(e), "type": type(e).__name__}

    # --- dispatch ---

    def call(self, dao: str, method: str, args: Dict[str, Any]) -> Any:
        if dao not in _DAOS:
            raise KeyError(f"unknown dao {dao!r}")
        accessor, kind, trait = _DAOS[dao]
        target = getattr(self.storage, accessor)()
        if dao == "levents":
            return self._call_levents(target, method, args)
        if trait not in _TRAIT_ALLOWLIST:
            _TRAIT_ALLOWLIST[trait] = _trait_methods(trait)
        if method not in _TRAIT_ALLOWLIST[trait]:
            raise KeyError(f"unknown {kind} method {method!r}")
        return self._call_metadata(target, kind, method, args)

    def _call_levents(self, le, method: str, args: Dict[str, Any]) -> Any:
        a = dict(args)
        if method in ("init", "remove"):
            return getattr(le, method)(a["app_id"], a.get("channel_id"))
        if method == "insert":
            ev = wire.event_from_wire(a["event"])
            return le.insert(ev, a["app_id"], a.get("channel_id"))
        if method == "write":
            evs = [wire.event_from_wire(e) for e in a["events"]]
            return le.write(evs, a["app_id"], a.get("channel_id"))
        if method == "insert_batch":
            evs = [wire.event_from_wire(e) for e in a["events"]]
            return le.insert_batch(evs, a["app_id"], a.get("channel_id"))
        if method == "get":
            ev = le.get(a["event_id"], a["app_id"], a.get("channel_id"))
            return None if ev is None else wire.event_to_wire(ev)
        if method == "delete":
            return le.delete(a["event_id"], a["app_id"], a.get("channel_id"))
        if method == "find":
            from predictionio_tpu.data.storage.base import UNSET

            kwargs: Dict[str, Any] = {
                "app_id": a["app_id"],
                "channel_id": a.get("channel_id"),
                "start_time": wire.opt_dt_from_wire(a.get("start_time")),
                "until_time": wire.opt_dt_from_wire(a.get("until_time")),
                "entity_type": a.get("entity_type"),
                "entity_id": a.get("entity_id"),
                "event_names": a.get("event_names"),
                "limit": a.get("limit"),
                "reversed": a.get("reversed", False),
            }
            for f in ("target_entity_type", "target_entity_id"):
                v = a.get(f, wire.UNSET_WIRE)
                kwargs[f] = UNSET if v == wire.UNSET_WIRE else v
            return [wire.event_to_wire(e) for e in le.find(**kwargs)]
        if method == "aggregate_properties":
            out = le.aggregate_properties(
                app_id=a["app_id"],
                entity_type=a["entity_type"],
                channel_id=a.get("channel_id"),
                start_time=wire.opt_dt_from_wire(a.get("start_time")),
                until_time=wire.opt_dt_from_wire(a.get("until_time")),
                required=a.get("required"),
            )
            # the fold happens HERE, next to the store: the wire carries
            # one PropertyMap per entity, not the full event history
            # (reference LEventAggregator.scala:39-136 semantics)
            return {
                k: wire.property_map_to_wire(v) for k, v in out.items()
            }
        if method in ("insert_columns", "insert_columns_v2"):
            # bulk columnar import: dictionaries as JSON strings, codes
            # and values as packed base64 (data/storage/columnar.py)
            from predictionio_tpu.data.storage import columnar as col

            import numpy as np

            return le.insert_columns_encoded(
                a["app_id"],
                a.get("channel_id"),
                event=a["event"],
                entity_type=a["entity_type"],
                target_entity_type=a["target_entity_type"],
                entity_names=a["entity_names"],
                entity_codes=col.array_from_b64(a["entity_codes"], np.int32),
                target_names=a["target_names"],
                target_codes=col.array_from_b64(a["target_codes"], np.int32),
                values=col.array_from_b64(a["values"], np.float32),
                value_property=a.get("value_property", "rating"),
                event_time=wire.opt_dt_from_wire(a.get("event_time")),
                event_times_ms=(
                    None
                    if a.get("event_times_ms") is None
                    else col.array_from_b64(a["event_times_ms"], np.int64)
                ),
            )
        if method == "find_columns_native":
            from predictionio_tpu.data.storage import columnar as col
            from predictionio_tpu.data.storage.base import UNSET

            tet = a.get("target_entity_type", wire.UNSET_WIRE)
            cols = le.find_columns_native(
                a["app_id"],
                a.get("channel_id"),
                value_spec=col.spec_from_wire(a.get("value_spec")),
                start_time=wire.opt_dt_from_wire(a.get("start_time")),
                until_time=wire.opt_dt_from_wire(a.get("until_time")),
                entity_type=a.get("entity_type"),
                target_entity_type=UNSET if tet == wire.UNSET_WIRE else tet,
                event_names=a.get("event_names"),
            )
            return None if cols is None else col.columnar_to_wire(cols)
        if method == "aggregate_properties_of_entity":
            pm = le.aggregate_properties_of_entity(
                app_id=a["app_id"],
                entity_type=a["entity_type"],
                entity_id=a["entity_id"],
                channel_id=a.get("channel_id"),
                start_time=wire.opt_dt_from_wire(a.get("start_time")),
                until_time=wire.opt_dt_from_wire(a.get("until_time")),
            )
            return None if pm is None else wire.property_map_to_wire(pm)
        raise KeyError(f"unknown levents method {method!r}")

    def _call_metadata(self, dao, kind: str, method: str, args: Dict[str, Any]) -> Any:
        a = dict(args)
        if "record" in a:
            a["record"] = wire.record_from_wire(kind, a["record"])
        record = a.pop("record", None)
        fn = getattr(dao, method, None)  # allowlisted against the trait in call()
        if fn is None:
            raise KeyError(f"unknown {kind} method {method!r}")
        out = fn(record, **a) if record is not None else fn(**a)
        # serialize records/record lists; scalars pass through
        if isinstance(out, list):
            return [
                wire.record_to_wire(x) if _is_record(x) else x for x in out
            ]
        return wire.record_to_wire(out) if _is_record(out) else out


def _is_record(x: Any) -> bool:
    import dataclasses

    return dataclasses.is_dataclass(x) and not isinstance(x, type)


_LOOPBACK_IPS = ("localhost", "127.0.0.1", "::1")


class StorageGatewayServer(JsonHTTPServer):
    """Defaults to loopback: the gateway exposes read/write access to ALL
    storage, so a non-loopback bind without a shared secret must be an
    explicit opt-in (``allow_insecure=True``), not a constructor default.
    The CLI path (`pio storagegateway`) opts in after printing a warning.
    """

    def __init__(
        self,
        storage: Optional[Storage] = None,
        ip: str = "localhost",
        port: int = DEFAULT_PORT,
        secret: str = "",
        allow_insecure: bool = False,
    ):
        if not secret and not allow_insecure and ip not in _LOOPBACK_IPS:
            raise ValueError(
                f"refusing to bind {ip!r} without a secret: pass secret=... "
                "or allow_insecure=True to expose unauthenticated storage "
                "on a non-loopback interface"
            )
        self.core = StorageGatewayCore(storage, secret=secret)
        super().__init__(self.core.handle, ip, port, "StorageGateway")
