"""Per-process observability sideband listener.

An SO_REUSEPORT fleet shares ONE serving port — the kernel routes each
accepted connection to an arbitrary worker, so a scrape of the shared
port samples a random member instead of enumerating the fleet. Exact
federation (utils/telemetry.py) needs every process individually
addressable. This sideband is the answer: a tiny second HTTP listener
per worker serving ONLY the observability surface —

- ``GET /metrics``      — the process-global registry,
- ``GET /healthz``      — liveness,
- ``GET /readyz``       — the process's daemon-stall verdict,
- ``GET /debug/traces.json`` — the span ring, with the incremental
  ``?since=<seq>`` cursor (gated by ``access_key`` when configured).

``pio deploy --metrics-port P`` / ``pio eventserver --metrics-port P``
start one beside the main server; the fleet supervisor
(``pio deploy --workers N --collector-url …``) assigns each worker its
own sideband port and registers those URLs with the local collector.
The sideband refuses non-loopback binds without an access key — it
exposes the same information class as the main servers' gated debug
routes.
"""

from __future__ import annotations

import logging
import secrets as _secrets

from predictionio_tpu.api.aio_http import make_http_server
from predictionio_tpu.api.http import traces_payload
from predictionio_tpu.utils import health as _health
from predictionio_tpu.utils import metrics as _metrics

logger = logging.getLogger(__name__)

__all__ = ["ObservabilitySideband"]

_LOOPBACK_IPS = ("localhost", "127.0.0.1", "::1")


class ObservabilitySideband:
    """The sideband server. Handlers are allocation-light and touch no
    storage, so they run inline on the event loop — a scrape can never
    park behind the main server's handler pool."""

    def __init__(
        self,
        ip: str = "localhost",
        port: int = 0,
        access_key: str = "",
        server_name: str = "Sideband",
    ):
        if not access_key and ip not in _LOOPBACK_IPS:
            raise ValueError(
                f"refusing to bind sideband on {ip!r} without an access "
                "key: the span dump carries entity ids and timings"
            )
        self.access_key = access_key
        self._http = make_http_server(
            self._handle, ip, port, server_name, transport="async"
        )

    def _handle(self, method, path, query, body, form=None, headers=None):
        if method != "GET":
            return 405, {"message": "Method not allowed."}
        if path == "/healthz":
            return 200, _health.liveness()
        if path == "/readyz":
            ok, payload = _health.readiness()
            return (200 if ok else 503), payload
        if path == "/metrics":
            return (
                200,
                _metrics.get_registry().render(),
                _metrics.render_content_type(),
            )
        if path == "/debug/traces.json":
            if self.access_key and not _secrets.compare_digest(
                (query or {}).get("accessKey", ""), self.access_key
            ):
                return 401, {"message": "Invalid accessKey."}
            return traces_payload(query)
        return 404, {"message": f"unknown route {method} {path}"}

    @property
    def port(self) -> int:
        return self._http.port

    def start(self) -> "ObservabilitySideband":
        self._http.start()
        return self

    def shutdown(self) -> None:
        self._http.shutdown()
