"""API-stability markers (reference common module:
common/src/main/scala/io/prediction/annotation/DeveloperApi.java and
Experimental.java — the only contents of the reference's `common` sbt
module). The Java originals are retention-CLASS annotations surfaced in
scaladoc; the Python analogs are decorators that tag the object with
``__pio_api__`` and prepend the stability contract to its docstring, so
the marker is visible both to tooling (``getattr(obj, "__pio_api__")``)
and in ``help()``.
"""

from __future__ import annotations

from typing import TypeVar

T = TypeVar("T")

_DEVELOPER_NOTE = (
    "A lower-level, developer-facing API. Unlike the user-facing "
    "controller API, these interfaces may change across minor versions."
)
_EXPERIMENTAL_NOTE = (
    "An experimental API for users who want to try new features; may be "
    "changed or removed in minor versions without deprecation."
)


def _mark(obj: T, kind: str, note: str) -> T:
    try:
        obj.__pio_api__ = kind
        obj.__doc__ = f"::{kind}:: {note}\n\n{obj.__doc__ or ''}"
    except (AttributeError, TypeError):  # slotted/builtin objects
        pass
    return obj


def developer_api(obj: T) -> T:
    """Marks a developer-facing API (reference @DeveloperApi)."""
    return _mark(obj, "developer_api", _DEVELOPER_NOTE)


def experimental(obj: T) -> T:
    """Marks an experimental API (reference @Experimental)."""
    return _mark(obj, "experimental", _EXPERIMENTAL_NOTE)
