"""Markov chain model over state-transition tallies.

Capability parity with the reference MarkovChain
(e2/src/main/scala/io/prediction/e2/engine/MarkovChain.scala:25-89):
``train`` takes a sparse tally of transitions (a coordinate matrix), keeps
the top-N transitions per source state normalized by the source's total
tally, and ``predict`` propagates a current-state probability vector one
step (current @ P over the kept transitions).

TPU-first design: the kept transitions live as dense [n_states, top_n]
(target-index, probability) arrays — a static shape XLA can tile — and
predict is one scatter-add device program instead of a per-row RDD map +
driver-side column sums.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class MarkovChainModel:
    """Top-N normalized transitions (reference MarkovChainModel :63-89)."""

    n_states: int
    n: int  # top-N kept per state
    targets: np.ndarray  # [n_states, n] int32 (self-loop padding w/ 0 prob)
    probs: np.ndarray  # [n_states, n] float32

    def transition_map(self) -> Dict[int, List[Tuple[int, float]]]:
        """Per-state kept transitions as {state: [(target, prob)]}, sorted
        by target index (the reference's SparseVector view)."""
        out: Dict[int, List[Tuple[int, float]]] = {}
        for i in range(self.n_states):
            entries = [
                (int(t), float(p))
                for t, p in zip(self.targets[i], self.probs[i])
                if p > 0.0
            ]
            if entries:
                out[i] = sorted(entries)
        return out

    def predict(self, current_state: Sequence[float]) -> List[float]:
        """Probabilities of the next state (reference predict :68-88)."""
        cur = jnp.asarray(np.asarray(current_state, np.float32))
        out = _step(
            cur, jnp.asarray(self.targets), jnp.asarray(self.probs),
            self.n_states,
        )
        return [float(x) for x in np.asarray(out)]


@functools.partial(jax.jit, static_argnames=("n_states",))
def _step(cur, targets, probs, n_states):
    # next[j] = sum_i cur[i] * P[i, j] over kept transitions
    contrib = probs * cur[:, None]  # [n_states, n]
    return jnp.zeros(n_states, jnp.float32).at[targets].add(contrib)


class MarkovChain:
    """Trainer (reference object MarkovChain :25-62)."""

    @staticmethod
    def train(
        entries: Sequence[Tuple[int, int, float]], n_states: int, top_n: int
    ) -> MarkovChainModel:
        """``entries`` is the transition tally as (from, to, count) triples
        (the reference's CoordinateMatrix entries)."""
        tally: Dict[int, Dict[int, float]] = {}
        for i, j, v in entries:
            if not (0 <= int(i) < n_states and 0 <= int(j) < n_states):
                raise ValueError(
                    f"transition ({i} -> {j}) out of range for "
                    f"{n_states} states"
                )
            row = tally.setdefault(int(i), {})
            row[int(j)] = row.get(int(j), 0.0) + float(v)

        targets = np.zeros((n_states, top_n), np.int32)
        probs = np.zeros((n_states, top_n), np.float32)
        for i, row in tally.items():
            total = sum(row.values())
            top = sorted(row.items(), key=lambda kv: -kv[1])[:top_n]
            top.sort(key=lambda kv: kv[0])  # reference sorts kept by index
            for k, (j, v) in enumerate(top):
                targets[i, k] = j
                probs[i, k] = v / total
        return MarkovChainModel(
            n_states=n_states, n=top_n, targets=targets, probs=probs
        )
