"""Markov chain model over state-transition tallies.

Capability parity with the reference MarkovChain
(e2/src/main/scala/io/prediction/e2/engine/MarkovChain.scala:25-89):
``train`` takes a sparse tally of transitions (a coordinate matrix), keeps
the top-N transitions per source state normalized by the source's total
tally, and ``predict`` propagates a current-state probability vector one
step (current @ P over the kept transitions).

TPU-first design: the kept transitions live as dense [n_states, top_n]
(target-index, probability) arrays — a static shape XLA can tile — and
predict is one scatter-add device program instead of a per-row RDD map +
driver-side column sums.

Multi-chip: with a ``mesh``, the [n_states, top_n] transition rows and
the current-state vector shard over the mesh's data axis; each device
scatter-adds its states' outgoing probability mass into a local
next-state vector and XLA all-reduces the partials over ICI (the TPU
analog of the reference's per-row RDD map + driver column sums).
"""

from __future__ import annotations

import dataclasses
import functools
import weakref
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from predictionio_tpu.parallel.mesh import shard_batch


@dataclasses.dataclass
class MarkovChainModel:
    """Top-N normalized transitions (reference MarkovChainModel :63-89)."""

    n_states: int
    n: int  # top-N kept per state
    targets: np.ndarray  # [n_states, n] int32 (self-loop padding w/ 0 prob)
    probs: np.ndarray  # [n_states, n] float32
    # device-resident transition arrays, placed once per (mesh, axis)
    # and reused across predicts (device state; never pickled)
    _placed: Optional[tuple] = dataclasses.field(
        default=None, repr=False, compare=False
    )

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_placed"] = None
        return state

    def transition_map(self) -> Dict[int, List[Tuple[int, float]]]:
        """Per-state kept transitions as {state: [(target, prob)]}, sorted
        by target index (the reference's SparseVector view)."""
        out: Dict[int, List[Tuple[int, float]]] = {}
        for i in range(self.n_states):
            entries = [
                (int(t), float(p))
                for t, p in zip(self.targets[i], self.probs[i])
                if p > 0.0
            ]
            if entries:
                out[i] = sorted(entries)
        return out

    def predict(
        self,
        current_state: Sequence[float],
        mesh: Optional[Mesh] = None,
        axis: str = "data",
    ) -> List[float]:
        """Probabilities of the next state (reference predict :68-88).

        With a ``mesh``, source states shard over its ``axis`` and each
        device's partial next-state vector all-reduces over ICI; padding
        rows carry zero probability, so results are mesh-shape
        independent up to float summation order."""
        cur = np.asarray(current_state, np.float32)
        if mesh is not None and mesh.shape[axis] == 1:
            mesh = None
        t_dev, p_dev = self._device_transitions(mesh, axis)
        if mesh is None:
            cur_dev = jnp.asarray(cur)
        else:
            cur_dev, _ = shard_batch(mesh, cur, axis)
        out = _step(cur_dev, t_dev, p_dev, self.n_states)
        return [float(x) for x in np.asarray(out)]

    def _device_transitions(self, mesh: Optional[Mesh], axis: str):
        """Transition arrays on device, placed ONCE per (mesh, axis) and
        cached — repeat predicts ship only the [n_states] state vector
        (same pattern as SimilarityScorer's device-resident factors).
        shard_batch zero-pads the state rows to divide the mesh axis;
        padded rows carry zero probability, so they drop from the sum.

        The cache key holds the mesh itself by WEAKREF and compares
        object identity: an ``id(mesh)`` key could collide when a dead
        mesh's address is reused by a new one, returning arrays placed
        for devices/sharding of a mesh that no longer exists."""
        if self._placed is not None:
            mesh_ref, cached_axis, t_dev, p_dev = self._placed
            cached_mesh = mesh_ref() if mesh_ref is not None else None
            if (
                cached_axis == axis
                and cached_mesh is mesh
                and (mesh is not None or mesh_ref is None)
            ):
                return t_dev, p_dev
        if mesh is None:
            t_dev = jnp.asarray(self.targets)
            p_dev = jnp.asarray(self.probs)
        else:
            t_dev, _ = shard_batch(mesh, self.targets, axis)
            p_dev, _ = shard_batch(mesh, self.probs, axis)
        self._placed = (
            weakref.ref(mesh) if mesh is not None else None,
            axis, t_dev, p_dev,
        )
        return t_dev, p_dev


@functools.partial(jax.jit, static_argnames=("n_states",))
def _step(cur, targets, probs, n_states):
    # next[j] = sum_i cur[i] * P[i, j] over kept transitions; with a
    # mesh the rows arrive sharded and XLA all-reduces per-device
    # partial vectors over ICI. Padding rows carry zero probs (their
    # target index 0 contributes 0.0).
    contrib = probs * cur[:, None]  # [n_states(+pad), n]
    return jnp.zeros(n_states, jnp.float32).at[targets].add(
        contrib, mode="drop"
    )


class MarkovChain:
    """Trainer (reference object MarkovChain :25-62)."""

    @staticmethod
    def train(
        entries: Sequence[Tuple[int, int, float]], n_states: int, top_n: int
    ) -> MarkovChainModel:
        """``entries`` is the transition tally as (from, to, count) triples
        (the reference's CoordinateMatrix entries)."""
        tally: Dict[int, Dict[int, float]] = {}
        for i, j, v in entries:
            if not (0 <= int(i) < n_states and 0 <= int(j) < n_states):
                raise ValueError(
                    f"transition ({i} -> {j}) out of range for "
                    f"{n_states} states"
                )
            row = tally.setdefault(int(i), {})
            row[int(j)] = row.get(int(j), 0.0) + float(v)

        targets = np.zeros((n_states, top_n), np.int32)
        probs = np.zeros((n_states, top_n), np.float32)
        for i, row in tally.items():
            total = sum(row.values())
            top = sorted(row.items(), key=lambda kv: -kv[1])[:top_n]
            top.sort(key=lambda kv: kv[0])  # reference sorts kept by index
            for k, (j, v) in enumerate(top):
                targets[i, k] = j
                probs[i, k] = v / total
        return MarkovChainModel(
            n_states=n_states, n=top_n, targets=targets, probs=probs
        )
