"""Naive Bayes with string-categorical features.

Capability parity with the reference CategoricalNaiveBayes
(e2/src/main/scala/io/prediction/e2/engine/CategoricalNaiveBayes.scala:23-151):
``train`` computes log priors log(n_label / n_total) and per-feature-slot
log likelihoods log(count(label, slot, value) / n_label); the model scores
a point as prior + sum of per-slot likelihoods, with a pluggable default
for feature values unseen under a label (reference defaultLikelihood,
default negative infinity).

TPU-first design: where the reference counts with a combineByKey shuffle
over RDD partitions, labels and per-slot feature values are dense-encoded
(BiMap) on host and counted in ONE device segment-sum over flattened
(slot, label, value) keys; batch prediction is a gather + reduction over a
dense [L, S, V] likelihood tensor — one XLA program per batch instead of a
per-point Scala loop.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.parallel.mesh import pad_to_multiple

NEG_INF = float("-inf")


@dataclasses.dataclass(frozen=True)
class LabeledPoint:
    """A labeled categorical data point (reference LabeledPoint)."""

    label: str
    features: Tuple[str, ...]

    def __post_init__(self):
        object.__setattr__(self, "features", tuple(self.features))


@functools.partial(jax.jit, static_argnames=("n_keys",))
def _count_flat(keys, n_keys):
    # scatter-add of ones over flattened (slot, label, value) keys; with a
    # mesh the keys arrive sharded and XLA all-reduces per-device partial
    # counts over ICI (the TPU analog of the reference's combineByKey over
    # RDD partitions). Out-of-range keys (the mesh-padding sentinel
    # n_keys) drop, not clamp.
    return jnp.zeros(n_keys, jnp.float32).at[keys].add(1.0, mode="drop")


@dataclasses.dataclass
class CategoricalNaiveBayesModel:
    """Trained model. ``priors``/``likelihoods`` expose the reference's
    map-shaped view; scoring runs on the dense tensors."""

    label_index: BiMap  # label -> l
    value_indexes: Tuple[BiMap, ...]  # per slot: value -> v
    log_priors: np.ndarray  # [L]
    log_likelihoods: np.ndarray  # [L, S, V] (NEG_INF where unseen)

    @property
    def feature_count(self) -> int:
        return self.log_likelihoods.shape[1]

    @property
    def priors(self) -> Dict[str, float]:
        return {
            label: float(self.log_priors[l])
            for label, l in self.label_index.items()
        }

    @property
    def likelihoods(self) -> Dict[str, List[Dict[str, float]]]:
        out: Dict[str, List[Dict[str, float]]] = {}
        for label, l in self.label_index.items():
            out[label] = [
                {
                    value: float(self.log_likelihoods[l, s, v])
                    for value, v in self.value_indexes[s].items()
                    if self.log_likelihoods[l, s, v] != NEG_INF
                }
                for s in range(self.feature_count)
            ]
        return out

    def log_score(
        self,
        point: LabeledPoint,
        default_likelihood: Callable[[Sequence[float]], float] = lambda ls: NEG_INF,
    ) -> Optional[float]:
        """Log score of (label, features); None when the label is unknown
        (reference logScore :96-115)."""
        if point.label not in self.label_index:
            return None
        self._check_feature_count(point.features)
        return self._log_score_internal(
            point.label, point.features, default_likelihood
        )

    def _check_feature_count(self, features: Sequence[str]) -> None:
        if len(features) != self.feature_count:
            raise ValueError(
                f"query has {len(features)} feature(s); model was trained "
                f"with {self.feature_count}"
            )

    def _log_score_internal(
        self, label: str, features: Sequence[str], default_likelihood
    ) -> float:
        l = self.label_index[label]
        total = float(self.log_priors[l])
        for s, feature in enumerate(features):
            v = self.value_indexes[s].get(feature)
            ll = self.log_likelihoods[l, s, v] if v is not None else NEG_INF
            if ll == NEG_INF:
                present = self.log_likelihoods[l, s]
                ll = default_likelihood(
                    [float(x) for x in present[present != NEG_INF]]
                )
            total += ll
        return total

    def predict(self, features: Sequence[str]) -> str:
        """Label with the highest score (reference predict :122-133)."""
        return self.predict_batch([tuple(features)])[0]

    def predict_batch(self, features_batch: Sequence[Sequence[str]]) -> List[str]:
        """Vectorized prediction: one gather+sum device program for the
        whole batch (the TPU hot path; no reference analog)."""
        n, S = len(features_batch), self.feature_count
        for features in features_batch:
            self._check_feature_count(features)
        enc = np.zeros((n, S), np.int32)
        known = np.zeros((n, S), bool)
        for i, features in enumerate(features_batch):
            for s in range(S):
                v = self.value_indexes[s].get(features[s])
                if v is not None:
                    enc[i, s] = v
                    known[i, s] = True
        scores = _batch_scores(
            jnp.asarray(self.log_likelihoods),
            jnp.asarray(self.log_priors),
            jnp.asarray(enc),
            jnp.asarray(known),
        )
        best = np.asarray(jnp.argmax(scores, axis=1))
        inv = self.label_index.inverse()
        return [inv[int(b)] for b in best]


@jax.jit
def _batch_scores(log_likelihoods, log_priors, enc, known):
    # log_likelihoods [L,S,V], enc [N,S], known [N,S] -> scores [N,L]
    ll = log_likelihoods[:, jnp.arange(enc.shape[1])[None, :], enc]  # [L,N,S]
    ll = jnp.where(known[None, :, :], ll, NEG_INF)
    return log_priors[None, :] + jnp.transpose(ll, (1, 0, 2)).sum(-1)


class CategoricalNaiveBayes:
    """Trainer (reference object CategoricalNaiveBayes :29-80)."""

    @staticmethod
    def train(
        points: Sequence[LabeledPoint],
        mesh: Optional[Mesh] = None,
        axis: str = "data",
    ) -> CategoricalNaiveBayesModel:
        """Train; with a ``mesh`` the flattened count keys shard over its
        ``axis`` and per-device partial counts all-reduce (see module
        docstring). Counts are exact integers either way, so the model is
        bitwise identical across mesh shapes."""
        if not points:
            raise ValueError("cannot train on an empty dataset")
        S = len(points[0].features)
        for p in points:
            if len(p.features) != S:
                raise ValueError(
                    "all points must have the same number of features"
                )

        label_index = BiMap.string_int([p.label for p in points])
        value_indexes = tuple(
            BiMap.string_int([p.features[s] for p in points]) for s in range(S)
        )
        L = len(label_index)
        V = max((len(vi) for vi in value_indexes), default=1)

        labels = np.asarray([label_index[p.label] for p in points], np.int32)
        # flattened keys (s * L + l) * V + v counted in one device scatter-add
        flat_keys = np.empty(len(points) * S, np.int32)
        pos = 0
        for s in range(S):
            vi = value_indexes[s]
            values = np.asarray(
                [vi[p.features[s]] for p in points], np.int32
            )
            flat_keys[pos : pos + len(points)] = (s * L + labels) * V + values
            pos += len(points)
        n_keys = S * L * V
        if mesh is not None and mesh.shape[axis] > 1:
            # pad with the out-of-range sentinel (dropped by the scatter)
            # so the key vector shards evenly, then place it sharded
            padded = pad_to_multiple(max(len(flat_keys), 1), mesh.shape[axis])
            if padded != len(flat_keys):
                flat_keys = np.concatenate(
                    [flat_keys,
                     np.full(padded - len(flat_keys), n_keys, np.int32)]
                )
            keys_dev = jax.device_put(
                flat_keys, NamedSharding(mesh, P(axis))
            )
        else:
            keys_dev = jnp.asarray(flat_keys)
        counts = np.asarray(_count_flat(keys_dev, n_keys)).reshape(S, L, V)

        label_counts = np.bincount(labels, minlength=L).astype(np.float64)
        log_priors = np.log(label_counts / len(points)).astype(np.float32)
        with np.errstate(divide="ignore"):
            log_likelihoods = np.where(
                counts > 0,
                np.log(counts / label_counts[None, :, None]),
                NEG_INF,
            ).transpose(1, 0, 2).astype(np.float32)  # [L, S, V]
        return CategoricalNaiveBayesModel(
            label_index=label_index,
            value_indexes=value_indexes,
            log_priors=log_priors,
            log_likelihoods=log_likelihoods,
        )
