"""(property, value) -> binary feature vectors.

Capability parity with the reference PropertiesToBinary
(e2/src/main/scala/io/prediction/e2/engine/PropertiesToBinary.scala:24-52):
build an index over every distinct (property, value) pair seen in the
input (restricted to a whitelist of property names), then encode a
property map as a binary vector with 1.0 at each present pair's index.

The encoder returns dense float32 matrices — the device-bound form for
downstream kernels (a batch encodes as one [n, F] array ready for
``jax.device_put``) — plus a sparse-indices view for parity with the
reference's SparseVector output.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Mapping, Sequence, Set, Tuple

import numpy as np

from predictionio_tpu.data.bimap import BiMap


class PropertiesToBinary:
    def __init__(self, property_map: Mapping[Tuple[str, str], int]):
        self.property_map = BiMap(dict(property_map))

    @property
    def num_features(self) -> int:
        return len(self.property_map)

    @classmethod
    def fit(
        cls,
        input_maps: Iterable[Mapping[str, str]],
        properties: Set[str],
    ) -> "PropertiesToBinary":
        """Index all distinct whitelisted (property, value) pairs
        (reference object PropertiesToBinary.apply :44-52). Pair order is
        first-seen, deterministic for a given input order."""
        seen: Dict[Tuple[str, str], int] = {}
        for m in input_maps:
            for k, v in m.items():
                if k in properties and (k, v) not in seen:
                    seen[(k, v)] = len(seen)
        return cls(seen)

    def indices(self, pairs: Sequence[Tuple[str, str]]) -> List[int]:
        """Sparse view: indices set to 1 (reference toBinary's SparseVector)."""
        return sorted(
            idx
            for pair in pairs
            if (idx := self.property_map.get(pair)) is not None
        )

    def to_binary(self, pairs: Sequence[Tuple[str, str]]) -> np.ndarray:
        """Dense binary vector [num_features]."""
        out = np.zeros(self.num_features, np.float32)
        out[self.indices(pairs)] = 1.0
        return out

    def to_binary_batch(
        self, maps: Sequence[Mapping[str, str]]
    ) -> np.ndarray:
        """Dense [n, num_features] batch — the device-bound form."""
        out = np.zeros((len(maps), self.num_features), np.float32)
        for i, m in enumerate(maps):
            out[i, self.indices(list(m.items()))] = 1.0
        return out
