"""k-fold cross-validation splitting.

Capability parity with the reference CommonHelperFunctions.splitData
(e2/src/main/scala/io/prediction/e2/evaluation/CrossValidation.scala:21-64):
point index modulo evalK selects the held-out fold; every other point
trains. Fold membership is positional (zipWithIndex in the reference),
so splits are deterministic for a given dataset order.
"""

from __future__ import annotations

from typing import Callable, List, Sequence, Tuple, TypeVar

D = TypeVar("D")
TD = TypeVar("TD")
EI = TypeVar("EI")
Q = TypeVar("Q")
A = TypeVar("A")


def split_data(
    eval_k: int,
    dataset: Sequence[D],
    evaluator_info: EI,
    training_data_creator: Callable[[List[D]], TD],
    query_creator: Callable[[D], Q],
    actual_creator: Callable[[D], A],
) -> List[Tuple[TD, EI, List[Tuple[Q, A]]]]:
    if eval_k < 1:
        raise ValueError("eval_k must be >= 1")
    out = []
    for fold in range(eval_k):
        training = [d for i, d in enumerate(dataset) if i % eval_k != fold]
        testing = [d for i, d in enumerate(dataset) if i % eval_k == fold]
        out.append(
            (
                training_data_creator(training),
                evaluator_info,
                [(query_creator(d), actual_creator(d)) for d in testing],
            )
        )
    return out
