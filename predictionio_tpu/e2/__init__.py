"""e2 — reusable algorithm/evaluation library.

Capability parity with the reference ``e2`` module (e2/src/main/scala/io/
prediction/e2/): CategoricalNaiveBayes, MarkovChain, PropertiesToBinary,
and k-fold ``split_data``. Where the reference runs these as Spark RDD
programs, counts and predictions here are dense-array JAX programs
(segment-sum count reductions, gather-based scoring, scatter-add
transition mixing) that XLA tiles onto the device.
"""

from predictionio_tpu.e2.naive_bayes import (  # noqa: F401
    CategoricalNaiveBayes,
    CategoricalNaiveBayesModel,
    LabeledPoint,
)
from predictionio_tpu.e2.markov_chain import (  # noqa: F401
    MarkovChain,
    MarkovChainModel,
)
from predictionio_tpu.e2.properties import PropertiesToBinary  # noqa: F401
from predictionio_tpu.e2.evaluation import split_data  # noqa: F401
