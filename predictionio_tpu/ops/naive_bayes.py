"""Multinomial Naive Bayes on device.

The kernel behind the classification engine template (reference
examples/scala-parallel-classification/add-algorithm/src/main/scala/
NaiveBayesAlgorithm.scala:24-44, which delegates to MLlib
``NaiveBayes.train(points, lambda)``). Semantics match MLlib multinomial NB:

  pi[c]       = log(n_c + lambda) - log(n + lambda * C)
  theta[c][j] = log(S[c][j] + lambda) - log(sum_j S[c][j] + lambda * F)

where S[c][j] is the sum of feature j over class-c points.

TPU-first design: the per-class feature sums are ONE [C, n] x [n, F]
matmul (one-hot labels against the feature matrix — MXU work, not a
combineByKey shuffle), and batch prediction is scores = X @ theta.T + pi,
again a single matmul. All shapes static; float32 accumulation.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class NaiveBayesModelArrays:
    """log class priors [C] and log feature likelihoods [C, F]."""

    pi: np.ndarray
    theta: np.ndarray
    labels: np.ndarray  # [C] the class label values (e.g. 0.0, 1.0, 2.0)

    @property
    def n_classes(self) -> int:
        return self.pi.shape[0]


@functools.partial(jax.jit, static_argnames=("n_classes",))
def _fit(features, label_idx, lam, n_classes):
    n = features.shape[0]
    one_hot = jnp.asarray(
        label_idx[None, :] == jnp.arange(n_classes)[:, None], jnp.float32
    )  # [C, n]
    class_counts = one_hot.sum(axis=1)  # [C]
    sums = jnp.dot(one_hot, features, preferred_element_type=jnp.float32)  # [C, F]
    pi = jnp.log(class_counts + lam) - jnp.log(
        jnp.float32(n) + lam * n_classes
    )
    theta = jnp.log(sums + lam) - jnp.log(
        sums.sum(axis=1, keepdims=True) + lam * features.shape[1]
    )
    return pi, theta


@jax.jit
def _scores(features, pi, theta):
    return (
        jnp.dot(features, theta.T, preferred_element_type=jnp.float32)
        + pi[None, :]
    )


def train_naive_bayes(
    features: np.ndarray, labels: np.ndarray, lam: float = 1.0
) -> NaiveBayesModelArrays:
    """Train on [n, F] nonnegative features with arbitrary scalar labels."""
    features = np.asarray(features, np.float32)
    labels = np.asarray(labels)
    if features.ndim != 2 or len(features) != len(labels):
        raise ValueError("features must be [n, F] aligned with labels [n]")
    if len(labels) == 0:
        raise ValueError("cannot train on an empty dataset")
    if (features < 0).any():
        raise ValueError("multinomial NB requires nonnegative features")
    classes, label_idx = np.unique(labels, return_inverse=True)
    pi, theta = _fit(
        jnp.asarray(features),
        jnp.asarray(label_idx.astype(np.int32)),
        jnp.float32(lam),
        n_classes=len(classes),
    )
    return NaiveBayesModelArrays(
        pi=np.asarray(pi), theta=np.asarray(theta), labels=classes
    )


def predict_naive_bayes(
    model: NaiveBayesModelArrays, features: np.ndarray
) -> np.ndarray:
    """Predicted label for each row of [B, F] (batch = one matmul)."""
    features = np.atleast_2d(np.asarray(features, np.float32))
    scores = _scores(
        jnp.asarray(features), jnp.asarray(model.pi), jnp.asarray(model.theta)
    )
    return model.labels[np.asarray(jnp.argmax(scores, axis=1))]
