"""Multinomial Naive Bayes on device.

The kernel behind the classification engine template (reference
examples/scala-parallel-classification/add-algorithm/src/main/scala/
NaiveBayesAlgorithm.scala:24-44, which delegates to MLlib
``NaiveBayes.train(points, lambda)``). Semantics match MLlib multinomial NB:

  pi[c]       = log(n_c + lambda) - log(n + lambda * C)
  theta[c][j] = log(S[c][j] + lambda) - log(sum_j S[c][j] + lambda * F)

where S[c][j] is the sum of feature j over class-c points.

TPU-first design: the per-class feature sums are ONE [C, n] x [n, F]
matmul (one-hot labels against the feature matrix — MXU work, not a
combineByKey shuffle), and batch prediction is scores = X @ theta.T + pi,
again a single matmul. All shapes static; float32 accumulation.

Multi-chip: with a ``mesh``, the [n, F] feature matrix and the label
vector shard rows over the mesh's data axis; the one-hot contraction
reduces over that sharded axis, so XLA lowers the [C, F] per-class sums
to per-shard matmuls + an all-reduce over ICI — the TPU analog of the
reference's cluster-distributed MLlib ``NaiveBayes.train`` (a
combineByKey over RDD partitions). Row padding carries label index C
(matching no class), so padded rows contribute nothing; the true row
count is recovered on device as ``class_counts.sum()``.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.parallel.mesh import pad_to_multiple, shard_batch


@dataclasses.dataclass
class NaiveBayesModelArrays:
    """log class priors [C] and log feature likelihoods [C, F]."""

    pi: np.ndarray
    theta: np.ndarray
    labels: np.ndarray  # [C] the class label values (e.g. 0.0, 1.0, 2.0)

    @property
    def n_classes(self) -> int:
        return self.pi.shape[0]


@functools.partial(jax.jit, static_argnames=("n_classes",))
def _fit(features, label_idx, lam, n_classes):
    one_hot = jnp.asarray(
        label_idx[None, :] == jnp.arange(n_classes)[:, None], jnp.float32
    )  # [C, n]
    class_counts = one_hot.sum(axis=1)  # [C]
    # true row count: padded rows carry label index n_classes, matching no
    # class, so they drop out of every count (exact integer sum)
    n = class_counts.sum()
    sums = jnp.dot(one_hot, features, preferred_element_type=jnp.float32)  # [C, F]
    pi = jnp.log(class_counts + lam) - jnp.log(n + lam * n_classes)
    theta = jnp.log(sums + lam) - jnp.log(
        sums.sum(axis=1, keepdims=True) + lam * features.shape[1]
    )
    return pi, theta


@jax.jit
def _scores(features, pi, theta):
    return (
        jnp.dot(features, theta.T, preferred_element_type=jnp.float32)
        + pi[None, :]
    )


def train_naive_bayes(
    features: np.ndarray,
    labels: np.ndarray,
    lam: float = 1.0,
    mesh: Optional[Mesh] = None,
    axis: str = "data",
) -> NaiveBayesModelArrays:
    """Train on [n, F] nonnegative features with arbitrary scalar labels.

    With a ``mesh``, rows shard over its ``axis`` and the per-class sums
    all-reduce over ICI (see module docstring); results are bitwise
    independent of the mesh shape up to float summation order.
    """
    features = np.asarray(features, np.float32)
    labels = np.asarray(labels)
    if features.ndim != 2 or len(features) != len(labels):
        raise ValueError("features must be [n, F] aligned with labels [n]")
    if len(labels) == 0:
        raise ValueError("cannot train on an empty dataset")
    if (features < 0).any():
        raise ValueError("multinomial NB requires nonnegative features")
    classes, label_idx = np.unique(labels, return_inverse=True)
    label_idx = label_idx.astype(np.int32)
    if mesh is not None and mesh.shape[axis] == 1:
        mesh = None
    if mesh is None:
        feats_dev = jnp.asarray(features)
        labels_dev = jnp.asarray(label_idx)
    else:
        # rows pad so they shard evenly (zero feature rows); padding
        # labels index n_classes (no one-hot match) so the padded rows
        # vanish from every sum — labels can't use shard_batch's zero
        # padding, which would inflate class 0's counts
        n = len(labels)
        padded = pad_to_multiple(n, mesh.shape[axis])
        if padded != n:
            label_idx = np.pad(
                label_idx, (0, padded - n),
                constant_values=np.int32(len(classes)),
            )
        feats_dev, _ = shard_batch(mesh, features, axis)
        labels_dev = jax.device_put(label_idx, NamedSharding(mesh, P(axis)))
    pi, theta = _fit(
        feats_dev, labels_dev, jnp.float32(lam), n_classes=len(classes)
    )
    return NaiveBayesModelArrays(
        pi=np.asarray(pi), theta=np.asarray(theta), labels=classes
    )


def predict_naive_bayes(
    model: NaiveBayesModelArrays,
    features: np.ndarray,
    mesh: Optional[Mesh] = None,
    axis: str = "data",
) -> np.ndarray:
    """Predicted label for each row of [B, F] (batch = one matmul).

    With a ``mesh``, the query batch shards over its ``axis`` (pure data
    parallelism — each shard scores its rows against the replicated
    model); padding rows are sliced off the result.
    """
    features = np.atleast_2d(np.asarray(features, np.float32))
    b = features.shape[0]
    if mesh is not None and mesh.shape[axis] > 1:
        feats_dev, _ = shard_batch(mesh, features, axis)
    else:
        feats_dev = jnp.asarray(features)
    scores = _scores(
        feats_dev, jnp.asarray(model.pi), jnp.asarray(model.theta)
    )
    return model.labels[np.asarray(jnp.argmax(scores, axis=1))[:b]]
