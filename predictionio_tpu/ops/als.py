"""Alternating Least Squares on a TPU mesh — explicit and implicit feedback.

This is the TPU-native replacement for MLlib ALS
(`ALS.train` / `ALS.trainImplicit`), which the reference's recommendation
templates delegate to (examples/scala-parallel-recommendation/custom-query/
src/main/scala/ALSAlgorithm.scala:66-73). MLlib's implementation exchanges
rating blocks over Spark shuffles each half-iteration; here the ragged
rating matrix is repacked host-side into a **fixed-width segment layout**
(ELL-style, in the spirit of the ALX paper's static-shape recipe,
PAPERS.md — arXiv:2112.02194), chosen over per-density bucketing after
profiling: a bucket ladder turns each half-iteration into ~40 small
sequential device ops, each at ~1% utilization, while one packed layout
runs the whole side as a handful of large ops.

- **Segment packing (host, vectorized):** each row's observation list is
  split into segments of exactly ``L`` slots (short rows pad their single
  segment; long rows span several segments). All device shapes are
  static; the ragged CSR never reaches the accelerator, and padding waste
  is bounded by L per nonempty row.
- **Gather + einsum normal equations (device):** gather the counter-side
  factors ``Yg = Y[cols]`` ([S, L, k]) chunk-by-chunk, form per-segment
  Gramian corrections with one einsum ([S, k, k] — MXU work), and
  scatter-add segments into per-row systems ``A`` [R, k, k], ``b`` [R, k]
  (most rows are a single segment). Add the shared Gramian (implicit
  mode) and regularization, then solve ALL rows with one batched
  Cholesky. Rows with no observations keep their previous factors.
- **Sharding:** segments are sharded over the mesh's ``data`` axis;
  factor/system rows are row-sharded and the counter-side factors
  replicated for the gather. The shared Gramian ``YᵀY`` of a row-sharded
  factor matrix is a sharded matmul whose partial products XLA
  all-reduces over ICI — the explicit Gramian all-reduce of the
  ALX/MLlib designs falls out of the sharding annotations.

Solves run in float32 (k×k, numerically delicate); gathers/einsums can run
in bfloat16 with float32 accumulation via ``compute_dtype``.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import logging
import math
import threading
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.parallel.mesh import pad_to_multiple
from predictionio_tpu.utils import compilation_cache as _cc
from predictionio_tpu.utils import device_ledger as _dl
from predictionio_tpu.utils import metrics as _metrics

logger = logging.getLogger(__name__)

# Concurrent fused-loop executions from several host threads (a grid
# evaluation's thread-parallel variants) deterministically deadlock the
# XLA CPU client on small-core boxes: threads park forever inside
# run_iters/device_get (tier-1's test_grid_evaluation_picks_best hang).
# On the CPU backend the device work serializes on the cores anyway, so
# a process-wide lock around the device loop + factor fetch costs
# nothing and removes the deadlock; accelerator backends never take it.
_CPU_DEVICE_LOOP_LOCK = threading.Lock()


def _device_loop_guard():
    import contextlib

    if jax.default_backend() == "cpu":
        return _CPU_DEVICE_LOOP_LOCK
    return contextlib.nullcontext()


@dataclasses.dataclass(frozen=True)
class ALSConfig:
    rank: int = 10
    iterations: int = 10
    reg: float = 0.01
    alpha: float = 1.0  # implicit-feedback confidence scale
    implicit_prefs: bool = False
    # MLlib<=1.3 scales reg by per-row observation count (ALS-WR); "plain"
    # uses unscaled reg.
    reg_mode: str = "weighted"
    seed: int = 0
    compute_dtype: str = "float32"  # or "bfloat16" for MXU-rate einsums
    # MAX slot width of the packed segment layout. Each solve side uses
    # the smallest power of two >= its mean observation count (min 8,
    # capped here): sparse sides would otherwise pad every row out to the
    # full width (e.g. 3 obs/user -> 40x waste at width 128), while dense
    # sides want wide segments for big einsum chunks.
    segment_length: int = 128
    # max gathered slots per device chunk (bounds the [chunk, L, k]
    # gather buffer; ~4M slots * rank 32 * bf16 = 256 MB)
    chunk_slots: int = 4_194_304
    # per-sweep convergence telemetry from the fused loop (factor-delta
    # RMS per side, written into a fixed [TELEMETRY_SLOTS, 4] output —
    # no host callback inside the jit). Two elementwise reductions over
    # the factor matrices per sweep: noise against the gather/einsum/
    # Cholesky work (bench.py gates the overhead at <2% of sweep time).
    # Off = a separate executable (the flag is a static jit arg).
    sweep_telemetry: bool = True
    # per-row solver. "exact" solves the full k x k normal equations with
    # one batched Cholesky per half-sweep; "subspace" runs the iALS++
    # blocked Gauss-Seidel update (arXiv:2110.14044): one pass over
    # rank/block_size column blocks per half-sweep, each block a batched
    # block_size x block_size solve against the residual — the [R, k, k]
    # system tensor is never materialized, so solve FLOPs and HBM traffic
    # drop by ~rank/block_size at equal per-sweep quality. Both solvers
    # target the same normal equations (same fixed point); block_size
    # must divide rank.
    solver: str = "exact"
    block_size: int = 0

    def __post_init__(self):
        if self.reg_mode not in ("weighted", "plain"):
            raise ValueError(f"reg_mode must be weighted|plain, got {self.reg_mode}")
        validate_solver(self.solver, self.block_size, self.rank)

    @property
    def telemetry_rows_per_sweep(self) -> int:
        """Telemetry rows the fused loop records per sweep: one for the
        exact solver, one PER BLOCK for the subspace solver (the
        per-block convergence curve of satellite telemetry)."""
        if self.solver == "subspace" and self.block_size:
            return self.rank // self.block_size
        return 1


def validate_solver(solver: str, block_size: int, rank: int) -> None:
    """Shared solver-param coherence check: ALSConfig and every engine's
    algorithm params call this at construction, so an incoherent
    solver/block_size pair fails at PARAM PARSE time with a clear error
    instead of surfacing as a shape error inside the jit."""
    if solver not in ("exact", "subspace"):
        raise ValueError(
            f"solver must be 'exact' or 'subspace', got {solver!r}"
        )
    if solver == "subspace":
        if not isinstance(block_size, int) or block_size <= 0:
            raise ValueError(
                "solver='subspace' requires block_size > 0 (a divisor of "
                f"rank={rank}); got block_size={block_size!r}"
            )
        if rank % block_size != 0:
            raise ValueError(
                f"block_size={block_size} must divide rank={rank} for "
                "the iALS++ blocked subspace solver (the rank splits "
                "into rank/block_size equal column blocks)"
            )


def config_train_key(config: "ALSConfig") -> tuple:
    """The training-semantics identity of a config — everything that
    changes what the fused loop COMPUTES for fixed data. The resident
    pack (ops/streaming.py) keys its device-held factor/regularizer
    state on this: a mismatch on any component (reg sweep, implicit
    flip, alpha retune, solver or block-size change) demotes the round
    to the host wire instead of warm-starting from factors trained
    under different semantics."""
    return (
        config.rank, config.reg, config.reg_mode,
        config.implicit_prefs, config.alpha,
        config.solver, config.block_size,
    )


@dataclasses.dataclass
class PackedSide:
    """Host-side fixed-width segment view of one solve side, pre-shaped
    for the chunked device loop: segment arrays are [C, Sc, L] where
    C·Sc ≥ #segments and Sc·L ≤ chunk_slots.

    There is NO per-slot validity mask: each segment's valid slots are a
    prefix, so one count per segment (``rem``) reconstructs the mask
    on-device as ``iota(L) < rem`` — L bytes/segment less host->HBM
    transfer than the uint8 mask plane rounds 1-3 shipped (≈50 MB at
    ML-20M scale through a relayed link), and one less [C, Sc, L] stream
    in the accumulation loop."""

    n_rows: int  # real (unpadded) row count
    seg_rows: np.ndarray  # [C, Sc] row id of each segment (padding -> n_rows)
    cols: np.ndarray  # [C, Sc, L] column ids (padding = 0, masked)
    vals: np.ndarray  # [C, Sc, L] ratings
    rem: np.ndarray  # [C, Sc] int32 valid slots per segment (prefix)
    counts: np.ndarray  # [n_rows] observation counts

    @property
    def n_segments(self) -> int:
        return self.seg_rows.shape[0] * self.seg_rows.shape[1]


def pack_segments(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n_rows: int,
    segment_length: int = 128,
    pad_segments_to: int = 1,
    chunk_slots: int = 4_194_304,
) -> PackedSide:
    """Pack COO observations into fixed-width row segments (vectorized).

    Each nonempty row occupies ``ceil(count / L)`` consecutive segments of
    exactly ``L`` slots; the last segment of a row is zero-padded and
    masked. Padding segments (to fill the [C, Sc] grid and make the
    segment dim divide ``pad_segments_to``, the mesh axis size) carry the
    sentinel row id ``n_rows`` so their scatter-add lands in a discarded
    system row.
    """
    L = int(segment_length)
    rows = np.asarray(rows, dtype=np.int32)
    cols = np.asarray(cols, dtype=np.int32)
    vals = np.asarray(vals, dtype=np.float32)
    order = np.argsort(rows, kind="stable")
    rows_s, cols_s, vals_s = rows[order], cols[order], vals[order]
    counts = np.bincount(rows_s, minlength=n_rows).astype(np.int32)
    g = _segment_geometry(counts, n_rows, L, pad_segments_to, chunk_slots)

    p_cols = np.zeros((g.total, L), dtype=np.int32)
    p_vals = np.zeros((g.total, L), dtype=np.float32)
    if len(rows_s):
        offset = np.arange(len(rows_s), dtype=np.int64) - g.starts[rows_s]
        flat = (g.seg_base[rows_s] + offset // L) * L + offset % L
        p_cols.reshape(-1)[flat] = cols_s
        p_vals.reshape(-1)[flat] = vals_s
    return PackedSide(
        n_rows=n_rows,
        seg_rows=g.seg_rows.reshape(g.n_chunks, g.sc),
        cols=p_cols.reshape(g.n_chunks, g.sc, L),
        vals=p_vals.reshape(g.n_chunks, g.sc, L),
        rem=g.rem.reshape(g.n_chunks, g.sc),
        counts=counts,
    )


@dataclasses.dataclass
class _SegGeometry:
    """Segment-grid geometry of one solve side, computed from per-row
    counts alone (no pass over the observations)."""

    n_rows: int
    L: int
    counts: np.ndarray  # [n_rows] int32
    starts: np.ndarray  # [n_rows + 1] int64 CSR offsets of the sorted COO
    seg_base: np.ndarray  # [n_rows + 1] int64 first segment of each row
    n_segs: int
    sc: int
    n_chunks: int
    total: int  # n_chunks * sc >= n_segs
    seg_rows: np.ndarray  # [total] row of each segment (padding -> n_rows)
    rem: np.ndarray  # [total] valid slots per segment


def _segment_geometry(
    counts: np.ndarray,
    n_rows: int,
    L: int,
    pad_segments_to: int,
    chunk_slots: int,
) -> _SegGeometry:
    starts = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    segs_per_row = -(-counts // L)  # ceil; 0 for empty rows
    seg_base = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(segs_per_row, out=seg_base[1:])
    n_segs = int(seg_base[-1])

    # chunk grid: Sc segments per chunk, Sc*L <= chunk_slots, Sc a
    # multiple of the shard count so each chunk's segment dim shards
    # evenly — and no larger than the data needs, so small inputs don't
    # pad out to the full chunk budget
    sc = max(1, int(chunk_slots) // L)
    sc = max(pad_segments_to, sc - sc % pad_segments_to)
    # Bucket the needed segment count (to a multiple of the shard pad):
    # the packed arrays' shapes feed straight into jit, and k-fold/grid
    # evaluation produces near-identical segment counts (e.g. 402/403/
    # 408) that would otherwise each pay a full XLA compile. Rounding up
    # at 4-significant-bit granularity (the granule is 2^(bitlength-4))
    # collapses them onto one executable with ≤12.5% padding — round 3
    # bucketed to full powers of two, which cost up to 2x padded slots
    # and measurably slowed the single-train benchmarks. The extra
    # segments carry the sentinel row id and are masked out. Bucketing
    # only changes sc in the single-chunk regime (sc_needed below the
    # chunk budget, min() below); budget-capped large trains (ML-20M)
    # get the same sc as before and pad at most one trailing chunk.
    per_pad = -(-max(n_segs, 1) // pad_segments_to)
    sc_needed = pad_segments_to * _bucket_count(per_pad)
    sc = min(sc, sc_needed)
    n_chunks = max(1, -(-max(n_segs, 1) // sc))
    total = n_chunks * sc

    seg_rows = np.full(total, n_rows, dtype=np.int32)
    rem = np.zeros(total, dtype=np.int32)
    if n_segs:
        seg_rows[:n_segs] = np.repeat(
            np.arange(n_rows, dtype=np.int32), segs_per_row
        )
        # valid slots per segment: full L except each row's last segment
        seg_ord = np.arange(n_segs, dtype=np.int64) - seg_base[seg_rows[:n_segs]]
        rem[:n_segs] = np.minimum(
            counts[seg_rows[:n_segs]].astype(np.int64) - seg_ord * L, L
        )
    return _SegGeometry(
        n_rows=n_rows, L=L, counts=counts, starts=starts,
        seg_base=seg_base, n_segs=n_segs, sc=sc, n_chunks=n_chunks,
        total=total, seg_rows=seg_rows, rem=rem,
    )


# --- device-side packing (single-device fast path) ---
#
# The padded segment arrays are up to ~3x the COO bytes; building them on
# HOST means shipping that inflation over the host->device link, which on
# relayed rigs runs at tens of MB/s (the dominant ML-20M phase in rounds
# 1-3: 14-80 s). Instead the COO crosses the link ONCE, losslessly
# narrowed (item ids to uint16 when they fit, half-step ratings to int8)
# and — since round 5 — WITHOUT its row-id plane: the host stable-sorts
# by user, the CSR offsets (already needed for the scatter) encode the
# row ids, and _device_pack_presorted rebuilds them in HBM with one
# cumsum pass — and half-step ratings nibble-pack two per byte.
# ML-20M wire: ~51 MB vs ~140 MB with the int32 row plane.
# This replaces the role of the reference's region-parallel HBase scan
# feeding Spark block shuffles (data/storage/hbase/HBPEvents.scala:84-90):
# the wire carries the minimal representation, the accelerator does the
# layout.


def _narrow_ids(idx: np.ndarray) -> np.ndarray:
    """Ids as the narrowest lossless wire dtype (uint16 covers catalogs
    under 64k — the item axis of every MovieLens-class dataset)."""
    return idx.astype(np.uint16) if idx.size and idx.max() < 65536 else idx


def _narrow_vals(vals: np.ndarray) -> Tuple[np.ndarray, float]:
    """(wire_array, scale): ratings on half-step scales (MovieLens 1..5
    or 0.5..5.0) travel as int8 exactly; anything else stays float32."""
    if vals.size == 0:
        return vals, 1.0
    doubled = vals * 2.0
    rounded = np.rint(doubled)
    if (
        np.abs(doubled - rounded).max() == 0.0
        and np.abs(rounded).max() <= 127
    ):
        return rounded.astype(np.int8), 0.5
    return vals, 1.0


def _nibble_packable(vw: np.ndarray) -> bool:
    """Half-step ratings in [0, 7.5] (doubled: 0..15) fit a NIBBLE each —
    two per wire byte, halving the value plane (20 MB -> 10 MB at
    ML-20M). Requires an even element count (pairing; the 4-bit COO
    length bucketing makes any non-tiny wire even) and no negatives
    (implicit-feedback dislikes keep the plain int8 tier)."""
    return (
        vw.dtype == np.int8
        and vw.size > 0
        and vw.size % 2 == 0
        and vw.min() >= 0
        and vw.max() <= 15
    )


def _pack_nibbles_host(vw: np.ndarray) -> np.ndarray:
    return (
        (vw[0::2].astype(np.uint8) & 0xF)
        | (vw[1::2].astype(np.uint8) << 4)
    )


def _unpack_nibbles_host(packed: np.ndarray) -> np.ndarray:
    """Host inverse of _pack_nibbles_host (the delta-fold path recovers
    the cached wire's exact COO instead of rescanning the store)."""
    out = np.empty(packed.size * 2, np.int8)
    out[0::2] = (packed & np.uint8(0xF)).astype(np.int8)
    out[1::2] = (packed >> np.uint8(4)).astype(np.int8)
    return out


def wire_coo(wire: "HostWire") -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Recover the exact user-major (user, item, value) COO a HostWire
    was finished from — every narrowing tier is lossless, so feeding
    this back through :func:`finish_wire` (after a dense-id relabel)
    reproduces the wire byte-for-byte. This is what lets the delta-fold
    path re-finish a grown store from the CACHED wire without touching
    the old rows in storage."""
    n = int(wire.counts_u.sum())
    u = np.repeat(
        np.arange(wire.n_users, dtype=np.int32), wire.counts_u
    )
    i = np.asarray(wire.iw[:n], dtype=np.int32)
    if wire.nibble:
        v = _unpack_nibbles_host(wire.vw)[:n].astype(np.float32)
        v *= np.float32(wire.v_scale)
    elif wire.vw.dtype == np.int8:
        v = wire.vw[:n].astype(np.float32) * np.float32(wire.v_scale)
    else:
        v = np.asarray(wire.vw[:n], dtype=np.float32)
    return u, i, v


@jax.jit
def _unpack_nibbles(packed):
    """uint8 [n/2] -> int8 [n], inverse of _pack_nibbles_host (one cheap
    elementwise pass in HBM; the wire stays half-size)."""
    lo = (packed & jnp.uint8(0xF)).astype(jnp.int8)
    hi = ((packed >> jnp.uint8(4)) & jnp.uint8(0xF)).astype(jnp.int8)
    return jnp.stack([lo, hi], axis=1).reshape(-1)


@functools.partial(jax.jit, static_argnames=("total", "L", "scale"))
def _device_pack_presorted(cols, vals, starts, seg_base, total, L, scale):
    """Pack a HOST-presorted (by row id) COO side WITHOUT the row-id
    plane on the wire: row ids rebuild on device from the CSR offsets by
    an indicator-cumsum (one memory-bound pass over [n]), then the
    scatter layout is identical to _device_scatter_pack's post-sort
    layout — but with no 20M-row device sort and, at ML-20M, ~80 MB less
    host->device traffic (the int32 row plane compresses to the CSR
    offsets already shipped for the scatter). Sentinel-padded tail
    elements get row ids past the last real row; their gathers clamp to
    the CSR edge values, so they land in masked padding segments or drop
    (mode="drop"), exactly like the sorted path. Returns the rebuilt row
    ids — the counter side's pack consumes them as its column values."""
    n = cols.shape[0]
    j = jnp.arange(n, dtype=jnp.int32)
    marks = (
        jnp.zeros((n + 1,), jnp.int32).at[starts[1:]].add(1, mode="drop")
    )
    keys = jnp.cumsum(marks[:n], dtype=jnp.int32)
    offset = j - starts[keys]
    flat = (seg_base[keys] + offset // L) * L + offset % L
    opts = dict(unique_indices=True, indices_are_sorted=True, mode="drop")
    p_cols = (
        jnp.zeros((total * L,), jnp.int32)
        .at[flat].set(cols.astype(jnp.int32), **opts)
    )
    p_vals = (
        jnp.zeros((total * L,), jnp.float32)
        .at[flat].set(vals.astype(jnp.float32) * scale, **opts)
    )
    return keys, p_cols, p_vals


@functools.partial(jax.jit, static_argnames=("total", "L", "scale"))
def _device_scatter_pack(keys, cols, vals, starts, seg_base, total, L, scale):
    """Sort the COO by ``keys`` and scatter values/cols into the padded
    [total, L] segment layout — all on device. The flat slot index of the
    j-th sorted element is derivable from the CSR offsets alone, and is
    strictly increasing, so the scatters are sorted unique-index writes.
    The stable sort makes slot assignment deterministic for a given input
    order (since round 5 the input arrives user-sorted, so within-row
    slot order differs from the host packer's insertion order by a
    permutation — same masked sums, float-rounding-level differences
    only). Sentinel-padded COO elements (row id == n_rows) sort last and
    either land in masked padding segments or drop out of bounds
    (mode="drop")."""
    ks, cs, vs = jax.lax.sort(
        (keys.astype(jnp.int32), cols.astype(jnp.int32), vals),
        num_keys=1, is_stable=True,
    )
    n = keys.shape[0]
    j = jnp.arange(n, dtype=jnp.int32)
    offset = j - starts[ks]
    flat = (seg_base[ks] + offset // L) * L + offset % L
    opts = dict(unique_indices=True, indices_are_sorted=True, mode="drop")
    p_cols = jnp.zeros((total * L,), jnp.int32).at[flat].set(cs, **opts)
    p_vals = (
        jnp.zeros((total * L,), jnp.float32)
        .at[flat].set(vs.astype(jnp.float32) * scale, **opts)
    )
    return p_cols, p_vals


# --- device kernels ---


def _accumulate_systems(
    Y: jax.Array,  # [n_cols(+pad), k] counter-side factors (replicated)
    seg_rows: jax.Array,  # [C, Sc]
    cols: jax.Array,  # [C, Sc, L]
    vals: jax.Array,  # [C, Sc, L]
    rem: jax.Array,  # [C, Sc] valid slots per segment
    alpha,
    n_sys_rows: int,
    *,
    implicit: bool,
    compute_dtype: str,
) -> Tuple[jax.Array, jax.Array]:
    """Per-row normal-equation systems A [R, k, k], b [R, k] from the
    packed segments: a fori_loop over chunks, each chunk ONE gather + two
    einsums + a scatter-add. The chunk loop bounds the [Sc, L, k] gather
    buffer; the einsums are the MXU work."""
    k = Y.shape[-1]
    L = cols.shape[-1]
    cdt = jnp.dtype(compute_dtype)
    # float32 inputs ask for full-precision MXU passes; bfloat16 trades
    # precision for MXU rate explicitly via compute_dtype
    prec = "highest" if cdt == jnp.float32 else "default"
    # The gather is ROW-RATE bound on TPU (measured ~420M rows/s either
    # dtype), so gathering pre-cast rows also skips a cast pass over the
    # [Sc, L, k] buffer; the cast of Y itself is one cheap pass.
    Yc = Y.astype(cdt)
    iota_l = jnp.arange(L, dtype=jnp.int32)
    A0 = jnp.zeros((n_sys_rows, k, k), jnp.float32)
    b0 = jnp.zeros((n_sys_rows, k), jnp.float32)

    def body(c, carry):
        A, b = carry
        rows_c = jax.lax.dynamic_index_in_dim(seg_rows, c, keepdims=False)
        cols_c = jax.lax.dynamic_index_in_dim(cols, c, keepdims=False)
        vals_c = jax.lax.dynamic_index_in_dim(vals, c, keepdims=False)
        rem_c = jax.lax.dynamic_index_in_dim(rem, c, keepdims=False)
        # per-slot validity, reconstructed from the per-segment prefix
        # count (valid slots always lead) — no [C, Sc, L] mask stream
        mask_c = (iota_l[None, :] < rem_c[:, None]).astype(jnp.float32)
        Yg = Yc[cols_c]  # [Sc, L, k] gather from HBM
        if implicit:
            # MLlib trainImplicit semantics (Hu-Koren-Volinsky):
            # confidence c = alpha·|r| (non-negative — keeps A
            # positive-definite even for dislike ratings r<0, e.g.
            # similarproduct LikeAlgorithm's -1); preference p = 1(r>0).
            # A = G + Σ c·y yᵀ ; b = Σ p·(1+c)·y, so a dislike contributes
            # confidence to A but nothing to b.
            aw = (alpha * jnp.abs(vals_c) * mask_c).astype(cdt)
            pref = (vals_c > 0).astype(jnp.float32) * mask_c
            bw = (pref * (1.0 + alpha * jnp.abs(vals_c))).astype(cdt)
        else:
            # A = Σ y yᵀ over observed ; b = Σ r·y
            aw = mask_c.astype(cdt)
            bw = (vals_c * mask_c).astype(cdt)
        A_seg = jnp.einsum(
            "slk,sl,slj->skj", Yg, aw, Yg,
            preferred_element_type=jnp.float32, precision=prec,
        )
        b_seg = jnp.einsum(
            "slk,sl->sk", Yg, bw,
            preferred_element_type=jnp.float32, precision=prec,
        )
        # most rows are one segment; multi-segment rows combine here
        return A.at[rows_c].add(A_seg), b.at[rows_c].add(b_seg)

    return jax.lax.fori_loop(0, seg_rows.shape[0], body, (A0, b0))


def _spd_solve(A: jax.Array, b: jax.Array) -> jax.Array:
    """Batched SPD solve: in-place vectorized Cholesky with the forward
    substitution fused into the factorization sweep.

    XLA's native cho_factor/cho_solve on TPU streams the [R, k, k] batch
    through HBM dozens of times — measured 502 ms per solve at
    R=138k, k=32 (v5e), which was HALF the ML-20M device loop. This
    formulation is k fused steps, each one column rescale + rank-1
    update over the whole batch (~4.5x faster measured, max rel err
    ~6e-7 vs cho_solve on the same systems). Entries outside the lower
    triangle are left stale rather than masked — each step's column
    read masks them off, saving a full [R, k, k] pass per step.

    Supports leading batch dims via vmap (the grid path vmaps it).
    """
    n = A.shape[-1]
    idx = jnp.arange(n)

    def fac_body(j, carry):
        A, y, r, dinv = carry
        col = jax.lax.dynamic_index_in_dim(A, j, axis=2, keepdims=False)
        d = jax.lax.rsqrt(
            jax.lax.dynamic_index_in_dim(col, j, axis=1, keepdims=False)
        )
        col = jnp.where(idx[None, :] >= j, col * d[:, None], 0.0)
        # forward substitution, fused: y_j = r_j / L_jj, r -= L[:, j] y_j
        yj = jax.lax.dynamic_index_in_dim(r, j, axis=1, keepdims=False) * d
        r = r - col * yj[:, None]
        y = jax.lax.dynamic_update_index_in_dim(y, yj, j, axis=1)
        dinv = jax.lax.dynamic_update_index_in_dim(dinv, d, j, axis=1)
        # rank-1 Schur update; col is zero above j, so rows/cols < j are
        # untouched and the (never-read) upper triangle absorbs the rest.
        # The scaled column lands in A[:, :, j] via the SAME fused pass (a
        # select on the column index) — a separate dynamic_update_slice
        # here materialized a full [R, k, k] data-formatting copy per
        # pass, doubling solve HBM traffic (trace: copy.80/copy.110 ~
        # equal bytes to the multiply-subtract itself).
        A = jnp.where(
            idx[None, None, :] == j,
            col[:, :, None],
            A - col[:, :, None] * col[:, None, :],
        )
        return (A, y, r, dinv)

    zeros = jnp.zeros_like(b)
    L, y, _, dinv = jax.lax.fori_loop(
        0, n, fac_body, (A, zeros, b, zeros)
    )

    def back_body(jj, x):
        j = n - 1 - jj
        lcol = jax.lax.dynamic_index_in_dim(L, j, axis=2, keepdims=False)
        # x_j = (y_j - sum_{i>j} L_ij x_i) / L_jj ; x_i is still zero for
        # i <= j and L_ij zero for i < j, so the full dot is the tail sum
        s = jnp.sum(lcol * x, axis=-1)
        xj = (
            jax.lax.dynamic_index_in_dim(y, j, axis=1, keepdims=False) - s
        ) * jax.lax.dynamic_index_in_dim(dinv, j, axis=1, keepdims=False)
        return jax.lax.dynamic_update_index_in_dim(x, xj, j, axis=1)

    return jax.lax.fori_loop(0, n, back_body, zeros)


def _solve_side(
    X_prev: jax.Array,  # [R, k] previous factors (kept for zero-obs rows)
    Y: jax.Array,  # [n_cols(+pad), k] counter-side factors
    G: jax.Array,  # [k, k] shared Gramian YᵀY (implicit) or zeros
    pack,  # (seg_rows, cols, vals, rem) pre-shaped [C, Sc(, L)]
    lam: jax.Array,  # [R] per-row regularizer (precomputed, guarded > 0)
    has_obs: jax.Array,  # [R] bool — rows with at least one observation
    alpha,
    *,
    implicit: bool,
    compute_dtype: str,
) -> jax.Array:
    k = Y.shape[-1]
    seg_rows, cols, vals, rem = pack
    A, b = _accumulate_systems(
        Y, seg_rows, cols, vals, rem, alpha, X_prev.shape[0],
        implicit=implicit, compute_dtype=compute_dtype,
    )
    if implicit:
        A = A + G[None]
    A = A + lam[:, None, None] * jnp.eye(k, dtype=jnp.float32)
    # ONE batched Cholesky over every row's k x k system
    x = _spd_solve(A, b)
    # rows with no observations keep their previous factors (MLlib only
    # materializes factors for observed ids; init survives here)
    return jnp.where(has_obs[:, None], x.astype(X_prev.dtype), X_prev)


def _solve_side_subspace(
    X_prev: jax.Array,  # [R, k] previous factors (updated in place per block)
    Y: jax.Array,  # [n_cols(+pad), k] counter-side factors
    G: jax.Array,  # [k, k] shared Gramian YᵀY (implicit) or zeros
    pack,  # (seg_rows, cols, vals, rem) pre-shaped [C, Sc(, L)]
    lam: jax.Array,  # [R] per-row regularizer
    has_obs: jax.Array,  # [R] bool
    alpha,
    *,
    implicit: bool,
    compute_dtype: str,
    block_size: int,
) -> Tuple[jax.Array, jax.Array]:
    """One iALS++ block-Gauss-Seidel pass over the side's normal
    equations (arXiv:2110.14044): for each of the rank/block_size column
    blocks B, accumulate only the [R, b, b] block system and the [R, b]
    residual right-hand side ``b_B - (M x)_B`` (M = A + G + lam·I), solve
    the batched b x b systems, and update the block columns in place —
    later blocks see earlier blocks' updates (Gauss-Seidel), which is
    what buys the faster per-sweep convergence the paper measures.

    Versus the exact solver this never materializes the [R, k, k]
    systems: per slot the einsum work drops from k² to k²/b + k·b
    (score recompute + block outer products) and the batched solve from
    k³ to k·b² — ~4x fewer solve-phase FLOPs at rank 64 / block 8, and
    [R, k, b]-not-[R, k, k] of HBM behind the Cholesky. The (A x)_B
    residual term reuses the per-slot score d = y·x, so dislikes /
    confidence weights flow through exactly as in the exact accumulator.

    Returns ``(X_new, block_deltas)`` with ``block_deltas`` the [n_blocks]
    per-block update RMS — the subspace convergence telemetry. Rows with
    no observations keep their previous factors (their block deltas are
    forced to zero before the update lands)."""
    k = Y.shape[-1]
    b = block_size
    n_blocks = k // b
    seg_rows, cols, vals, rem = pack
    L = cols.shape[-1]
    cdt = jnp.dtype(compute_dtype)
    prec = "highest" if cdt == jnp.float32 else "default"
    Yc = Y.astype(cdt)
    iota_l = jnp.arange(L, dtype=jnp.int32)
    R = X_prev.shape[0]
    eye_b = jnp.eye(b, dtype=jnp.float32)

    x = X_prev.astype(jnp.float32)
    deltas = []
    for bi in range(n_blocks):  # static unroll: block slices stay static
        s0 = bi * b
        A0 = jnp.zeros((R, b, b), jnp.float32)
        r0 = jnp.zeros((R, b), jnp.float32)

        def body(c, carry, s0=s0, x=x):
            A, rs = carry
            rows_c = jax.lax.dynamic_index_in_dim(seg_rows, c, keepdims=False)
            cols_c = jax.lax.dynamic_index_in_dim(cols, c, keepdims=False)
            vals_c = jax.lax.dynamic_index_in_dim(vals, c, keepdims=False)
            rem_c = jax.lax.dynamic_index_in_dim(rem, c, keepdims=False)
            mask_c = (iota_l[None, :] < rem_c[:, None]).astype(jnp.float32)
            Yg = Yc[cols_c]  # [Sc, L, k]
            Yb = jax.lax.slice_in_dim(Yg, s0, s0 + b, axis=2)  # [Sc, L, b]
            xg = x[rows_c].astype(cdt)  # [Sc, k] CURRENT factors
            # per-slot score d = y·x against the current (partially
            # updated) factors — the Gauss-Seidel residual ingredient
            d = jnp.einsum(
                "slk,sk->sl", Yg, xg,
                preferred_element_type=jnp.float32, precision=prec,
            )
            if implicit:
                aw = alpha * jnp.abs(vals_c) * mask_c
                pref = (vals_c > 0).astype(jnp.float32) * mask_c
                bw = pref * (1.0 + alpha * jnp.abs(vals_c))
            else:
                aw = mask_c
                bw = vals_c * mask_c
            A_seg = jnp.einsum(
                "slb,sl,slc->sbc", Yb, aw.astype(cdt), Yb,
                preferred_element_type=jnp.float32, precision=prec,
            )
            # b_B - (A x)_B in one weighted reduction: Σ (bw - aw·d)·y_B
            r_seg = jnp.einsum(
                "sl,slb->sb", (bw - aw * d).astype(cdt), Yb,
                preferred_element_type=jnp.float32, precision=prec,
            )
            return A.at[rows_c].add(A_seg), rs.at[rows_c].add(r_seg)

        A, rs = jax.lax.fori_loop(0, seg_rows.shape[0], body, (A0, r0))
        xB = jax.lax.slice_in_dim(x, s0, s0 + b, axis=1)  # [R, b]
        if implicit:
            GB = jax.lax.slice_in_dim(G, s0, s0 + b, axis=0)  # [b, k]
            A = A + jax.lax.slice_in_dim(GB, s0, s0 + b, axis=1)[None]
            rs = rs - x @ GB.T  # (G x)_B — G is symmetric
        A = A + lam[:, None, None] * eye_b
        rs = rs - lam[:, None] * xB
        delta = _spd_solve(A, rs)
        delta = jnp.where(has_obs[:, None], delta, 0.0)
        x = jax.lax.dynamic_update_slice_in_dim(x, xB + delta, s0, axis=1)
        deltas.append(jnp.sqrt(jnp.mean(jnp.square(delta))))
    return x.astype(X_prev.dtype), jnp.stack(deltas)


@jax.jit
def _gramian(Y: jax.Array) -> jax.Array:
    """YᵀY in float32. With Y row-sharded this is a reduce over the data
    axis that XLA lowers to psum over ICI."""
    Yf = Y.astype(jnp.float32)
    return jnp.einsum(
        "nk,nj->kj", Yf, Yf,
        preferred_element_type=jnp.float32, precision="highest",
    )


def _implicit_objective(
    X: jax.Array,
    Y: jax.Array,
    user_pack,
    user_lam: jax.Array,
    item_lam: jax.Array,
    alpha,
    *,
    compute_dtype: str,
) -> jax.Array:
    """The Hu-Koren-Volinsky implicit objective at the current factors:
    ``Σ_all s² + Σ_obs [c·s² − 2(1+c)·p·s + (1+c)·p²] + Σ lam·‖·‖²``
    (c = α·|r|, p = 1(r>0), s = x·y). The full-matrix term collapses via
    the Gramian trick — ⟨XᵀX, YᵀY⟩, two k×k matmuls — and the observed
    correction is one extra gather+score pass over the user-side pack
    (k·L per slot, ~1/k of a solve sweep's einsum work). Padding rows
    are zero on both sides, so the Gramians are exact over the padded
    matrices. The pack is event-level (duplicate (u,i) events are not
    merged — delta folds depend on that), so each repeat subtracts its
    cell's s² again while the all-pairs term counts it once: stores
    with repeated interactions can report negative values. The
    per-sweep trend is the convergence signal, not the absolute
    level."""
    seg_rows, cols, vals, rem = user_pack
    L = cols.shape[-1]
    cdt = jnp.dtype(compute_dtype)
    prec = "highest" if cdt == jnp.float32 else "default"
    Xc = X.astype(cdt)
    Yc = Y.astype(cdt)
    iota_l = jnp.arange(L, dtype=jnp.int32)

    def body(c, acc):
        rows_c = jax.lax.dynamic_index_in_dim(seg_rows, c, keepdims=False)
        cols_c = jax.lax.dynamic_index_in_dim(cols, c, keepdims=False)
        vals_c = jax.lax.dynamic_index_in_dim(vals, c, keepdims=False)
        rem_c = jax.lax.dynamic_index_in_dim(rem, c, keepdims=False)
        mask_c = (iota_l[None, :] < rem_c[:, None]).astype(jnp.float32)
        s = jnp.einsum(
            "slk,sk->sl", Yc[cols_c], Xc[rows_c],
            preferred_element_type=jnp.float32, precision=prec,
        )
        cw = alpha * jnp.abs(vals_c) * mask_c
        p = (vals_c > 0).astype(jnp.float32) * mask_c
        term = cw * s * s - 2.0 * (1.0 + cw) * p * s + (1.0 + cw) * p * p
        return acc + jnp.sum(term)

    obs = jax.lax.fori_loop(
        0, seg_rows.shape[0], body, jnp.float32(0.0)
    )
    all_sq = jnp.sum(_gramian(X) * _gramian(Y))
    Xf = X.astype(jnp.float32)
    Yf = Y.astype(jnp.float32)
    reg = jnp.sum(user_lam * jnp.sum(Xf * Xf, axis=-1)) + jnp.sum(
        item_lam * jnp.sum(Yf * Yf, axis=-1)
    )
    return all_sq + obs + reg


def _constrain(a: jax.Array, sharding) -> jax.Array:
    return (
        jax.lax.with_sharding_constraint(a, sharding)
        if sharding is not None
        else a
    )


# per-sweep telemetry rows the fused loop can record before the ring
# wraps (sweeps past this many stop recording — mode="drop" scatter);
# each row is [dx_rms, dy_rms, x_rms, y_rms, objective] float32. The
# subspace solver records ONE ROW PER BLOCK per sweep, so its buffer is
# allocated at TELEMETRY_SLOTS x rows_per_sweep rows (the block count is
# a jit static) — the same TELEMETRY_SLOTS sweeps fit either way, and
# sweeps x blocks rows never silently truncate into the sweep budget.
TELEMETRY_SLOTS = 64
TELEMETRY_COLS = 5


@functools.partial(
    jax.jit,
    static_argnames=(
        "implicit", "compute_dtype", "rep_sharding", "row_sharding",
        "telemetry", "solver", "block_size",
    ),
    donate_argnums=(0, 1),
)
def _run_iterations(
    X: jax.Array,
    Y: jax.Array,
    user_pack,  # (seg_rows, cols, vals, rem) each [C, Sc(, L)]
    item_pack,
    user_lam: jax.Array,  # [R_u] per-row regularizer
    item_lam: jax.Array,  # [R_i]
    user_has_obs: jax.Array,  # [R_u] bool
    item_has_obs: jax.Array,  # [R_i]
    alpha,
    n_iters: jax.Array,  # dynamic: one compile serves every chunk size
    *,
    implicit: bool,
    compute_dtype: str,
    rep_sharding,  # NamedSharding(P()) or None — replicate for gathers
    row_sharding,  # NamedSharding(P(axis)) or None
    telemetry: bool = True,
    solver: str = "exact",
    block_size: int = 0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """The whole training loop as ONE XLA program: lax.fori_loop over
    iterations, each half-iteration a chunked gather/einsum accumulation
    plus one batched solve (``solver="exact"``) or an iALS++ block
    Gauss-Seidel pass (``solver="subspace"``, see _solve_side_subspace).
    One dispatch covers all iterations — no host round trip per
    half-step, factors never leave HBM, and the replicate/shard handoffs
    become compiled all-gathers instead of per-step device_puts. The
    trip count is a runtime value so warm-up, checkpoint chunks, and
    resumes all reuse the same executable. The regularizer (with reg
    and, in weighted mode, per-row counts baked in) arrives as data, so
    sweeping reg reuses the executable too.

    With ``telemetry`` (the convergence tentpole), sweep ``i`` also
    writes [RMS(X_i - X_{i-1}), RMS(Y_i - Y_{i-1}), RMS(X_i), RMS(Y_i),
    objective] rows into a fixed [TELEMETRY_SLOTS x rows_per_sweep, 5]
    output — one row per sweep (exact) or per sweep x block (subspace,
    with per-block update RMS in the delta columns). The objective
    column carries the Hu-Koren-Volinsky implicit loss via the Gramian
    trick in implicit mode and 0 otherwise. All of it is computed IN the
    loop and fetched alongside the factors, never via a host callback
    inside the jit."""
    k = X.shape[-1]
    zeros_g = jnp.zeros((k, k), jnp.float32)
    subspace = solver == "subspace"
    nb = (k // block_size) if (subspace and block_size) else 1

    def half(X, Y, pack, lam, has_obs):
        G = _gramian(Y) if implicit else zeros_g
        Y_rep = _constrain(Y, rep_sharding)
        if subspace:
            X, block_d = _solve_side_subspace(
                X, Y_rep, G, pack, lam, has_obs, alpha,
                implicit=implicit, compute_dtype=compute_dtype,
                block_size=block_size,
            )
        else:
            X = _solve_side(
                X, Y_rep, G, pack, lam, has_obs, alpha,
                implicit=implicit, compute_dtype=compute_dtype,
            )
            block_d = None
        return _constrain(X, row_sharding), block_d

    def _rms(a):
        return jnp.sqrt(jnp.mean(jnp.square(a.astype(jnp.float32))))

    def body(i, carry):
        X, Y, tel = carry
        Xn, dxb = half(X, Y, user_pack, user_lam, user_has_obs)
        Yn, dyb = half(Y, Xn, item_pack, item_lam, item_has_obs)
        if telemetry:
            obj = (
                _implicit_objective(
                    Xn, Yn, user_pack, user_lam, item_lam, alpha,
                    compute_dtype=compute_dtype,
                )
                if implicit
                else jnp.float32(0.0)
            )
            x_rms, y_rms = _rms(Xn), _rms(Yn)
            if subspace:
                # one row per block; sweep-level deltas reassemble on
                # host as sqrt(mean(block_delta²)) — blocks are disjoint
                # column sets, so the identity is exact
                for j in range(nb):
                    row = jnp.stack([dxb[j], dyb[j], x_rms, y_rms, obj])
                    tel = tel.at[i * nb + j].set(row, mode="drop")
            else:
                row = jnp.stack(
                    [_rms(Xn - X), _rms(Yn - Y), x_rms, y_rms, obj]
                )
                tel = tel.at[i].set(row, mode="drop")
        return (Xn, Yn, tel)

    tel0 = jnp.zeros((TELEMETRY_SLOTS * nb, TELEMETRY_COLS), jnp.float32)
    return jax.lax.fori_loop(0, n_iters, body, (X, Y, tel0))


@functools.partial(
    jax.jit,
    static_argnames=(
        "implicit", "compute_dtype", "rep_sharding", "row_sharding",
    ),
    donate_argnums=(0, 1),
)
def _run_iterations_grid(
    X: jax.Array,  # [V, R_u, k] per-variant factors
    Y: jax.Array,  # [V, R_i, k]
    user_pack,  # shared across variants — only the regularizer differs
    item_pack,
    user_lam: jax.Array,  # [V, R_u]
    item_lam: jax.Array,  # [V, R_i]
    user_has_obs: jax.Array,  # [R_u]
    item_has_obs: jax.Array,  # [R_i]
    alpha,
    n_iters: jax.Array,
    *,
    implicit: bool,
    compute_dtype: str,
    rep_sharding=None,  # NamedSharding(P(None, None, None)) or None
    row_sharding=None,  # NamedSharding(P(None, axis, None)) or None
) -> Tuple[jax.Array, jax.Array]:
    """The reg-grid training loop as ONE vmapped XLA program: V variants
    that share data/rank/iterations and differ only in the regularizer
    train together, so one dispatch covers the whole grid axis and the
    per-variant einsums batch onto the MXU instead of running as V
    serial programs (the reference's grid is host-thread `.par`,
    MetricEvaluator.scala:221-230 — there is no device-side analog).

    On a mesh, rows/segments shard over the mesh axis exactly as the
    single-variant program does (the variant axis is unsharded — every
    device trains all variants over its row shard); there the fori loop
    sits OUTSIDE the vmap so the replicate/row-shard constraints apply
    to the whole [V, R, k] batch each half-iteration. Single-device
    grids keep the r3 vmap-outside structure, which tracks serial
    train_als runs most closely — equivalence is float-level (~1e-5
    factor noise from differing XLA fusion), not bit-exact, the same
    nondeterminism class as the reference's `.par` thread-pool grid."""

    if rep_sharding is None and row_sharding is None:

        def single(X1, Y1, ul, il):
            k = X1.shape[-1]
            zeros_g = jnp.zeros((k, k), jnp.float32)

            def half1(Xs, Ys, pack, lam, has_obs):
                G = _gramian(Ys) if implicit else zeros_g
                return _solve_side(
                    Xs, Ys, G, pack, lam, has_obs, alpha,
                    implicit=implicit, compute_dtype=compute_dtype,
                )

            def body1(_, carry):
                Xc, Yc = carry
                Xc = half1(Xc, Yc, user_pack, ul, user_has_obs)
                Yc = half1(Yc, Xc, item_pack, il, item_has_obs)
                return (Xc, Yc)

            return jax.lax.fori_loop(0, n_iters, body1, (X1, Y1))

        return jax.vmap(single)(X, Y, user_lam, item_lam)

    def half(X, Y, pack, lam, has_obs):
        if implicit:
            G = jax.vmap(_gramian)(Y)
        else:
            k = X.shape[-1]
            G = jnp.zeros((X.shape[0], k, k), jnp.float32)
        Y_rep = _constrain(Y, rep_sharding)
        X = jax.vmap(
            lambda Xv, Yv, Gv, lamv: _solve_side(
                Xv, Yv, Gv, pack, lamv, has_obs, alpha,
                implicit=implicit, compute_dtype=compute_dtype,
            )
        )(X, Y_rep, G, lam)
        return _constrain(X, row_sharding)

    def body(_, carry):
        Xc, Yc = carry
        Xc = half(Xc, Yc, user_pack, user_lam, user_has_obs)
        Yc = half(Yc, Xc, item_pack, item_lam, item_has_obs)
        return (Xc, Yc)

    return jax.lax.fori_loop(0, n_iters, body, (X, Y))


def train_als_grid(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    ratings: np.ndarray,
    n_users: int,
    n_items: int,
    config: "ALSConfig",
    regs: Sequence[float],
    mesh: Optional[Mesh] = None,
    axis: str = "data",
) -> List["ALSModelArrays"]:
    """Train ``len(regs)`` regularizer variants of one ALS configuration
    in a single batched device program (everything but ``config.reg`` is
    shared: data is packed once, initial factors are identical, and the
    iteration loop is vmapped over the reg axis).

    Returns one ALSModelArrays per reg, in order — numerically matching
    ``train_als`` with ``config.reg = regs[i]`` run one at a time. On a
    multi-device mesh (round-4 upgrade; rounds 1-3 fell back to serial
    per-variant training there) rows/segments shard over ``axis`` with
    the variant axis unsharded, so the whole grid still runs as ONE
    device program with the same collective pattern as train_als.
    """
    if config.solver != "exact":
        raise ValueError(
            "train_als_grid supports solver='exact' only (the vmapped "
            "grid program has no subspace variant); train subspace "
            "configs one at a time via train_als"
        )
    if mesh is not None and mesh.size == 1:
        mesh = None
    k = config.rank
    n_variants = len(regs)
    if n_variants == 0:
        return []
    n_shards = mesh.shape[axis] if mesh is not None else 1

    user_side = pack_segments(
        user_idx, item_idx, ratings, n_users,
        auto_segment_length(user_idx, n_users, config.segment_length),
        n_shards, config.chunk_slots,
    )
    item_side = pack_segments(
        item_idx, user_idx, ratings, n_items,
        auto_segment_length(item_idx, n_items, config.segment_length),
        n_shards, config.chunk_slots,
    )
    logger.info(
        "ALS grid: %d reg variants x (%d users, %d items, %d ratings, "
        "rank %d) in one vmapped program%s",
        n_variants, n_users, n_items, len(ratings), k,
        f" over a {n_shards}-way mesh" if mesh is not None else "",
    )

    rng = np.random.default_rng(config.seed)
    # +1 sentinel row, bucketed (_bucket_count) so near-identical
    # cardinalities share one executable, padded so the row dim shards
    # evenly over the mesh
    r_u = pad_to_multiple(_bucket_count(n_users + 1), n_shards)
    r_i = pad_to_multiple(_bucket_count(n_items + 1), n_shards)
    Y0 = np.zeros((r_i, k), np.float32)
    Y0[:n_items] = np.abs(rng.standard_normal((n_items, k))) / math.sqrt(k)

    weighted = config.reg_mode == "weighted"

    def lam_grid(side: PackedSide, n_sys_rows: int) -> np.ndarray:
        counts = np.zeros(n_sys_rows, np.float32)
        counts[: side.n_rows] = side.counts
        out = np.empty((n_variants, n_sys_rows), np.float32)
        for v, reg in enumerate(regs):
            lam = reg * counts if weighted else np.full_like(counts, reg)
            out[v] = np.maximum(lam, 1e-8)
        return out

    def obs(side: PackedSide, n_sys_rows: int) -> np.ndarray:
        counts = np.zeros(n_sys_rows, np.float32)
        counts[: side.n_rows] = side.counts
        return counts > 0

    vrow = P(None, axis, None) if mesh is not None else P()
    vlam = P(None, axis) if mesh is not None else P()
    seg2 = P(None, axis) if mesh is not None else P()
    seg3 = P(None, axis, None) if mesh is not None else P()
    row1 = P(axis) if mesh is not None else P()
    pack = lambda side: (
        _place(mesh, side.seg_rows, seg2),
        _place(mesh, side.cols, seg3),
        _place(mesh, side.vals, seg3),
        _place(mesh, side.rem, seg2),
    )
    X = _place(mesh, np.zeros((n_variants, r_u, k), np.float32), vrow)
    Y = _place(
        mesh, np.broadcast_to(Y0, (n_variants, r_i, k)).copy(), vrow
    )
    X, Y = _run_iterations_grid(
        X, Y, pack(user_side), pack(item_side),
        _place(mesh, lam_grid(user_side, r_u), vlam),
        _place(mesh, lam_grid(item_side, r_i), vlam),
        _place(mesh, obs(user_side, r_u), row1),
        _place(mesh, obs(item_side, r_i), row1),
        config.alpha, jnp.int32(config.iterations),
        implicit=config.implicit_prefs,
        compute_dtype=config.compute_dtype,
        rep_sharding=(
            NamedSharding(mesh, P(None, None, None))
            if mesh is not None else None
        ),
        row_sharding=(
            NamedSharding(mesh, vrow) if mesh is not None else None
        ),
    )
    if getattr(X, "is_fully_addressable", True) and getattr(
        Y, "is_fully_addressable", True
    ):
        # one device_get for both factor stacks (each separate fetch is a
        # full round trip on relayed rigs — at k-fold scale that was a
        # fifth of each grid call)
        X_host, Y_host = (np.asarray(a) for a in jax.device_get((X, Y)))
    else:
        X_host, Y_host = _fetch_global(X), _fetch_global(Y)
    return [
        ALSModelArrays(X_host[v, :n_users], Y_host[v, :n_items])
        for v in range(n_variants)
    ]


def _place(mesh: Optional[Mesh], arr, spec):
    if mesh is None:
        return jnp.asarray(arr)
    return jax.device_put(arr, NamedSharding(mesh, spec))


def _bucket_count(n: int) -> int:
    """Round a count up at 4-significant-bit granularity (≤12.5% padding
    worst-case, just above a power of two; ~6% typical).

    Every jit-visible dimension derived from data cardinalities buckets
    through this so near-identical inputs share one compiled executable:
    segment grids already did (see _segment_geometry); round 5 extends it
    to the system-ROW dimension, because a retrain after new users arrive
    — or the store-scan path seeing 138,432 distinct users where the
    direct path passed 138,493 — otherwise recompiles the whole iteration
    program over a 0.04% shape change (a multi-second XLA pause that
    showed up as the round-4 store→train seam)."""
    n = int(n)
    granule = 1 << max(0, n.bit_length() - 4)
    return -(-n // granule) * granule


def auto_segment_length(
    idx: Optional[np.ndarray], n_rows: int, cap: int,
    counts: Optional[np.ndarray] = None,
) -> int:
    """Smallest power of two >= the side's mean observation count, within
    [min(8, cap), cap] — shared by train_als and train_als_grid so the
    two paths always pack identically (see ALSConfig.segment_length).
    Pass precomputed per-row ``counts`` to skip the bincount pass;
    ``idx`` may then be None (the streaming packer never materializes a
    row-id plane)."""
    floor = min(8, cap)  # honor caps below 8
    if counts is None:
        counts = np.bincount(idx, minlength=n_rows)
    nonempty = int((counts > 0).sum())
    if nonempty == 0:
        return floor
    mean = (
        len(idx) if idx is not None else int(counts.sum())
    ) / nonempty
    L = floor
    while L < cap and L < mean:
        L *= 2
    return L


def _fence(tree) -> None:
    """Wait for the computation producing ``tree`` WITHOUT fetching it:
    device_get of a 1-element slice of each leaf. The slice executes
    after its producer, and fetching its single element round-trips real
    data (so the relayed-backend early-return caveat of
    block_until_ready does not apply) while moving 4 bytes instead of
    the array — fetching the ML-20M factor matrices (21 MB) through a
    ~15 MB/s relay would otherwise bill ~1.5 s of link time to the
    device-loop phase. Costs one tiny cached executable per leaf shape;
    multi-process-sharded leaves fall back to block_until_ready."""
    for a in jax.tree_util.tree_leaves(tree):
        if getattr(a, "is_fully_addressable", True):
            jax.device_get(jnp.ravel(a)[:1])
        else:
            jax.block_until_ready(a)


def _sync_fetch(tree) -> None:
    """Force device work to completion for phase timing: on relayed
    backends ``block_until_ready`` can return before execution finishes,
    so fetch results through the real transfer path. Callers pass SMALL
    arrays only — a scalar-index fence would jit a fresh tiny executable
    per shape, which costs seconds through a relayed backend.

    Arrays sharded across processes can't be fetched (device_get raises
    on non-addressable devices); they fence with block_until_ready —
    multi-host runs aren't relayed, so the early-return caveat above
    doesn't apply there."""
    for a in jax.tree_util.tree_leaves(tree):
        if getattr(a, "is_fully_addressable", True):
            jax.device_get(a)
        else:
            jax.block_until_ready(a)


@dataclasses.dataclass
class ALSModelArrays:
    """Trained factors (host-resident numpy for persistence; see
    models/recommendation for the serving wrapper)."""

    user_factors: np.ndarray  # [n_users, k]
    item_factors: np.ndarray  # [n_items, k]


# --- host wire: the presorted, narrowed COO + geometry ---
#
# Everything the single-device pack path ships to the accelerator, as one
# value: the streaming ingest pipeline (ops/streaming.py) builds it
# incrementally while the store scan is still running, the pack-artifact
# cache stores it so a repeat train skips scan+pack entirely, and
# train_als builds it monolithically. All three enter training through
# train_from_wire, so the device program is identical regardless of how
# the wire was produced.


def aux_pad(arr: np.ndarray) -> np.ndarray:
    """Bucket a CSR-offset array's length (indexed only by row ids
    <= n_rows, so edge-padding is inert) — keeps the pack executable
    shared across near-identical cardinalities, matching the row-dim
    bucketing of the iteration program."""
    out = np.full(_bucket_count(len(arr)), arr[-1], np.int32)
    out[: len(arr)] = arr
    return out


@dataclasses.dataclass
class HostWire:
    """Presorted (by user), narrowed COO wire plus segment geometry —
    the minimal host representation of one training input."""

    n_users: int
    n_items: int
    L_u: int
    L_i: int
    geo_u: _SegGeometry
    geo_i: _SegGeometry
    iw: np.ndarray  # item ids, user-sorted, sentinel-padded, narrowed
    vw: np.ndarray  # values (nibble-packed uint8, int8, or float32)
    nibble: bool
    v_scale: float
    aux: dict  # su/bu/si/bi int32 CSR offsets + segment bases (aux_pad'd)
    counts_u: np.ndarray  # [n_users] int32 observation counts
    counts_i: np.ndarray  # [n_items]
    # a STRIPPED wire kept only its geometry/metadata: the COO planes
    # (iw/vw) and aux offsets live on device under a ResidentPack
    # (ops/streaming.py) and must be restored before any host use
    stripped: bool = False

    @property
    def wire_mb(self) -> float:
        return round(
            (
                self.iw.nbytes
                + self.vw.nbytes
                + sum(int(a.nbytes) for a in self.aux.values())
            )
            / 2**20,
            1,
        )

    @property
    def padded_slots(self) -> int:
        return self.geo_u.total * self.L_u + self.geo_i.total * self.L_i

    def identity_bytes(self) -> bytes:
        """Data-identity material for the checkpoint fingerprint."""
        return self.iw.tobytes() + self.vw.tobytes()


def build_host_wire(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    ratings: np.ndarray,
    n_users: int,
    n_items: int,
    config: ALSConfig,
    counts_u: Optional[np.ndarray] = None,
    counts_i: Optional[np.ndarray] = None,
) -> HostWire:
    """Monolithic wire build from a COO batch: the host stable-sorts by
    user (the CSR offsets then encode row ids on device), narrows item
    ids and ratings to their minimal lossless wire dtypes, and
    nibble-packs half-step ratings two per byte."""
    user_idx = np.asarray(user_idx, np.int32)
    item_idx = np.asarray(item_idx, np.int32)
    ratings_f = np.asarray(ratings, np.float32)
    if counts_u is None:
        counts_u = np.bincount(user_idx, minlength=n_users).astype(np.int32)
    if counts_i is None:
        counts_i = np.bincount(item_idx, minlength=n_items).astype(np.int32)
    L_u = auto_segment_length(
        user_idx, n_users, config.segment_length, counts=counts_u
    )
    L_i = auto_segment_length(
        item_idx, n_items, config.segment_length, counts=counts_i
    )
    geo_u = _segment_geometry(counts_u, n_users, L_u, 1, config.chunk_slots)
    geo_i = _segment_geometry(counts_i, n_items, L_i, 1, config.chunk_slots)
    n = len(ratings_f)
    order = np.argsort(user_idx, kind="stable")
    # bucket the COO length (4 significant bits) so k-fold/grid runs
    # with near-identical rating counts share one pack executable;
    # padding elements carry the sentinel row id on BOTH sides and
    # either land in masked padding segments or drop out of bounds
    pad = (_bucket_count(n) - n) if n else 1
    iw = np.concatenate([item_idx[order], np.full(pad, n_items, np.int32)])
    vw = np.concatenate([ratings_f[order], np.zeros(pad, np.float32)])
    return finish_wire(
        iw, vw, n_users, n_items, L_u, L_i, geo_u, geo_i,
        counts_u, counts_i,
    )


def finish_wire(
    iw: np.ndarray,
    vw: np.ndarray,
    n_users: int,
    n_items: int,
    L_u: int,
    L_i: int,
    geo_u: _SegGeometry,
    geo_i: _SegGeometry,
    counts_u: np.ndarray,
    counts_i: np.ndarray,
) -> HostWire:
    """Shared tail of the monolithic and streaming packers: narrow a
    user-sorted, sentinel-padded (to the bucketed COO length) item/value
    COO to its minimal wire dtypes and assemble the :class:`HostWire` —
    both producers hand identical inputs here, so the wires (and the
    device programs consuming them) are byte-identical."""
    iw = _narrow_ids(iw)
    vw, v_scale = _narrow_vals(vw)
    nibble = _nibble_packable(vw)
    if nibble:
        vw = _pack_nibbles_host(vw)
    aux = {
        "su": aux_pad(geo_u.starts.astype(np.int32)),
        "bu": aux_pad(geo_u.seg_base.astype(np.int32)),
        "si": aux_pad(geo_i.starts.astype(np.int32)),
        "bi": aux_pad(geo_i.seg_base.astype(np.int32)),
    }
    return HostWire(
        n_users=n_users, n_items=n_items, L_u=L_u, L_i=L_i,
        geo_u=geo_u, geo_i=geo_i, iw=iw, vw=vw, nibble=nibble,
        v_scale=v_scale, aux=aux, counts_u=counts_u, counts_i=counts_i,
    )


def _padded_rows(n: int, n_shards: int) -> int:
    # +1 sentinel row for segment padding, bucketed so near-identical
    # cardinalities share one executable (see _bucket_count), rounded
    # up so the row dim shards evenly over the mesh
    return pad_to_multiple(_bucket_count(n + 1), n_shards)


def _factor_init_host(
    n_users: int, n_items: int, config: ALSConfig, n_shards: int
) -> Tuple[np.ndarray, np.ndarray]:
    """MLlib-style init: nonnegative scaled normals on the item side;
    sentinel/padding rows zero."""
    k = config.rank
    rng = np.random.default_rng(config.seed)
    X0 = np.zeros((_padded_rows(n_users, n_shards), k), np.float32)
    Y0 = np.zeros((_padded_rows(n_items, n_shards), k), np.float32)
    Y0[:n_items] = np.abs(rng.standard_normal((n_items, k))) / math.sqrt(k)
    return X0, Y0


def _lam_obs_host(
    counts: np.ndarray, n_real: int, n_sys_rows: int, config: ALSConfig
) -> Tuple[np.ndarray, np.ndarray]:
    padded = np.zeros(n_sys_rows, np.float32)
    padded[:n_real] = counts
    weighted = config.reg_mode == "weighted"
    lam = config.reg * padded if weighted else np.full_like(padded, config.reg)
    # guard zero-count/padding rows against singular systems (their
    # solutions are discarded by the has_obs select anyway)
    return np.maximum(lam, 1e-8).astype(np.float32), padded > 0


# geometries whose iteration executable this process already warmed up
# (under _CPU_DEVICE_LOOP_LOCK's module; guarded by its own lock). The
# continuous-training loop re-enters start_compile_async every round
# with bucket-stable shapes — re-running the zero-filled warm-up
# execution would serialize behind the device-loop guard and burn a
# core for nothing. If the jit cache was dropped anyway, training just
# compiles inline (timing-accounted, never wrong).
_WARMED_GEOMETRIES: set = set()
_WARMED_LOCK = threading.Lock()


def start_compile_async(
    n_users: int,
    n_items: int,
    geo_u: _SegGeometry,
    geo_i: _SegGeometry,
    L_u: int,
    L_i: int,
    config: ALSConfig,
):
    """Compile the single-device iteration executable for these shapes on
    a BACKGROUND thread, so XLA compile hides under scan/pack/transfer
    (the streaming pipeline calls this the moment bucket geometry is
    known). The warm-up is a zero-iteration run on zero-filled arrays of
    the exact shapes/dtypes the real call uses, so the jit cache (and the
    persistent compilation cache) is hot when training dispatches; a
    geometry this process already warmed skips the whole thing.

    Returns ``wait() -> dict`` with ``busy_s`` (and ``error`` if the
    warm-up failed — best-effort; training then compiles inline)."""
    import threading
    import time as _time

    geo_key = (
        _padded_rows(n_users, 1), _padded_rows(n_items, 1),
        geo_u.n_chunks, geo_u.sc, L_u, geo_i.n_chunks, geo_i.sc, L_i,
        config.rank, config.implicit_prefs, config.compute_dtype,
        config.sweep_telemetry, config.solver, config.block_size,
    )
    with _WARMED_LOCK:
        warmed = geo_key in _WARMED_GEOMETRIES
    if warmed:
        # geometry-bucket hit: the warm-up skip the continuous loop
        # relies on every round (accounted so /metrics can show the
        # AOT cache doing its job)
        _record_compile("cached")
        return lambda: {"busy_s": 0.0}

    rec: dict = {}

    def work() -> None:
        t0 = _time.perf_counter()
        try:
            k = config.rank
            r_u = _padded_rows(n_users, 1)
            r_i = _padded_rows(n_items, 1)

            def zpack(geo: _SegGeometry, L: int):
                return (
                    jnp.zeros((geo.n_chunks, geo.sc), jnp.int32),
                    jnp.zeros((geo.n_chunks, geo.sc, L), jnp.int32),
                    jnp.zeros((geo.n_chunks, geo.sc, L), jnp.float32),
                    jnp.zeros((geo.n_chunks, geo.sc), jnp.int32),
                )

            # the warm-up EXECUTES (zero iterations) — on the CPU
            # backend it must serialize with any in-flight device loop,
            # or the concurrent-execution deadlock the guard exists for
            # can recur through this background thread
            with _device_loop_guard():
                out = _run_iterations(
                    jnp.zeros((r_u, k), jnp.float32),
                    jnp.zeros((r_i, k), jnp.float32),
                    zpack(geo_u, L_u), zpack(geo_i, L_i),
                    jnp.zeros((r_u,), jnp.float32),
                    jnp.zeros((r_i,), jnp.float32),
                    jnp.zeros((r_u,), bool), jnp.zeros((r_i,), bool),
                    config.alpha, jnp.int32(0),
                    implicit=config.implicit_prefs,
                    compute_dtype=config.compute_dtype,
                    rep_sharding=None, row_sharding=None,
                    telemetry=config.sweep_telemetry,
                    solver=config.solver, block_size=config.block_size,
                )
                _fence(out)
            with _WARMED_LOCK:
                _WARMED_GEOMETRIES.add(geo_key)
        except Exception as e:  # pragma: no cover - defensive
            rec["error"] = repr(e)
        rec["busy_s"] = _time.perf_counter() - t0
        _record_compile(
            "error" if "error" in rec else "warmed", rec["busy_s"]
        )

    th = threading.Thread(target=work, daemon=True, name="als-warm-compile")
    th.start()

    def wait() -> dict:
        th.join()
        return rec

    return wait


def init_factor_state_single(
    counts_u: np.ndarray,
    counts_i: np.ndarray,
    n_users: int,
    n_items: int,
    config: ALSConfig,
    warm: Optional[Tuple[np.ndarray, np.ndarray]] = None,
) -> tuple:
    """Place the single-device factor/regularizer state: X as DEVICE
    zeros (its [r_u, k] buffer never crosses the host→device link — at
    ML-20M that is ~17 MB of zeros the wire no longer carries), Y0 and
    the small lam/has_obs vectors shipped from host.

    ``warm`` — ``([n_users, k], [n_items, k])`` host factor seeds (the
    delta-training warm start: previous model rows carried over, new
    rows already given a fresh init by the caller). A few ALS sweeps
    from a warm seed recover full quality after small data changes (the
    ALX / GPU-MF warm-start observation, PAPERS.md), which is what makes
    a reduced sweep budget safe."""
    k = config.rank
    if warm is not None:
        Xw, Yw = warm
        if Xw.shape != (n_users, k) or Yw.shape != (n_items, k):
            raise ValueError(
                f"warm factor shapes {Xw.shape}/{Yw.shape} do not match "
                f"({n_users}, {k})/({n_items}, {k})"
            )
        X0 = np.zeros((_padded_rows(n_users, 1), k), np.float32)
        X0[:n_users] = Xw
        Y0 = np.zeros((_padded_rows(n_items, 1), k), np.float32)
        Y0[:n_items] = Yw
        # these device arrays enter the DONATED X/Y slots of the fused
        # loop; place them as device-owned copies (jnp.array copies,
        # jnp.asarray may zero-copy alias page-aligned host memory on
        # the CPU backend — donating an alias hands XLA a buffer the
        # caller's numpy still points into)
        X = jnp.array(X0)
        Y = jnp.array(Y0)
        user_lam_h, user_obs_h = _lam_obs_host(
            counts_u, n_users, X.shape[0], config
        )
        item_lam_h, item_obs_h = _lam_obs_host(
            counts_i, n_items, Y.shape[0], config
        )
        return (
            X, Y,
            jnp.asarray(user_lam_h), jnp.asarray(item_lam_h),
            jnp.asarray(user_obs_h), jnp.asarray(item_obs_h),
        )
    _, Y0 = _factor_init_host(n_users, n_items, config, 1)
    X = jnp.zeros((_padded_rows(n_users, 1), k), jnp.float32)
    Y = jnp.array(Y0)  # device-owned copy: Y is DONATED (see warm note)
    user_lam_h, user_obs_h = _lam_obs_host(counts_u, n_users, X.shape[0], config)
    item_lam_h, item_obs_h = _lam_obs_host(counts_i, n_items, Y.shape[0], config)
    return (
        X, Y,
        jnp.asarray(user_lam_h), jnp.asarray(item_lam_h),
        jnp.asarray(user_obs_h), jnp.asarray(item_obs_h),
    )


def device_pack_from_wire(
    wire: HostWire,
    device_wire: Optional[tuple] = None,  # (i_dev, v_dev, aux_dev) pre-shipped
    timings: Optional[dict] = None,
    geo_dev: Optional[tuple] = None,  # resident (sr_u, rem_u, sr_i, rem_i)
) -> Tuple[tuple, tuple]:
    """Transfer the wire (unless pre-shipped) and build the padded
    segment layout in HBM. Returns (user_pack, item_pack) ready for
    :func:`_train_packed`.

    ``geo_dev`` — device-resident ``(seg_rows_u, rem_u, seg_rows_i,
    rem_i)`` flat int32 arrays (the ResidentPack's copies): when given,
    the per-call ``jnp.asarray`` upload of the host geometry arrays is
    skipped — on a resident scatter round nothing store-sized crosses
    the link."""
    import time as _time

    t_phase = _time.perf_counter()
    if device_wire is None:
        i_dev = jax.device_put(wire.iw)
        v_wire_dev = jax.device_put(wire.vw)
        v_dev = _unpack_nibbles(v_wire_dev) if wire.nibble else v_wire_dev
        aux = jax.device_put(wire.aux)
        if timings is not None:
            # aux was enqueued last; fetching it (small) fences the
            # serialized transfer queue behind the COO arrays
            _sync_fetch(aux)
            timings["device_put_s"] = _time.perf_counter() - t_phase
    else:
        i_dev, v_dev, aux = device_wire
    if timings is not None:
        timings["wire_mb"] = wire.wire_mb
    t_phase = _time.perf_counter()
    u_keys, pcu, pvu = _device_pack_presorted(
        i_dev, v_dev, aux["su"], aux["bu"],
        total=wire.geo_u.total, L=wire.L_u, scale=wire.v_scale,
    )
    pci, pvi = _device_scatter_pack(
        i_dev, u_keys, v_dev, aux["si"], aux["bi"],
        total=wire.geo_i.total, L=wire.L_i, scale=wire.v_scale,
    )
    if timings is not None:
        # dispatch is async; this records the (cached-after-first)
        # pack-executable compile time, not the scatter itself
        timings["device_pack_dispatch_s"] = _time.perf_counter() - t_phase

    def geo_pack(geo: _SegGeometry, pc, pv, sr_dev=None, rem_dev=None):
        return (
            (
                sr_dev.reshape(geo.n_chunks, geo.sc)
                if sr_dev is not None
                else jnp.asarray(geo.seg_rows.reshape(geo.n_chunks, geo.sc))
            ),
            pc.reshape(geo.n_chunks, geo.sc, geo.L),
            pv.reshape(geo.n_chunks, geo.sc, geo.L),
            (
                rem_dev.reshape(geo.n_chunks, geo.sc)
                if rem_dev is not None
                else jnp.asarray(geo.rem.reshape(geo.n_chunks, geo.sc))
            ),
        )

    sr_u = rem_u = sr_i = rem_i = None
    if geo_dev is not None:
        sr_u, rem_u, sr_i, rem_i = geo_dev
    return (
        geo_pack(wire.geo_u, pcu, pvu, sr_u, rem_u),
        geo_pack(wire.geo_i, pci, pvi, sr_i, rem_i),
    )


def train_from_wire(
    wire: HostWire,
    config: ALSConfig,
    *,
    device_wire: Optional[tuple] = None,  # (i_dev, v_dev, aux_dev) pre-shipped
    timings: Optional[dict] = None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 5,
    profile_dir: Optional[str] = None,
    compile_wait=None,  # callable from start_compile_async, or None
    factor_state: Optional[tuple] = None,  # pre-placed (X, Y, lam/obs x4)
    warm_start: Optional[ALSModelArrays] = None,
    _fp_material=None,
    geo_dev: Optional[tuple] = None,  # resident geometry device arrays
    factor_slots_out: Optional[dict] = None,  # receives final device X/Y
) -> ALSModelArrays:
    """Train from a :class:`HostWire` (single-device device-pack path).

    ``device_wire``/``factor_state``/``compile_wait`` let the streaming
    pipeline hand in work it already overlapped with the store scan;
    left as None, this performs the same transfer → device-pack →
    compile → loop sequence train_als always did. ``geo_dev`` passes
    resident segment-geometry device arrays straight through to
    :func:`device_pack_from_wire`; ``factor_slots_out`` (a dict)
    receives the fused loop's FINAL device-resident factor arrays under
    ``"X"``/``"Y"`` — the donated slots round-trip back to the caller
    (the ResidentPack keeps them for the next round) instead of being
    dropped after the host fetch.

    ``warm_start`` seeds the factor state from a previous model whose
    rows are ALREADY aligned to this wire's dense id spaces (shapes must
    be exactly [n_users, k]/[n_items, k] — callers relabel old rows and
    fresh-init new ones; see ops/streaming's delta fold). Combined with
    a reduced ``config.iterations`` this is the delta-retrain budget:
    cost proportional to the data change, not the store size."""
    if factor_state is None:
        # factor/lam/obs placement first: their (small) transfers enqueue
        # ahead of the wire, so the device_put fence attributes them too
        factor_state = init_factor_state_single(
            wire.counts_u, wire.counts_i, wire.n_users, wire.n_items,
            config,
            warm=(
                None
                if warm_start is None
                else (
                    np.asarray(warm_start.user_factors, np.float32),
                    np.asarray(warm_start.item_factors, np.float32),
                )
            ),
        )
    user_pack, item_pack = device_pack_from_wire(
        wire, device_wire=device_wire, timings=timings, geo_dev=geo_dev
    )
    if timings is not None:
        timings["padded_slots"] = wire.padded_slots
    # geometry-bucket padding waste: each rating occupies one slot on
    # each side's segment grid; everything else is padding the bucketed
    # executables bought (pio_padding_waste_ratio{site="als_pack"})
    slots = wire.padded_slots
    if slots:
        nnz = int(wire.counts_u.sum())
        _metrics.get_registry().gauge(
            "pio_padding_waste_ratio",
            "Fraction of a padded dimension that is padding (0 = no "
            "waste): serving batch rows, top-k ladder width, ALS "
            "geometry-bucket slots — the compile-sharing cost the "
            "capacity planning reads",
            labels=("site",),
        ).labels(site="als_pack").set(
            max(0.0, (slots - 2 * nnz) / slots)
        )
    return _train_packed(
        user_pack, item_pack, *factor_state,
        config=config, mesh=None, axis="data",
        n_users=wire.n_users, n_items=wire.n_items,
        checkpoint_dir=checkpoint_dir, checkpoint_every=checkpoint_every,
        timings=timings, profile_dir=profile_dir,
        fp_material=(
            _fp_material if _fp_material is not None else wire.identity_bytes
        ),
        compile_wait=compile_wait,
        factor_slots_out=factor_slots_out,
    )


def train_als(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    ratings: np.ndarray,
    n_users: int,
    n_items: int,
    config: ALSConfig = ALSConfig(),
    mesh: Optional[Mesh] = None,
    axis: str = "data",
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 5,
    timings: Optional[dict] = None,
    profile_dir: Optional[str] = None,
) -> ALSModelArrays:
    """Train ALS factors from COO ratings.

    With a mesh, packed segments and factor rows are sharded over
    ``axis`` and counter-side factors replicated; each half-iteration's
    Gramian + factor handoff generates the all-reduce/all-gather pattern
    over ICI.

    With ``checkpoint_dir``, factor state saves every ``checkpoint_every``
    iterations and training resumes from the latest step after an
    interruption (mid-training checkpoint/resume — absent in the
    reference, SURVEY.md §5).

    ``timings``, if given, receives a phase breakdown: ``pack_s`` (host
    geometry/packing), ``device_put_s`` (host->device transfer —
    single-device runs ship only the narrowed COO, ``wire_mb``; the
    padded layout is built in HBM by _device_scatter_pack),
    ``compile_s`` (a zero-iteration run that builds the executable
    before the timed loop — the trip count is dynamic, so the real run
    reuses it), ``device_loop_s`` (accumulated across checkpoint chunks
    when checkpointing), and ``padded_slots`` (total segment-grid slots
    both sides, the denominator for hardware-busyness numbers). At
    ML-20M scale host prep and the transfer are distinct from the
    on-device solve loop, and MFU must be computed against the latter.
    """
    import time as _time

    n_shards = mesh.shape[axis] if mesh is not None else 1

    t_phase = _time.perf_counter()
    user_idx = np.asarray(user_idx, np.int32)
    item_idx = np.asarray(item_idx, np.int32)
    ratings_f = np.asarray(ratings, np.float32)

    def fp_material() -> bytes:
        return user_idx.tobytes() + item_idx.tobytes() + ratings_f.tobytes()

    if mesh is None:
        # Device-side packing: the COO crosses the link once WITHOUT its
        # row-id plane — the host stable-sorts by user (radix, ~1 s at
        # 20M), so user ids rebuild on device from the CSR offsets
        # (_device_pack_presorted) and only the narrowed item ids +
        # ratings (nibble-packed when half-step) travel. At ML-20M that is
        # ~51 MB on the wire instead
        # of ~140 MB, and ONE device sort instead of two (the item side
        # still lax.sorts by item key, consuming the rebuilt user ids).
        wire = build_host_wire(
            user_idx, item_idx, ratings_f, n_users, n_items, config
        )
        logger.info(
            "ALS: %d users (%d segments of %d), %d items (%d segments of "
            "%d), %d ratings, rank %d",
            n_users, wire.geo_u.total, wire.L_u, n_items, wire.geo_i.total,
            wire.L_i, len(ratings_f), config.rank,
        )
        if timings is not None:
            timings["pack_s"] = _time.perf_counter() - t_phase
        return train_from_wire(
            wire, config,
            timings=timings,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            profile_dir=profile_dir,
            _fp_material=fp_material,
        )

    # Mesh path: host-side packing + sharded placement. Multi-device
    # meshes are local or multi-host (no relayed link), and the packed
    # arrays must be laid out per the mesh sharding anyway.
    counts_u = np.bincount(user_idx, minlength=n_users).astype(np.int32)
    counts_i = np.bincount(item_idx, minlength=n_items).astype(np.int32)
    L_u = auto_segment_length(
        user_idx, n_users, config.segment_length, counts=counts_u
    )
    L_i = auto_segment_length(
        item_idx, n_items, config.segment_length, counts=counts_i
    )
    geo_u = _segment_geometry(counts_u, n_users, L_u, n_shards, config.chunk_slots)
    geo_i = _segment_geometry(counts_i, n_items, L_i, n_shards, config.chunk_slots)
    logger.info(
        "ALS: %d users (%d segments of %d), %d items (%d segments of %d), "
        "%d ratings, rank %d",
        n_users, geo_u.total, L_u, n_items, geo_i.total, L_i,
        len(ratings_f), config.rank,
    )

    row_sharded = P(axis)
    # segment arrays are [C, Sc(, L)]; the segment dim (Sc, a multiple of
    # the shard count) shards over the mesh axis, the chunk dim C is the
    # device-loop trip dim and stays unsharded
    seg_sharded2 = P(None, axis)
    seg_sharded3 = P(None, axis, None)
    X0, Y0 = _factor_init_host(n_users, n_items, config, n_shards)
    X = _place(mesh, X0, row_sharded)
    Y = _place(mesh, Y0, row_sharded)

    user_side = pack_segments(
        user_idx, item_idx, ratings_f, n_users, L_u,
        n_shards, config.chunk_slots,
    )
    item_side = pack_segments(
        item_idx, user_idx, ratings_f, n_items, L_i,
        n_shards, config.chunk_slots,
    )
    if timings is not None:
        timings["pack_s"] = _time.perf_counter() - t_phase
    t_phase = _time.perf_counter()

    def put_pack(side: PackedSide):
        return (
            _place(mesh, side.seg_rows, seg_sharded2),
            _place(mesh, side.cols, seg_sharded3),
            _place(mesh, side.vals, seg_sharded3),
            _place(mesh, side.rem, seg_sharded2),
        )

    user_pack = put_pack(user_side)
    item_pack = put_pack(item_side)

    user_lam_h, user_obs_h = _lam_obs_host(counts_u, n_users, X.shape[0], config)
    item_lam_h, item_obs_h = _lam_obs_host(counts_i, n_items, Y.shape[0], config)
    user_lam = _place(mesh, user_lam_h, row_sharded)
    item_lam = _place(mesh, item_lam_h, row_sharded)
    user_has_obs = _place(mesh, user_obs_h, row_sharded)
    item_has_obs = _place(mesh, item_obs_h, row_sharded)
    if timings is not None:
        # the has_obs arrays were enqueued last; fetching them (small)
        # fences the serialized transfer queue behind the pack arrays
        _sync_fetch((user_has_obs, item_has_obs))
        timings["device_put_s"] = _time.perf_counter() - t_phase
        timings["padded_slots"] = geo_u.total * L_u + geo_i.total * L_i
    return _train_packed(
        user_pack, item_pack, X, Y,
        user_lam, item_lam, user_has_obs, item_has_obs,
        config=config, mesh=mesh, axis=axis,
        n_users=n_users, n_items=n_items,
        checkpoint_dir=checkpoint_dir, checkpoint_every=checkpoint_every,
        timings=timings, profile_dir=profile_dir, fp_material=fp_material,
    )


# --- training telemetry (the observability tentpole's device-loop leg):
# per-sweep convergence rows recorded by the fused loop land in the
# process-global metrics registry, so /metrics on any in-process server
# (and status.json via continuous.py) carries the convergence state of
# the latest round. Families are get-or-create per call — a dict lookup,
# training-round granularity, not a hot path. ---


def _record_compile(outcome: str, busy_s: float = 0.0) -> None:
    """Compile/AOT-cache accounting: ``outcome`` is ``warmed`` (a
    background start_compile_async warm-up built+executed the
    executable), ``cached`` (the geometry bucket was already warm — the
    warm-up skip), ``inline`` (training compiled on the caller's
    thread), or ``error``."""
    reg = _metrics.get_registry()
    reg.counter(
        "pio_als_compile_total",
        "ALS iteration-executable compile events by outcome",
        labels=("outcome",),
    ).labels(outcome=outcome).inc()
    if outcome in ("warmed", "inline"):
        # the geometry-bucket ladder reports into the shared
        # executable-cache accounting (cold-site attribution included:
        # an inline compile under a serving/ingest compile_site counts
        # in pio_cold_compiles_total)
        _cc.record_executable_compile("als-geometry", busy_s)
    if busy_s:
        reg.counter(
            "pio_als_compile_seconds_total",
            "Cumulative seconds spent compiling/warming ALS executables",
        ).inc(busy_s)
    with _WARMED_LOCK:
        n_warm = len(_WARMED_GEOMETRIES)
    reg.gauge(
        "pio_als_warm_geometries",
        "Distinct bucketed geometries whose iteration executable this "
        "process has warmed",
    ).set(n_warm)


def _fetch_telemetry(tel_parts, rows_per_sweep: int = 1) -> Optional[np.ndarray]:
    """Concatenate the per-chunk telemetry buffers into one
    [n_sweeps x rows_per_sweep, TELEMETRY_COLS] host array (rows past
    the TELEMETRY_SLOTS sweep budget per chunk were dropped by the
    in-loop scatter; the subspace solver's buffers carry rows_per_sweep
    block rows per sweep). Multi-host-sharded outputs skip telemetry
    rather than force a cross-process gather."""
    rps = max(1, int(rows_per_sweep))
    rows = []
    for tel, n in tel_parts:
        k = min(int(n), TELEMETRY_SLOTS) * rps
        if k <= 0:
            continue
        if not getattr(tel, "is_fully_addressable", True):
            return None
        rows.append(np.asarray(jax.device_get(tel))[:k])
    if not rows:
        return None
    return np.concatenate(rows, axis=0)


def _sweep_aggregate(sweep_rows: np.ndarray, rows_per_sweep: int) -> np.ndarray:
    """Collapse per-block telemetry rows to one row per sweep: the delta
    columns combine as sqrt(mean(block_rms²)) — exact, since blocks are
    disjoint column sets of equal width — and the per-sweep columns
    (factor RMS, objective) come from the sweep's last block row."""
    rps = max(1, int(rows_per_sweep))
    if rps == 1:
        return sweep_rows
    per = sweep_rows.reshape(-1, rps, sweep_rows.shape[-1])
    out = per[:, -1, :].copy()
    out[:, 0] = np.sqrt(np.mean(np.square(per[:, :, 0]), axis=1))
    out[:, 1] = np.sqrt(np.mean(np.square(per[:, :, 1]), axis=1))
    return out


def _record_sweep_telemetry(
    sweep_rows: np.ndarray,
    device_loop_s: Optional[float],
    n_executed: Optional[int] = None,
    rows_per_sweep: int = 1,
    implicit: bool = False,
) -> None:
    reg = _metrics.get_registry()
    rps = max(1, int(rows_per_sweep))
    per_sweep = _sweep_aggregate(sweep_rows, rps)
    # the telemetry buffer caps at TELEMETRY_SLOTS sweeps per fused-loop
    # call; the sweep counter (and the per-sweep time gauge) must count
    # EXECUTED sweeps, not fetched rows, or a >64-sweep round undercounts
    n = len(per_sweep)
    executed = n if n_executed is None else int(n_executed)
    reg.counter(
        "pio_train_sweeps_total", "ALS sweeps executed by the fused loop"
    ).inc(executed)
    h = reg.histogram(
        "pio_train_sweep_factor_delta",
        "Per-sweep factor-delta RMS (the convergence proxy), by side",
        labels=("side",),
        buckets=_metrics.CONVERGENCE_BUCKETS,
    )
    g_last = reg.gauge(
        "pio_train_last_factor_delta",
        "Factor-delta RMS of the latest round's final sweep, by side",
        labels=("side",),
    )
    for side, col in (("user", 0), ("item", 1)):
        child = h.labels(side=side)
        for v in per_sweep[:, col]:
            if np.isfinite(v):
                child.observe(float(v))
        last = float(per_sweep[-1, col])
        if np.isfinite(last):
            g_last.labels(side=side).set(last)
    if rps > 1:
        # per-block convergence curve of the subspace solver: every
        # block row's update RMS, by side (docs/OBSERVABILITY.md)
        hb = reg.histogram(
            "pio_train_block_factor_delta",
            "Per-block subspace-update RMS of the iALS++ solver, by side",
            labels=("side",),
            buckets=_metrics.CONVERGENCE_BUCKETS,
        )
        for side, col in (("user", 0), ("item", 1)):
            child = hb.labels(side=side)
            for v in sweep_rows[:, col]:
                if np.isfinite(v):
                    child.observe(float(v))
    if implicit:
        obj = float(per_sweep[-1, 4])
        if np.isfinite(obj):
            reg.gauge(
                "pio_train_objective",
                "Implicit (Hu-Koren-Volinsky) training objective at the "
                "latest round's final sweep, Gramian-trick full-matrix "
                "term included",
            ).set(obj)
    if device_loop_s is not None and executed:
        reg.histogram(
            "pio_train_device_loop_seconds",
            "Fused-device-loop wall clock per training round",
            buckets=_metrics.LATENCY_BUCKETS_S,
        ).observe(device_loop_s)
        reg.gauge(
            "pio_train_sweep_seconds",
            "Average device seconds per sweep, latest round",
        ).set(device_loop_s / executed)


def _train_packed(
    user_pack,
    item_pack,
    X: jax.Array,
    Y: jax.Array,
    user_lam: jax.Array,
    item_lam: jax.Array,
    user_has_obs: jax.Array,
    item_has_obs: jax.Array,
    *,
    config: ALSConfig,
    mesh: Optional[Mesh],
    axis: str,
    n_users: int,
    n_items: int,
    checkpoint_dir: Optional[str],
    checkpoint_every: int,
    timings: Optional[dict],
    profile_dir: Optional[str],
    fp_material,  # Callable[[], bytes] — data identity for checkpoints
    compile_wait=None,  # callable from start_compile_async, or None
    factor_slots_out: Optional[dict] = None,  # receives final device X/Y
) -> ALSModelArrays:
    """The shared training tail: compile warm-up, checkpoint/resume, the
    fused iteration loop, and the factor fetch. Every entry path (COO,
    host wire, streaming pipeline, mesh pack) converges here, so the
    device program — and its timings contract — is identical for all."""
    import time as _time

    n_shards = mesh.shape[axis] if mesh is not None else 1
    rep_sharding = NamedSharding(mesh, P()) if mesh is not None else None
    row_sharded = P(axis) if mesh is not None else P()
    row_sharding = NamedSharding(mesh, row_sharded) if mesh is not None else None

    # HBM residency ledger: the live factor state is resident for the
    # whole fused loop. The Anchor ties the entry to this frame, so an
    # exception mid-train still zeroes it; the explicit close below
    # fires on the normal path right after the factors come home.
    _ledger_anchor = _dl.Anchor()
    _fs_label, _fs_bytes, _fs_members = _dl.device_footprint(X, Y)
    _ledger_handle = _dl.get_ledger().register(
        component="train-factors",
        nbytes=_fs_bytes,
        device=_fs_label,
        anchor=_ledger_anchor,
        members=_fs_members,
    )

    def run_iters(X, Y, n_iters: int):
        return _run_iterations(
            X, Y, user_pack, item_pack,
            user_lam, item_lam, user_has_obs, item_has_obs,
            config.alpha, jnp.int32(n_iters),
            implicit=config.implicit_prefs,
            compute_dtype=config.compute_dtype,
            rep_sharding=rep_sharding,
            row_sharding=row_sharding,
            telemetry=config.sweep_telemetry,
            solver=config.solver, block_size=config.block_size,
        )

    if compile_wait is not None:
        # the executable was compiled on a background thread while
        # scan/pack/transfer ran (start_compile_async); only the residual
        # wait — usually zero — is exposed wall clock
        t_phase = _time.perf_counter()
        rec = compile_wait()
        if timings is not None:
            timings["compile_exposed_s"] = _time.perf_counter() - t_phase
            if "busy_s" in rec:
                timings["compile_s"] = rec["busy_s"]
        if rec.get("error") and timings is not None:
            # best-effort warm-up failed; compile inline so the loop
            # timing stays clean
            t_phase = _time.perf_counter()
            with _device_loop_guard():
                _fence(run_iters(X + 0, Y + 0, 0))
            timings["compile_s"] = _time.perf_counter() - t_phase
            _record_compile("inline", timings["compile_s"])
    elif timings is not None:
        # compile outside the timed loop: a ZERO-iteration run builds the
        # same executable the real run reuses (dynamic trip count).
        # Donation consumes its inputs, so feed it copies of the factor
        # arrays (cheap HBM-side copies).
        t_phase = _time.perf_counter()
        with _device_loop_guard():
            _fence(run_iters(X + 0, Y + 0, 0))
        timings["compile_s"] = _time.perf_counter() - t_phase
        _record_compile("inline", timings["compile_s"])

    from predictionio_tpu.workflow.checkpoint import StepCheckpointer

    checkpoint_every = max(1, checkpoint_every)
    ckpt = StepCheckpointer(checkpoint_dir, every=checkpoint_every)
    start_it = 0
    fingerprint = None
    if ckpt.enabled:
        # run identity: same data + same config (iteration count aside) may
        # resume; anything else starts fresh. Guards against silently
        # reusing a finished run's factors after new events arrive, and
        # against shape mismatches from changed user/item counts — the
        # PADDED row dims are part of the identity, so a checkpoint
        # written under a different padding rule (e.g. pre-row-bucketing)
        # restarts cleanly instead of crashing resume on a shape mismatch
        fingerprint = np.frombuffer(
            hashlib.sha256(
                fp_material()
                + repr(dataclasses.replace(config, iterations=0)).encode()
                + f"{n_users},{n_items},{n_shards}".encode()
                + f";rows={X.shape[0]},{Y.shape[0]}".encode()
            ).digest(),
            dtype=np.uint8,
        )
        state = ckpt.restore_latest()
        if state is not None:
            saved_it = int(state["iteration"])
            if not np.array_equal(
                np.asarray(state.get("fingerprint")), fingerprint
            ):
                logger.info(
                    "checkpoint in %s is from a different run (data/config "
                    "changed); training from scratch", checkpoint_dir,
                )
            elif saved_it > config.iterations:
                # can't "untrain": a checkpoint past the requested
                # iteration count would silently return an over-trained
                # model, so start fresh
                logger.info(
                    "checkpoint at iteration %d exceeds requested %d; "
                    "training from scratch", saved_it, config.iterations,
                )
            else:
                start_it = saved_it
                X = _place(mesh, np.asarray(state["X"], np.float32), row_sharded)
                Y = _place(mesh, np.asarray(state["Y"], np.float32), row_sharded)
                logger.info("resuming ALS from iteration %d", start_it)

    from predictionio_tpu.utils.profiling import trace as _profiler_trace

    # per-op observability of the hot loop (SURVEY.md §5): with a
    # profile_dir, EXACTLY the timed device loop(s) run under
    # jax.profiler.trace — no pack/transfer/compile events mixed in
    # (bench.py --trace-loop reduces the trace to docs/ALS_LOOP_TRACE.json).
    # Covers both the single-program path and the checkpoint-chunked loop.
    tel_parts: List[Tuple[jax.Array, int]] = []
    try:
        with _device_loop_guard(), _profiler_trace(profile_dir):
            if not ckpt.enabled:
                # the entire loop is one device program
                if config.iterations > start_it:
                    n_sweeps = config.iterations - start_it
                    t_phase = _time.perf_counter()
                    X, Y, tel = run_iters(X, Y, n_sweeps)
                    tel_parts.append((tel, n_sweeps))
                    if timings is not None or profile_dir is not None:
                        _fence((X, Y))
                    if timings is not None:
                        # recorded before the tracer exits so trace
                        # collection overhead never inflates the loop time
                        timings["device_loop_s"] = (
                            _time.perf_counter() - t_phase
                        )
            else:
                # chunk the fused loop at the checkpoint cadence
                it = start_it
                while it < config.iterations:
                    chunk = min(checkpoint_every, config.iterations - it)
                    t_phase = _time.perf_counter()
                    X, Y, tel = run_iters(X, Y, chunk)
                    tel_parts.append((tel, chunk))
                    if timings is not None:
                        _fence((X, Y))
                        timings["device_loop_s"] = timings.get(
                            "device_loop_s", 0.0
                        ) + (_time.perf_counter() - t_phase)
                    it += chunk
                    logger.debug(
                        "ALS iteration %d/%d done", it, config.iterations
                    )
                    # hand the (possibly mesh-sharded) factor arrays to
                    # orbax as-is: StandardSave handles sharded jax.Arrays
                    # natively, and np.asarray would both crash on
                    # non-fully-addressable multi-host arrays and force a
                    # device->host copy per chunk
                    ckpt.maybe_save(
                        it,
                        {
                            "iteration": it,
                            "X": X,
                            "Y": Y,
                            "fingerprint": fingerprint,
                        },
                        force=True,  # chunk boundaries ARE the cadence
                    )
                    # The next run_iters call DONATES X/Y (donate_argnums),
                    # overwriting these buffers in place; orbax's save may
                    # still be copying them device->host. Block until the
                    # save has committed before handing the buffers back.
                    ckpt.wait_until_finished()
    finally:
        ckpt.close()

    if factor_slots_out is not None:
        # the donated slots' FINAL buffers: after the loop X/Y are fresh
        # device arrays (donation consumed the inputs, not these) — the
        # resident-pack path parks them for the next round's warm start
        # so no factor state ever re-crosses the host→device link
        factor_slots_out["X"] = X
        factor_slots_out["Y"] = Y
    with _device_loop_guard():
        if getattr(X, "is_fully_addressable", True) and getattr(
            Y, "is_fully_addressable", True
        ):
            # one device_get for both factor matrices: each separate fetch
            # costs a full round trip on relayed rigs (~65 ms), which at
            # ML-100K scale is a third of the train wall clock
            X_host, Y_host = jax.device_get((X, Y))
            X_host, Y_host = np.asarray(X_host), np.asarray(Y_host)
        else:
            X_host, Y_host = _fetch_global(X), _fetch_global(Y)
        rows_per_sweep = config.telemetry_rows_per_sweep
        sweep_rows = (
            _fetch_telemetry(tel_parts, rows_per_sweep)
            if config.sweep_telemetry
            else None
        )
    _ledger_handle.close()
    if sweep_rows is not None and len(sweep_rows):
        _record_sweep_telemetry(
            sweep_rows,
            None if timings is None else timings.get("device_loop_s"),
            n_executed=sum(n for _, n in tel_parts),
            rows_per_sweep=rows_per_sweep,
            implicit=config.implicit_prefs,
        )
        if timings is not None:
            per_sweep = _sweep_aggregate(sweep_rows, rows_per_sweep)
            timings["sweep_telemetry"] = [
                {
                    "dx": float(r[0]), "dy": float(r[1]),
                    "x_rms": float(r[2]), "y_rms": float(r[3]),
                    # objective only carries meaning in implicit mode;
                    # explicit rounds keep the historical 4-key rows
                    **(
                        {"objective": float(r[4])}
                        if config.implicit_prefs
                        else {}
                    ),
                }
                for r in per_sweep
            ]
            if rows_per_sweep > 1:
                timings["block_telemetry"] = [
                    {
                        "sweep": ri // rows_per_sweep,
                        "block": ri % rows_per_sweep,
                        "dx": float(r[0]), "dy": float(r[1]),
                    }
                    for ri, r in enumerate(sweep_rows)
                ]
    # OWN the returned factors: on the CPU backend device_get is
    # zero-copy (owndata=False views over XLA-owned buffers). A model —
    # or the delta fold's warm-start seed — outlives the jax.Arrays it
    # was fetched from, and re-reading the view after later donated
    # executions recycled that memory produced flaky NaNs and exit
    # segfaults. One catalog-sized memcpy buys unconditional safety.
    if not X_host.flags.owndata:
        X_host = X_host.copy()
    if not Y_host.flags.owndata:
        Y_host = Y_host.copy()
    return ALSModelArrays(X_host[:n_users], Y_host[:n_items])


def _fetch_global(arr) -> np.ndarray:
    """Materialize a (possibly multi-host-sharded) factor matrix on every
    host. Single-host arrays fetch directly; on a mesh spanning processes
    each host holds only its row shards, so the full matrix assembles via
    an all-gather over DCN (np.asarray would crash on the
    non-fully-addressable array)."""
    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


# --- prediction / evaluation helpers ---


@jax.jit
def _predict_pairs(X, Y, u, i):
    return jnp.sum(X[u] * Y[i], axis=-1)


def predict_ratings(
    model: ALSModelArrays, user_idx, item_idx, chunk: int = 1_048_576
) -> np.ndarray:
    """Predicted rating for each (user, item) pair, chunked through device."""
    X = jnp.asarray(model.user_factors)
    Y = jnp.asarray(model.item_factors)
    u = np.asarray(user_idx, np.int32)
    i = np.asarray(item_idx, np.int32)
    outs = []
    for s in range(0, len(u), chunk):
        outs.append(np.asarray(_predict_pairs(X, Y, u[s : s + chunk], i[s : s + chunk])))
    return np.concatenate(outs) if outs else np.zeros(0, np.float32)


def rmse(model: ALSModelArrays, user_idx, item_idx, ratings) -> float:
    pred = predict_ratings(model, user_idx, item_idx)
    err = pred - np.asarray(ratings, np.float32)
    return float(np.sqrt(np.mean(err * err)))


def _topn_packed_impl(factors_q, Y, n):
    scores = jnp.dot(factors_q, Y.T, preferred_element_type=jnp.float32)
    s, i = jax.lax.top_k(scores, n)  # [B, n] each — one MXU matmul + top_k
    # pack scores+indices into ONE buffer: device->host fetches cost a
    # round trip per buffer (painfully so through relayed test rigs).
    # Indices travel as raw int32 bits, not a float cast — a cast would
    # corrupt ids >= 2^24 (float32 mantissa) on large catalogs.
    i_bits = jax.lax.bitcast_convert_type(i, jnp.float32)
    return jnp.concatenate([s, i_bits], axis=1)


_topn_packed = jax.jit(_topn_packed_impl, static_argnames=("n",))


@functools.partial(jax.jit, static_argnames=("n", "out_s"))
def _topn_packed_sharded(factors_q, Y, n, out_s):
    """Mesh-path top-N with the output PINNED row-sharded. XLA's sharding
    propagation is free to replicate the result of the per-shard
    matmul+top_k (and does on some backends/core counts), which would put
    a B×catalog-independent collective on the serving hot path;
    ``out_s`` (a hashable NamedSharding, so it rides the jit cache as a
    static) keeps each device holding only its query rows' results."""
    return jax.lax.with_sharding_constraint(
        _topn_packed_impl(factors_q, Y, n), out_s
    )


@functools.partial(jax.jit, static_argnames=("n",))
def _topn_packed_chain(factors_q, Y, n, n_iters):
    """n_iters chained top-N passes in ONE dispatch — a measurement tool:
    per-pass device time = (t(K) - t(1)) / (K - 1) cancels the host<->device
    round trip (which on relayed rigs costs ~100 ms and would otherwise
    swamp the ~0.1 ms compute). The query is perturbed per iteration so
    XLA cannot hoist the matmul out of the loop."""
    init = jnp.zeros((factors_q.shape[0], 2 * n), jnp.float32)

    def body(i, _):
        qq = factors_q + i.astype(jnp.float32) * 1e-7
        return _topn_packed_impl(qq, Y, n)

    return jax.lax.fori_loop(0, n_iters, body, init)


# serving top-k executable keys this process already compiled (the
# _topn_packed jit caches are process-global, so the seen-set is too)
_TOPK_SEEN: set = set()


class ServingFactors:
    """Device-resident factors for the serving hot path.

    Transfers the factor matrices to device once; each request then ships
    only the query rows up and one packed result buffer down.

    With a ``mesh``, serving is data-parallel: the item factor matrix
    replicates across the mesh (every device holds the catalog), query
    batches shard rows over the mesh's ``axis``, and each device runs the
    matmul + top_k on its row shard — no collective on the hot path, B×
    the single-chip throughput.
    """

    def __init__(
        self,
        user_factors: np.ndarray,
        item_factors: np.ndarray,
        mesh: Optional[Mesh] = None,
        axis: str = "data",
    ):
        if mesh is not None and mesh.shape[axis] == 1:
            mesh = None
        self.mesh = mesh
        self._axis = axis
        self.user_factors = np.asarray(user_factors)
        if mesh is None:
            self._uf_dev = jax.device_put(
                np.asarray(user_factors, np.float32)
            )
            self._if_dev = jax.device_put(
                np.asarray(item_factors, np.float32)
            )
        else:
            rep = NamedSharding(mesh, P())
            self._uf_dev = jax.device_put(
                np.asarray(user_factors, np.float32), rep
            )
            self._if_dev = jax.device_put(
                np.asarray(item_factors, np.float32), rep
            )
        self.n_items = self._if_dev.shape[0]
        # HBM residency ledger: the replicated serving upload — the
        # footprint counts every per-device COPY (physical bytes), and
        # the member map attributes each copy to its device for drift
        # reconciliation. No explicit free path exists (release_serving
        # just drops the reference and the buffers free by refcount),
        # so the anchor finalizer IS the close — the ledger entry
        # zeroes when the last reference (including a straggler
        # batch's) resolves.
        label, nbytes, members = _dl.device_footprint(
            self._uf_dev, self._if_dev
        )
        self._ledger = _dl.get_ledger().register(
            component="serving-factors",
            nbytes=nbytes,
            device=label,
            anchor=self,
            members=members,
        )

    def topn_by_rows(self, user_rows: np.ndarray, n: int):
        """Top-N for explicit query factor rows [B, k]."""
        b = len(user_rows)
        packed = np.asarray(self.topn_packed_device(user_rows, n))[:b]
        return packed[:, :n], _unpack_indices(packed, n)

    def topn_packed_device(self, user_rows: np.ndarray, n: int) -> jax.Array:
        """Device-resident top-N: upload query rows, run the matmul+top_k,
        return the packed result buffer WITHOUT fetching it to host. Lets
        latency instrumentation separate compute from the device->host hop
        (which costs a full relay round trip on tunneled rigs).

        The row dimension is padded to the next power of two (min 8) so a
        serving workload with varying batch sizes compiles O(log max_batch)
        executables instead of one per distinct size — a cold compile costs
        seconds, which under concurrent load turns the micro-batching
        executor into a compile queue. Callers slice the padding off.
        """
        from predictionio_tpu.ops.similarity import pad_rows_pow2

        q = pad_rows_pow2(user_rows, 8)
        # executable-cache accounting for the serving top-k ladder: the
        # jit cache is keyed by (padded batch, catalog shape, n); a new
        # key is a compile — cold if it lands inside a serving batch
        exec_key = (
            q.shape, self._if_dev.shape, n, self.mesh is None,
        )
        if self.mesh is None:
            q_dev = jax.device_put(q)
            with _cc.track_compile("serving-topk", _TOPK_SEEN, exec_key):
                return _topn_packed(q_dev, self._if_dev, n)
        # shard_batch further pads so the batch divides the mesh axis
        # (a no-op for power-of-two axes), then places row-sharded
        from predictionio_tpu.parallel.mesh import shard_batch

        q_dev, _ = shard_batch(self.mesh, q, self._axis)
        with _cc.track_compile("serving-topk", _TOPK_SEEN, exec_key):
            return _topn_packed_sharded(
                q_dev, self._if_dev, n,
                NamedSharding(self.mesh, P(self._axis)),
            )

    def warm(self, n: int = 16, max_batch: int = 128) -> None:
        """Compile every padded-batch-size executable the serving path can
        hit (deploy-time warm-up; see BaseAlgorithm.warm). With row
        padding to powers of two this is O(log max_batch) compiles."""
        k = self._uf_dev.shape[1]
        n = min(n, self.n_items)
        b = 8
        while True:
            self.topn_by_rows(np.zeros((b, k), np.float32), n)
            if b >= max_batch:
                break
            b *= 2

    def measure_compute_ms(
        self, user_rows: np.ndarray, n: int, iters: int = 256, reps: int = 5
    ) -> float:
        """Amortized per-call device compute time of the top-N op: a
        chained on-device loop of `iters` passes in one dispatch, so the
        host/relay round trip contributes once and cancels in
        (t(iters) - t(1)) / (iters - 1)."""
        import time as _time

        if self.mesh is None:
            q = jax.device_put(np.asarray(user_rows, np.float32))
        else:
            # match the serving placement (row-sharded over the mesh) so
            # the chain's operands live on compatible device sets and the
            # measurement times the sharded executable serving actually runs
            from predictionio_tpu.parallel.mesh import shard_batch

            q, _ = shard_batch(
                self.mesh, np.asarray(user_rows, np.float32), self._axis
            )

        def chain(k):
            return _topn_packed_chain(q, self._if_dev, n, jnp.int32(k))

        chain(1).block_until_ready()  # compile (trip count is dynamic)
        samples = []
        for _ in range(reps):
            t0 = _time.perf_counter()
            chain(1).block_until_ready()
            t1 = _time.perf_counter() - t0
            t0 = _time.perf_counter()
            chain(iters).block_until_ready()
            tk = _time.perf_counter() - t0
            samples.append((tk - t1) / (iters - 1) * 1000.0)
        return float(np.median(samples))

    def topn_by_user(self, user_ids: Sequence[int], n: int):
        """Top-N for known user indices (gathers rows host-side; the row
        count is tiny relative to the item matmul)."""
        rows = self.user_factors[np.asarray(user_ids, np.int64)]
        return self.topn_by_rows(rows, n)


def recommend_batch(
    query_factors: np.ndarray, item_factors: np.ndarray, n: int
) -> Tuple[np.ndarray, np.ndarray]:
    """One-shot top-N (transfers factors each call — use ServingFactors on
    the serving path). Returns (scores [B, n], item indices [B, n])."""
    packed = np.asarray(
        _topn_packed(
            jax.device_put(np.asarray(query_factors, np.float32)),
            jax.device_put(np.asarray(item_factors, np.float32)),
            n,
        )
    )
    return packed[:, :n], _unpack_indices(packed, n)


def _unpack_indices(packed: np.ndarray, n: int) -> np.ndarray:
    """Recover int32 indices from their raw bits in the packed buffer."""
    return np.ascontiguousarray(packed[:, n:]).view(np.int32)
