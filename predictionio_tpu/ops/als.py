"""Alternating Least Squares on a TPU mesh — explicit and implicit feedback.

This is the TPU-native replacement for MLlib ALS
(`ALS.train` / `ALS.trainImplicit`), which the reference's recommendation
templates delegate to (examples/scala-parallel-recommendation/custom-query/
src/main/scala/ALSAlgorithm.scala:66-73). MLlib's implementation exchanges
rating blocks over Spark shuffles each half-iteration; here the design
follows the ALX paper's TPU recipe (PAPERS.md — arXiv:2112.02194):

- **Density bucketing (host):** rows (users, then items) are grouped into
  buckets by observation count; each bucket pads its rows' observation
  lists to a fixed length. All device shapes are static; the ragged CSR
  never reaches the accelerator.
- **Gather + einsum normal equations (device):** for each bucket, gather
  the counter-side factors ``Yg = Y[cols]`` ([N, L, k]), form per-row
  Gramian corrections with one einsum ([N, k, k] — MXU work), add the
  shared Gramian (implicit mode) and regularization, and solve the batched
  k×k systems with Cholesky.
- **Sharding:** bucket rows are sharded over the mesh's ``data`` axis;
  counter-side factors are replicated. The shared Gramian ``YᵀY`` of a
  row-sharded factor matrix is a sharded matmul whose partial products XLA
  all-reduces over ICI — the explicit Gramian all-reduce of the ALX/MLlib
  designs falls out of the sharding annotations.

Solves run in float32 (k×k, numerically delicate); gathers/einsums can run
in bfloat16 with float32 accumulation via ``compute_dtype``.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import logging
import math
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.parallel.mesh import pad_to_multiple

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ALSConfig:
    rank: int = 10
    iterations: int = 10
    reg: float = 0.01
    alpha: float = 1.0  # implicit-feedback confidence scale
    implicit_prefs: bool = False
    # MLlib<=1.3 scales reg by per-row observation count (ALS-WR); "plain"
    # uses unscaled reg.
    reg_mode: str = "weighted"
    seed: int = 0
    compute_dtype: str = "float32"  # or "bfloat16" for MXU-rate einsums
    bucket_sizes: Sequence[int] = (16, 64, 256, 1024, 4096)

    def __post_init__(self):
        if self.reg_mode not in ("weighted", "plain"):
            raise ValueError(f"reg_mode must be weighted|plain, got {self.reg_mode}")


@dataclasses.dataclass
class _Bucket:
    """One padded bucket: rows with ≤ L observations each."""

    rows: np.ndarray  # [N] row ids (padding rows = n_rows sentinel)
    cols: np.ndarray  # [N, L] column ids (padding = 0, masked)
    vals: np.ndarray  # [N, L] ratings
    mask: np.ndarray  # [N, L] 1.0 where real


@dataclasses.dataclass
class BucketedSide:
    """Host-side bucketed view of the rating matrix for one solve side."""

    n_rows: int
    buckets: List[_Bucket]
    counts: np.ndarray  # [n_rows] observation counts


def bucketize(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n_rows: int,
    bucket_sizes: Sequence[int] = (16, 64, 256, 1024, 4096),
    pad_rows_to: int = 1,
) -> BucketedSide:
    """Group rows by observation count into fixed-width padded buckets.

    Rows with more observations than the largest bucket size get a final
    bucket sized to the next power of two ≥ the max count. Each bucket's
    row count is padded to a multiple of ``pad_rows_to`` (the mesh axis
    size) with sentinel rows (id == n_rows) so the row dimension shards
    evenly.
    """
    rows = np.asarray(rows, dtype=np.int32)
    cols = np.asarray(cols, dtype=np.int32)
    vals = np.asarray(vals, dtype=np.float32)
    order = np.argsort(rows, kind="stable")
    rows_s, cols_s, vals_s = rows[order], cols[order], vals[order]
    counts = np.bincount(rows_s, minlength=n_rows).astype(np.int32)
    starts = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])

    sizes = sorted(set(int(s) for s in bucket_sizes))
    max_count = int(counts.max()) if n_rows else 0
    if max_count > sizes[-1]:
        sizes.append(1 << int(math.ceil(math.log2(max(max_count, 2)))))

    # assign each (nonempty) row to the smallest sufficient bucket
    row_ids_by_bucket: List[List[int]] = [[] for _ in sizes]
    nonempty = np.nonzero(counts)[0]
    bucket_of = np.searchsorted(np.asarray(sizes), counts[nonempty])
    for rid, b in zip(nonempty.tolist(), bucket_of.tolist()):
        row_ids_by_bucket[b].append(rid)

    buckets: List[_Bucket] = []
    for L, rids in zip(sizes, row_ids_by_bucket):
        if not rids:
            continue
        n = len(rids)
        n_pad = pad_to_multiple(n, pad_rows_to)
        b_rows = np.full(n_pad, n_rows, dtype=np.int32)
        b_cols = np.zeros((n_pad, L), dtype=np.int32)
        b_vals = np.zeros((n_pad, L), dtype=np.float32)
        b_mask = np.zeros((n_pad, L), dtype=np.float32)
        for i, rid in enumerate(rids):
            s, e = starts[rid], starts[rid + 1]
            c = e - s
            b_rows[i] = rid
            b_cols[i, :c] = cols_s[s:e]
            b_vals[i, :c] = vals_s[s:e]
            b_mask[i, :c] = 1.0
        buckets.append(_Bucket(b_rows, b_cols, b_vals, b_mask))
    return BucketedSide(n_rows=n_rows, buckets=buckets, counts=counts)


# --- device kernels ---


def _solve_bucket(
    X: jax.Array,  # [n_rows+1, k] factor matrix being solved (row-sharded)
    Y: jax.Array,  # [n_cols(+1), k] counter-side factors (replicated)
    G: jax.Array,  # [k, k] shared Gramian YᵀY (implicit) or zeros
    rows: jax.Array,  # [N]
    cols: jax.Array,  # [N, L]
    vals: jax.Array,  # [N, L]
    mask: jax.Array,  # [N, L]
    reg: float,
    alpha: float,
    *,
    implicit: bool,
    weighted_reg: bool,
    compute_dtype: str,
) -> jax.Array:
    k = Y.shape[-1]
    cdt = jnp.dtype(compute_dtype)
    # float32 inputs ask for full-precision MXU passes; bfloat16 trades
    # precision for MXU rate explicitly via compute_dtype
    prec = "highest" if cdt == jnp.float32 else "default"
    Yg = Y[cols].astype(cdt)  # [N, L, k] gather from HBM
    n_obs = mask.sum(-1)  # [N]
    if implicit:
        # MLlib trainImplicit semantics (Hu-Koren-Volinsky): confidence
        # c = alpha·|r| (non-negative — keeps A positive-definite even for
        # dislike ratings r<0, e.g. similarproduct LikeAlgorithm's -1);
        # preference p = 1(r>0). A = G + Σ c·y yᵀ ; b = Σ p·(1+c)·y, so a
        # dislike contributes confidence to A but nothing to b.
        c = (alpha * jnp.abs(vals) * mask).astype(cdt)
        A = G + jnp.einsum(
            "nlk,nl,nlj->nkj", Yg, c, Yg,
            preferred_element_type=jnp.float32, precision=prec,
        )
        pref = (vals > 0).astype(jnp.float32) * mask
        b = jnp.einsum(
            "nlk,nl->nk",
            Yg,
            (pref * (1.0 + alpha * jnp.abs(vals))).astype(cdt),
            preferred_element_type=jnp.float32, precision=prec,
        )
    else:
        # A = Σ y yᵀ over observed ; b = Σ r·y
        A = jnp.einsum(
            "nlk,nl,nlj->nkj",
            Yg,
            mask.astype(cdt),
            Yg,
            preferred_element_type=jnp.float32, precision=prec,
        )
        b = jnp.einsum(
            "nlk,nl->nk",
            Yg,
            (vals * mask).astype(cdt),
            preferred_element_type=jnp.float32, precision=prec,
        )
    lam = reg * n_obs if weighted_reg else jnp.full_like(n_obs, reg)
    # guard all-padding rows against singular systems
    lam = jnp.maximum(lam, 1e-8)
    A = A + lam[:, None, None] * jnp.eye(k, dtype=jnp.float32)
    x = jax.scipy.linalg.cho_solve(jax.scipy.linalg.cho_factor(A), b)
    # scatter solved rows into X; sentinel rows land in the padding row
    return X.at[rows].set(x.astype(X.dtype))


@jax.jit
def _gramian(Y: jax.Array) -> jax.Array:
    """YᵀY in float32. With Y row-sharded this is a reduce over the data
    axis that XLA lowers to psum over ICI."""
    Yf = Y.astype(jnp.float32)
    return jnp.einsum(
        "nk,nj->kj", Yf, Yf,
        preferred_element_type=jnp.float32, precision="highest",
    )


def _constrain(a: jax.Array, sharding) -> jax.Array:
    return (
        jax.lax.with_sharding_constraint(a, sharding)
        if sharding is not None
        else a
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "implicit", "weighted_reg", "compute_dtype",
        "rep_sharding", "row_sharding",
    ),
    donate_argnums=(0, 1),
)
def _run_iterations(
    X: jax.Array,
    Y: jax.Array,
    user_buckets,  # tuple of (rows, cols, vals, mask) tuples
    item_buckets,
    reg: float,
    alpha: float,
    n_iters: jax.Array,  # dynamic: one compile serves every chunk size
    *,
    implicit: bool,
    weighted_reg: bool,
    compute_dtype: str,
    rep_sharding,  # NamedSharding(P()) or None — replicate for gathers
    row_sharding,  # NamedSharding(P(axis)) or None
) -> Tuple[jax.Array, jax.Array]:
    """The whole training loop as ONE XLA program: lax.fori_loop over
    iterations with the (static) bucket structure unrolled inside the
    body. One dispatch covers all iterations — no host round trip per
    half-step, factors never leave HBM, and the replicate/shard handoffs
    become compiled all-gathers instead of per-step device_puts. The trip
    count is a runtime value so warm-up, checkpoint chunks, and resumes
    all reuse the same executable."""
    k = X.shape[-1]
    zeros_g = jnp.zeros((k, k), jnp.float32)

    def half(X, Y, buckets):
        G = _gramian(Y) if implicit else zeros_g
        Y_rep = _constrain(Y, rep_sharding)
        for rows, cols, vals, mask in buckets:
            X = _solve_bucket(
                X, Y_rep, G, rows, cols, vals, mask, reg, alpha,
                implicit=implicit, weighted_reg=weighted_reg,
                compute_dtype=compute_dtype,
            )
        return _constrain(X, row_sharding)

    def body(_, carry):
        X, Y = carry
        X = half(X, Y, user_buckets)
        Y = half(Y, X, item_buckets)
        return (X, Y)

    return jax.lax.fori_loop(0, n_iters, body, (X, Y))


def _place(mesh: Optional[Mesh], arr, spec):
    if mesh is None:
        return jnp.asarray(arr)
    return jax.device_put(arr, NamedSharding(mesh, spec))


@dataclasses.dataclass
class ALSModelArrays:
    """Trained factors (host-resident numpy for persistence; see
    models/recommendation for the serving wrapper)."""

    user_factors: np.ndarray  # [n_users, k]
    item_factors: np.ndarray  # [n_items, k]


def train_als(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    ratings: np.ndarray,
    n_users: int,
    n_items: int,
    config: ALSConfig = ALSConfig(),
    mesh: Optional[Mesh] = None,
    axis: str = "data",
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 5,
) -> ALSModelArrays:
    """Train ALS factors from COO ratings.

    With a mesh, bucket rows are sharded over ``axis`` and counter-side
    factors replicated; each half-iteration's Gramian + factor handoff
    generates the all-reduce/all-gather pattern over ICI.

    With ``checkpoint_dir``, factor state saves every ``checkpoint_every``
    iterations and training resumes from the latest step after an
    interruption (mid-training checkpoint/resume — absent in the
    reference, SURVEY.md §5).
    """
    k = config.rank
    n_shards = mesh.shape[axis] if mesh is not None else 1
    user_side = bucketize(
        user_idx, item_idx, ratings, n_users, config.bucket_sizes, n_shards
    )
    item_side = bucketize(
        item_idx, user_idx, ratings, n_items, config.bucket_sizes, n_shards
    )
    logger.info(
        "ALS: %d users (%d buckets), %d items (%d buckets), %d ratings, rank %d",
        n_users, len(user_side.buckets), n_items, len(item_side.buckets),
        len(ratings), k,
    )

    rng = np.random.default_rng(config.seed)

    def padded_rows(n: int) -> int:
        # +1 sentinel row for bucket padding, rounded up so the row dim
        # shards evenly over the mesh
        return pad_to_multiple(n + 1, n_shards)

    # MLlib-style init: nonnegative scaled normals on the item side;
    # sentinel/padding rows zero
    Y0 = np.zeros((padded_rows(n_items), k), np.float32)
    Y0[:n_items] = np.abs(rng.standard_normal((n_items, k))) / math.sqrt(k)
    rep = P()
    row_sharded = P(axis) if mesh is not None else P()
    X = _place(mesh, np.zeros((padded_rows(n_users), k), np.float32), row_sharded)
    Y = _place(mesh, Y0, row_sharded)

    def put_side(side: BucketedSide):
        out = []
        for b in side.buckets:
            out.append(
                (
                    _place(mesh, b.rows, row_sharded),
                    _place(mesh, b.cols, row_sharded),
                    _place(mesh, b.vals, row_sharded),
                    _place(mesh, b.mask, row_sharded),
                )
            )
        return out

    user_buckets = tuple(put_side(user_side))
    item_buckets = tuple(put_side(item_side))
    rep_sharding = NamedSharding(mesh, rep) if mesh is not None else None
    row_sharding = NamedSharding(mesh, row_sharded) if mesh is not None else None

    def run_iters(X, Y, n_iters: int):
        return _run_iterations(
            X, Y, user_buckets, item_buckets, config.reg, config.alpha,
            jnp.int32(n_iters),
            implicit=config.implicit_prefs,
            weighted_reg=(config.reg_mode == "weighted"),
            compute_dtype=config.compute_dtype,
            rep_sharding=rep_sharding,
            row_sharding=row_sharding,
        )

    from predictionio_tpu.workflow.checkpoint import StepCheckpointer

    checkpoint_every = max(1, checkpoint_every)
    ckpt = StepCheckpointer(checkpoint_dir, every=checkpoint_every)
    start_it = 0
    fingerprint = None
    if ckpt.enabled:
        # run identity: same data + same config (iteration count aside) may
        # resume; anything else starts fresh. Guards against silently
        # reusing a finished run's factors after new events arrive, and
        # against shape mismatches from changed user/item counts.
        fingerprint = np.frombuffer(
            hashlib.sha256(
                user_idx.tobytes()
                + item_idx.tobytes()
                + np.asarray(ratings, np.float32).tobytes()
                + repr(dataclasses.replace(config, iterations=0)).encode()
                + f"{n_users},{n_items},{n_shards}".encode()
            ).digest(),
            dtype=np.uint8,
        )
        state = ckpt.restore_latest()
        if state is not None:
            saved_it = int(state["iteration"])
            if not np.array_equal(
                np.asarray(state.get("fingerprint")), fingerprint
            ):
                logger.info(
                    "checkpoint in %s is from a different run (data/config "
                    "changed); training from scratch", checkpoint_dir,
                )
            elif saved_it > config.iterations:
                # can't "untrain": a checkpoint past the requested
                # iteration count would silently return an over-trained
                # model, so start fresh
                logger.info(
                    "checkpoint at iteration %d exceeds requested %d; "
                    "training from scratch", saved_it, config.iterations,
                )
            else:
                start_it = saved_it
                X = _place(mesh, np.asarray(state["X"], np.float32), row_sharded)
                Y = _place(mesh, np.asarray(state["Y"], np.float32), row_sharded)
                logger.info("resuming ALS from iteration %d", start_it)

    try:
        if not ckpt.enabled:
            # the entire loop is one device program
            if config.iterations > start_it:
                X, Y = run_iters(X, Y, config.iterations - start_it)
        else:
            # chunk the fused loop at the checkpoint cadence
            it = start_it
            while it < config.iterations:
                chunk = min(checkpoint_every, config.iterations - it)
                X, Y = run_iters(X, Y, chunk)
                it += chunk
                logger.debug(
                    "ALS iteration %d/%d done", it, config.iterations
                )
                # hand the (possibly mesh-sharded) factor arrays to orbax
                # as-is: StandardSave handles sharded jax.Arrays natively,
                # and np.asarray would both crash on non-fully-addressable
                # multi-host arrays and force a device->host copy per chunk
                ckpt.maybe_save(
                    it,
                    {
                        "iteration": it,
                        "X": X,
                        "Y": Y,
                        "fingerprint": fingerprint,
                    },
                    force=True,  # chunk boundaries ARE the cadence
                )
    finally:
        ckpt.close()

    user_factors = np.asarray(X)[:n_users]
    item_factors = np.asarray(Y)[:n_items]
    return ALSModelArrays(user_factors, item_factors)


# --- prediction / evaluation helpers ---


@jax.jit
def _predict_pairs(X, Y, u, i):
    return jnp.sum(X[u] * Y[i], axis=-1)


def predict_ratings(
    model: ALSModelArrays, user_idx, item_idx, chunk: int = 1_048_576
) -> np.ndarray:
    """Predicted rating for each (user, item) pair, chunked through device."""
    X = jnp.asarray(model.user_factors)
    Y = jnp.asarray(model.item_factors)
    u = np.asarray(user_idx, np.int32)
    i = np.asarray(item_idx, np.int32)
    outs = []
    for s in range(0, len(u), chunk):
        outs.append(np.asarray(_predict_pairs(X, Y, u[s : s + chunk], i[s : s + chunk])))
    return np.concatenate(outs) if outs else np.zeros(0, np.float32)


def rmse(model: ALSModelArrays, user_idx, item_idx, ratings) -> float:
    pred = predict_ratings(model, user_idx, item_idx)
    err = pred - np.asarray(ratings, np.float32)
    return float(np.sqrt(np.mean(err * err)))


def _topn_packed_impl(factors_q, Y, n):
    scores = jnp.dot(factors_q, Y.T, preferred_element_type=jnp.float32)
    s, i = jax.lax.top_k(scores, n)  # [B, n] each — one MXU matmul + top_k
    # pack scores+indices into ONE buffer: device->host fetches cost a
    # round trip per buffer (painfully so through relayed test rigs).
    # Indices travel as raw int32 bits, not a float cast — a cast would
    # corrupt ids >= 2^24 (float32 mantissa) on large catalogs.
    i_bits = jax.lax.bitcast_convert_type(i, jnp.float32)
    return jnp.concatenate([s, i_bits], axis=1)


_topn_packed = jax.jit(_topn_packed_impl, static_argnames=("n",))


@functools.partial(jax.jit, static_argnames=("n",))
def _topn_packed_chain(factors_q, Y, n, n_iters):
    """n_iters chained top-N passes in ONE dispatch — a measurement tool:
    per-pass device time = (t(K) - t(1)) / (K - 1) cancels the host<->device
    round trip (which on relayed rigs costs ~100 ms and would otherwise
    swamp the ~0.1 ms compute). The query is perturbed per iteration so
    XLA cannot hoist the matmul out of the loop."""
    init = jnp.zeros((factors_q.shape[0], 2 * n), jnp.float32)

    def body(i, _):
        qq = factors_q + i.astype(jnp.float32) * 1e-7
        return _topn_packed_impl(qq, Y, n)

    return jax.lax.fori_loop(0, n_iters, body, init)


class ServingFactors:
    """Device-resident factors for the serving hot path.

    Transfers the factor matrices to device once; each request then ships
    only the query rows up and one packed result buffer down.
    """

    def __init__(self, user_factors: np.ndarray, item_factors: np.ndarray):
        self.user_factors = np.asarray(user_factors)
        self._uf_dev = jax.device_put(np.asarray(user_factors, np.float32))
        self._if_dev = jax.device_put(np.asarray(item_factors, np.float32))
        self.n_items = self._if_dev.shape[0]

    def topn_by_rows(self, user_rows: np.ndarray, n: int):
        """Top-N for explicit query factor rows [B, k]."""
        b = len(user_rows)
        packed = np.asarray(self.topn_packed_device(user_rows, n))[:b]
        return packed[:, :n], _unpack_indices(packed, n)

    def topn_packed_device(self, user_rows: np.ndarray, n: int) -> jax.Array:
        """Device-resident top-N: upload query rows, run the matmul+top_k,
        return the packed result buffer WITHOUT fetching it to host. Lets
        latency instrumentation separate compute from the device->host hop
        (which costs a full relay round trip on tunneled rigs).

        The row dimension is padded to the next power of two (min 8) so a
        serving workload with varying batch sizes compiles O(log max_batch)
        executables instead of one per distinct size — a cold compile costs
        seconds, which under concurrent load turns the micro-batching
        executor into a compile queue. Callers slice the padding off.
        """
        rows = np.asarray(user_rows, np.float32)
        b = rows.shape[0]
        b_pad = max(8, 1 << (b - 1).bit_length())
        if b_pad != b:
            rows = np.concatenate(
                [rows, np.zeros((b_pad - b, rows.shape[1]), np.float32)]
            )
        q = jax.device_put(rows)
        return _topn_packed(q, self._if_dev, n)

    def warm(self, n: int = 16, max_batch: int = 128) -> None:
        """Compile every padded-batch-size executable the serving path can
        hit (deploy-time warm-up; see BaseAlgorithm.warm). With row
        padding to powers of two this is O(log max_batch) compiles."""
        k = self._uf_dev.shape[1]
        n = min(n, self.n_items)
        b = 8
        while True:
            self.topn_by_rows(np.zeros((b, k), np.float32), n)
            if b >= max_batch:
                break
            b *= 2

    def measure_compute_ms(
        self, user_rows: np.ndarray, n: int, iters: int = 256, reps: int = 5
    ) -> float:
        """Amortized per-call device compute time of the top-N op: a
        chained on-device loop of `iters` passes in one dispatch, so the
        host/relay round trip contributes once and cancels in
        (t(iters) - t(1)) / (iters - 1)."""
        import time as _time

        q = jax.device_put(np.asarray(user_rows, np.float32))

        def chain(k):
            return _topn_packed_chain(q, self._if_dev, n, jnp.int32(k))

        chain(1).block_until_ready()  # compile (trip count is dynamic)
        samples = []
        for _ in range(reps):
            t0 = _time.perf_counter()
            chain(1).block_until_ready()
            t1 = _time.perf_counter() - t0
            t0 = _time.perf_counter()
            chain(iters).block_until_ready()
            tk = _time.perf_counter() - t0
            samples.append((tk - t1) / (iters - 1) * 1000.0)
        return float(np.median(samples))

    def topn_by_user(self, user_ids: Sequence[int], n: int):
        """Top-N for known user indices (gathers rows host-side; the row
        count is tiny relative to the item matmul)."""
        rows = self.user_factors[np.asarray(user_ids, np.int64)]
        return self.topn_by_rows(rows, n)


def recommend_batch(
    query_factors: np.ndarray, item_factors: np.ndarray, n: int
) -> Tuple[np.ndarray, np.ndarray]:
    """One-shot top-N (transfers factors each call — use ServingFactors on
    the serving path). Returns (scores [B, n], item indices [B, n])."""
    packed = np.asarray(
        _topn_packed(
            jax.device_put(np.asarray(query_factors, np.float32)),
            jax.device_put(np.asarray(item_factors, np.float32)),
            n,
        )
    )
    return packed[:, :n], _unpack_indices(packed, n)


def _unpack_indices(packed: np.ndarray, n: int) -> np.ndarray:
    """Recover int32 indices from their raw bits in the packed buffer."""
    return np.ascontiguousarray(packed[:, n:]).view(np.int32)
