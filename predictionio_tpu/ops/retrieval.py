"""Sharded on-device top-N retrieval: mesh-resident item factors, fused
score+top-k per shard, cross-shard merge, and on-device candidacy masks.

This is the ALX serving recipe (PAPERS.md, arXiv:2112.02194) applied to
the query path: where ``ServingFactors`` (ops/als.py) REPLICATES the
catalog on every device and data-parallelizes over query rows, this
module ROW-SHARDS the item-factor matrix over the mesh — the layout that
keeps scaling once the catalog outgrows a single device's HBM — and
never materializes the full [B, N] score matrix anywhere:

1. **Per-shard fused score+top-k** (``shard_map``): every device holds
   its factor rows resident between queries, scores the whole query
   batch against its slice with one [B, k] x [k, N/S] matmul, applies
   the candidacy masks as ``-inf`` IN the same program, and runs
   ``lax.top_k`` over its slice. No collective in this stage.
2. **Cross-shard merge**: each shard contributes its top
   ``min(n, rows_per_shard)`` candidates (score + global-id bits packed
   in one buffer); only those B x S x n_local rows cross the
   interconnect (sharded→replicated constraint), and one final
   ``top_k`` over the concatenated candidates yields the EXACT global
   top-N — every global top-n element is by construction within its own
   shard's top-n, so the merge loses nothing. Tie-breaking matches a
   full-matrix ``top_k`` (lowest index wins): within a shard ``top_k``
   orders ties by local index, and the merge concatenates shards in
   ascending-offset order.
3. **Candidacy as on-device masks**: business rules (ecommerce's
   unavailable/blacklist/seen sets, similarproduct's query-item
   exclusion) stop being a host post-filter over the full score row.
   A RESIDENT global mask (refreshed out-of-band on constraint-entity
   change, see data/constraints.py) plus small per-query
   inclusion/exclusion id lists travel as indices and scatter into the
   mask on device; masked scores become ``-inf`` before ``top_k``.

The single-device fallback is the SAME kernel fused into one jit
(score + mask + top_k, one dispatch) — 1-device serving no longer
materializes the full score row per query on host, and the parity tests
cover both shapes. The final packed buffer rides the
``_topn_packed``-style score+index-bits layout (and the row-sharded
output pinning lesson of ``_topn_packed_sharded``): one fetch per batch,
indices as raw int32 bits so ids >= 2^24 survive.

Metrics (utils/metrics.py conventions, visible in ``pio top``):
``pio_retrieval_shard_topk_seconds`` / ``pio_retrieval_merge_seconds``
(every batch off-mesh; SAMPLED on the sharded path — the split needs a
host sync), ``pio_retrieval_mask_refresh_total{component,outcome}``,
``pio_retrieval_mask_age_seconds{component}``, and
``pio_retrieval_resident_bytes{component}``.

Device-observability round: the resident factors/norms and the
candidacy mask register in the HBM residency ledger
(``pio_device_ledger_bytes{device,component,owner}``,
utils/device_ledger.py) — component ``<component>`` for factors+norms,
``<component>-mask`` for the constraint-fed mask; executable compiles
(the fused single-device program and the per-shard stage-1 ladder)
report through utils/compilation_cache.py's executable-cache
accounting, so one compiling inside a live serving batch is counted in
``pio_cold_compiles_total{site="serving"}`` and annotated on the
serving trace. Sampled batches also record padding waste
(``pio_padding_waste_ratio{site}``) and cross-shard skew
(``pio_retrieval_shard_skew{kind}`` — candidate-count and final-result
imbalance over the mesh, the stage-1 load-imbalance proxy: per-shard
scoring work is shape-uniform, so imbalance shows up in candidate
survival, not FLOPs).

Quantized residency (the approximate-computing MF / ALX recipe for
10M+-item catalogs, arXiv:1808.03843 + arXiv:2112.02194): with
``precision="int8"`` the resident rows store as int8 with one float32
scale per row (symmetric per-row quantization, ``scale =
max|row|/127``); ``"bf16"`` is the middle tier. Retrieval becomes
two stages fused into the SAME per-shard program: stage 1 quantizes
the query block the same way and contracts in the quantized domain
(int8 x int8 -> int32 accumulate — the MXU-native form) with the
dequant-rescale epilogue (``* q_scale * row_scale``) fused onto the
accumulator, masks exactly as the float32 path does, and shortlists
the top-(c·n) candidates; stage 2 gathers ONLY those c·n rows,
dequantizes them to float32, and rescores against the full-precision
query BEFORE the (unchanged) cross-shard merge, so the per-shard
truncation keeps the right candidates. The merge returns the full
c·n-wide candidate list, and a final host refinement rescores those
c·n rows per query against the ORIGINAL float32 factors — which stay
in host RAM, where every engine already keeps them for pickling; HBM
holds only the quantized rows. B·c·n·k host FLOPs per batch is noise
next to the device matmul, and it buys id parity with the exact path:
returned scores are exact over the original matrix, and recall can
only be lost when a true top-n item misses the entire merged c·n
shortlist (int8 round-trip error at the top-n boundary alone costs
~0.5% recall; the wide-shortlist + original-rows refine is what gets
the gate to ≥ 0.999). ``float32`` keeps the single-stage exact path
byte-for-byte. Capacity shows up in the ledger (component
``<component>/<precision>`` for quantized deployments) and in
``pio_retrieval_bytes_per_item{component,precision}``.
"""

from __future__ import annotations

import functools
import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.ops.similarity import pad_rows_pow2, pow2_at_least
from predictionio_tpu.parallel.mesh import pad_to_multiple
from predictionio_tpu.utils import compilation_cache as _cc
from predictionio_tpu.utils import device_ledger as _ledger
from predictionio_tpu.utils import metrics as _metrics

logger = logging.getLogger(__name__)

# how often the sharded path takes the host sync that splits shard-topk
# vs merge timing (see ItemRetriever.topn)
_SPLIT_SAMPLE_EVERY = 16

# executable keys this process already compiled on the SHARED
# single-device fused-program jit cache (executable-cache accounting:
# the cache is process-global, so the seen-set must be too — a second
# retriever with identical shapes hits jit's cache, not a compile)
_FUSED_SEEN: set = set()


# serving-time residency precisions for the resident item matrix
# (ItemRetriever ``precision=``, plumbed from the engines' params)
PRECISIONS = ("float32", "bf16", "int8")


def quantize_rows_int8(
    factors: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric per-row int8 quantization: ``scale = max|row|/127``,
    ``row_q = round(row/scale)``. Zero rows get scale 1.0 (their
    quantized form is all-zero either way), so dequantization never
    divides by zero and padding rows stay exactly zero."""
    f = np.asarray(factors, np.float32)
    scale = np.abs(f).max(axis=1) / 127.0
    scale = np.where(scale > 0, scale, 1.0).astype(np.float32)
    q = np.clip(np.rint(f / scale[:, None]), -127, 127).astype(np.int8)
    return q, scale


def dequantize_rows_int8(
    rows_q: np.ndarray, scale: np.ndarray
) -> np.ndarray:
    """f32 rows the int8 storage round-trips to — the matrix the exact
    stage-2 rescore (and therefore the parity oracle) scores against."""
    return rows_q.astype(np.float32) * np.asarray(scale, np.float32)[:, None]


def _reciprocal_norms(factors: np.ndarray) -> np.ndarray:
    """1/||y|| per row, 0 for zero rows — multiplying raw dot scores by
    this yields cosine-against-normalized-candidates, so ONE resident
    factor matrix serves both raw-dot (known-user) and cosine
    (similar-items) scoring instead of two catalog-sized copies."""
    norms = np.linalg.norm(np.asarray(factors, np.float32), axis=1)
    return np.where(norms > 0, 1.0 / np.where(norms == 0, 1.0, norms), 0.0).astype(
        np.float32
    )


def _mask_scores(scores, allow0, excl, incl, has_incl, positive_only):
    """Shared mask application: ``allow0`` is the resident [rows] mask,
    ``excl``/``incl`` are per-query id lists already mapped into THIS
    score block's index space with out-of-range values pointing past the
    last row (``mode="drop"`` discards them — sentinel-padded slots and,
    on a shard, ids owned by other shards). ``has_incl`` flags queries
    with a whitelist: only their rows intersect with the scattered
    inclusion mask."""
    b = jnp.arange(scores.shape[0], dtype=jnp.int32)[:, None]
    allow = jnp.broadcast_to(allow0[None, :], scores.shape)
    allow = allow.at[b, excl].set(False, mode="drop")
    inc = jnp.zeros(scores.shape, bool).at[b, incl].set(True, mode="drop")
    allow = allow & (inc | ~has_incl[:, None])
    if positive_only:
        allow = allow & (scores > 0)
    return jnp.where(allow, scores, -jnp.inf)


def _pack(scores, idx):
    # scores + raw int32 index bits in ONE buffer: one device->host fetch
    # per batch, no float cast of ids (2^24 mantissa cliff on large
    # catalogs) — the _topn_packed layout from ops/als.py
    return jnp.concatenate(
        [scores, jax.lax.bitcast_convert_type(idx, jnp.float32)], axis=1
    )


def unpack_topn(packed: np.ndarray, n: int) -> Tuple[np.ndarray, np.ndarray]:
    """(scores [B, n], global item idx [B, n]) from the packed buffer."""
    packed = np.asarray(packed)
    return (
        packed[:, :n],
        np.ascontiguousarray(packed[:, n:]).view(np.int32),
    )


def pow2_topk_width(
    max_num: int, n_items: int, site: str = "retrieval_topk"
) -> int:
    """The top-k width to request for a batch whose largest query wants
    ``max_num`` results: a power of two (min 16) so varying ``num``s
    share O(log) compiled executables, clamped to the catalog. EVERY
    top-k / shortlist width the serving tier requests routes through
    here (tests/test_lint.py enforces it) — a raw width is one
    executable per distinct ``num``. Records the ladder's padding waste
    (requested vs padded width) in ``pio_padding_waste_ratio{site}``."""
    w = min(max(16, pow2_at_least(max_num)), n_items)
    if w > 0:
        _m_padding_waste().labels(site=site).set(
            (w - min(max_num, w)) / w
        )
    return w


def trimmed_results(
    scores: np.ndarray, idx: np.ndarray, nums: Sequence[int]
) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Per-query ``(item idx, scores)`` pairs from a ``topn`` result,
    trimmed to each query's ``num`` and to its live candidates (masked
    slots carry ``-inf`` and sort to the tail, so the live rows are a
    prefix — this is the k > live-candidate-count edge)."""
    out = []
    for r, num in enumerate(nums):
        row_s, row_i = scores[r], idx[r]
        take = min(int(num), int((row_s > -np.inf).sum()))
        out.append((row_i[:take], row_s[:take]))
    return out


def build_category_index(items) -> Dict[str, np.ndarray]:
    """items dict (dense idx -> object with ``.categories``) inverted
    to category -> sorted dense indices: the host category loop of the
    templates' candidate masks, precomputed once and consumed as an
    on-device inclusion list."""
    by_cat: Dict[str, list] = {}
    for idx, item in items.items():
        for c in item.categories:
            by_cat.setdefault(c, []).append(idx)
    return {c: np.asarray(sorted(v), np.int64) for c, v in by_cat.items()}


def category_candidates(
    index: Dict[str, np.ndarray], categories
) -> np.ndarray:
    """Union of the index rows for the given categories (empty array =
    no item carries any of them, i.e. NO candidates)."""
    arrs = [index[c] for c in categories if c in index]
    if not arrs:
        return np.zeros(0, np.int64)
    return np.unique(np.concatenate(arrs))


def include_candidates(
    item_index, white_list, categories, category_items
) -> Optional[np.ndarray]:
    """The per-query inclusion list both templates share: the
    ``whiteList`` mapped through the item index, intersected with the
    category candidates (``category_items`` is the model's cached
    inverted-index lookup). ``None`` = unrestricted; an EMPTY array =
    NO candidates — matching the host paths' all-False whitelist
    mask."""
    wl: Optional[np.ndarray] = None
    if white_list is not None:
        wl = np.asarray(
            [item_index[i] for i in white_list if i in item_index],
            np.int64,
        )
    if categories is not None:
        cat = category_items(categories)
        wl = cat if wl is None else np.intersect1d(wl, cat)
    return wl


@functools.partial(
    jax.jit, static_argnames=("n", "positive_only", "normalize")
)
def _fused_topn_single(
    q, Y, rn, allow0, excl, incl, has_incl, n, positive_only, normalize
):
    """The single-device path as ONE program: matmul + optional cosine
    scaling + mask scatter + top_k, no [B, N] score materialization on
    host and no host post-filter (the pre-round-12 ecommerce predict
    computed the full score row in numpy and masked it in Python)."""
    scores = jnp.dot(q, Y.T, preferred_element_type=jnp.float32)
    if normalize:
        scores = scores * rn[None, :]
    scores = _mask_scores(scores, allow0, excl, incl, has_incl, positive_only)
    s, i = jax.lax.top_k(scores, n)
    return _pack(s, i)


def _approx_scores(q, Yq, scale, precision):
    """Stage-1 score block in the RESIDENT precision. ``int8`` runs the
    contraction in the quantized domain — the query block quantizes
    per-row the same way the resident rows did, the matmul accumulates
    int8 x int8 -> int32 (the MXU-native form), and the dequant-rescale
    epilogue ``* q_scale * row_scale`` is fused onto the accumulator in
    the same program. ``bf16`` contracts in bf16 with an f32
    accumulator; ``scale`` is unread there (and DCE'd)."""
    if precision == "int8":
        qs = jnp.max(jnp.abs(q), axis=1) / 127.0
        qs = jnp.where(qs > 0, qs, 1.0)
        qi = jnp.clip(
            jnp.round(q / qs[:, None]), -127, 127
        ).astype(jnp.int8)
        acc = jnp.dot(qi, Yq.T, preferred_element_type=jnp.int32)
        return acc.astype(jnp.float32) * qs[:, None] * scale[None, :]
    return jnp.dot(
        q.astype(jnp.bfloat16), Yq.T, preferred_element_type=jnp.float32
    )


def _rescore_exact(
    q, Yq, scale, s1, i1, rn, positive_only, normalize, precision
):
    """Stage 2: gather ONLY the shortlisted rows, dequantize to f32,
    and rescore against the full-precision query — a returned score is
    exact over the dequantized matrix, so quantization can only cost
    stage-1 shortlist misses, never wrong scores. ``positive_only``
    re-applies on the EXACT score (a borderline approx-positive item
    must not leak through), and stage-1 ``-inf`` (masked/dead) slots
    stay ``-inf``."""
    rows = jnp.take(Yq, i1, axis=0).astype(jnp.float32)
    if precision == "int8":
        rows = rows * jnp.take(scale, i1)[:, :, None]
    rescored = jnp.einsum("bk,bck->bc", q, rows)
    if normalize:
        rescored = rescored * jnp.take(rn, i1)
    if positive_only:
        rescored = jnp.where(rescored > 0, rescored, -jnp.inf)
    return jnp.where(s1 == -jnp.inf, -jnp.inf, rescored)


@functools.partial(
    jax.jit,
    static_argnames=(
        "n", "shortlist", "positive_only", "normalize", "precision"
    ),
)
def _fused_topn_single_2s(
    q, Yq, scale, rn, allow0, excl, incl, has_incl,
    n, shortlist, positive_only, normalize, precision,
):
    """Quantized single-device path: BOTH stages in one program —
    approx score with the fused dequant-rescale epilogue + the same
    mask scatter as the exact path + top-(c·n) shortlist, then the
    exact-f32 rescore of just the shortlist rows and the final
    top_k."""
    approx = _approx_scores(q, Yq, scale, precision)
    if normalize:
        approx = approx * rn[None, :]
    approx = _mask_scores(
        approx, allow0, excl, incl, has_incl, positive_only
    )
    s1, i1 = jax.lax.top_k(approx, shortlist)
    rescored = _rescore_exact(
        q, Yq, scale, s1, i1, rn, positive_only, normalize, precision
    )
    s, j = jax.lax.top_k(rescored, n)
    return _pack(s, jnp.take_along_axis(i1, j, axis=1))


def _shard_topk_kernel_2s(
    q, Yq, scale, rn, allow0, excl, incl, has_incl,
    *, axis, n_local, shortlist, positive_only, normalize, precision,
):
    """Per-shard two-stage body (runs under shard_map): the quantized
    counterpart of ``_shard_topk_kernel`` — candidacy masks and the
    id-list localize/scatter are IDENTICAL; only the score producer
    (quantized stage 1 + exact rescore of the top-(c·n_local)
    shortlist) differs. Emits packed top-n_local EXACT candidates with
    global ids, so the cross-shard merge is unchanged."""
    rows_l = Yq.shape[0]
    off = jax.lax.axis_index(axis).astype(jnp.int32) * rows_l

    def localize(g):
        return jnp.where((g >= off) & (g < off + rows_l), g - off, rows_l)

    approx = _approx_scores(q, Yq, scale, precision)
    if normalize:
        approx = approx * rn[None, :]
    approx = _mask_scores(
        approx, allow0, localize(excl), localize(incl), has_incl,
        positive_only,
    )
    s1, i1 = jax.lax.top_k(approx, shortlist)
    rescored = _rescore_exact(
        q, Yq, scale, s1, i1, rn, positive_only, normalize, precision
    )
    s, j = jax.lax.top_k(rescored, n_local)
    return _pack(s, jnp.take_along_axis(i1, j, axis=1) + off)


def _shard_topk_kernel(
    q, Y, rn, allow0, excl, incl, has_incl,
    *, axis, n_local, positive_only, normalize,
):
    """Per-shard body (runs under shard_map): local slice views of the
    resident arrays, replicated query block, NO collective — each shard
    emits its own packed top-n_local candidates with GLOBAL ids."""
    rows_l = Y.shape[0]
    off = jax.lax.axis_index(axis).astype(jnp.int32) * rows_l

    def localize(g):
        # ids owned by other shards map to rows_l (out of range, dropped
        # by the scatter) rather than subtracting into negative values,
        # which .at[] would WRAP NumPy-style back into this shard
        return jnp.where((g >= off) & (g < off + rows_l), g - off, rows_l)

    scores = jnp.dot(q, Y.T, preferred_element_type=jnp.float32)
    if normalize:
        scores = scores * rn[None, :]
    scores = _mask_scores(
        scores, allow0, localize(excl), localize(incl), has_incl,
        positive_only,
    )
    s, i = jax.lax.top_k(scores, n_local)
    return _pack(s, i + off)


@functools.partial(jax.jit, static_argnames=("n", "n_local", "rep_s"))
def _merge_candidates(packed, n, n_local, rep_s):
    """Cross-shard merge: the ONLY sharded→replicated hop, and it moves
    just the B x S x n_local candidate rows (scores + id bits), never
    the score matrix. One final top_k over the concatenation is exact
    (each shard already surfaced every global-top-n element it owns).
    ``rep_s`` pins the output replicated the same way
    ``_topn_packed_sharded`` pins its output row-sharded: as a hashable
    static, so XLA's propagation cannot choose a different layout on
    some backend/core-count combination."""
    x = jax.lax.with_sharding_constraint(packed, rep_s)
    B = x.shape[0]
    S = x.shape[1] // (2 * n_local)
    x = x.reshape(B, S, 2, n_local)
    s_cand = x[:, :, 0, :].reshape(B, S * n_local)
    i_cand = jax.lax.bitcast_convert_type(
        x[:, :, 1, :], jnp.int32
    ).reshape(B, S * n_local)
    s, j = jax.lax.top_k(s_cand, n)
    return _pack(s, jnp.take_along_axis(i_cand, j, axis=1))


# --- metric families (get-or-create per call: dict lookups at batch
# granularity, following the utils/metrics conventions) ---


def _m_shard_seconds():
    return _metrics.get_registry().histogram(
        "pio_retrieval_shard_topk_seconds",
        "Device time of the fused per-shard score+mask+top_k stage "
        "(single-device: the whole fused retrieval program, every "
        "batch; sharded: sampled batches only — the split needs a "
        "host sync)",
        buckets=_metrics.LATENCY_BUCKETS_S,
    )


def _m_merge_seconds():
    return _metrics.get_registry().histogram(
        "pio_retrieval_merge_seconds",
        "Time of the cross-shard candidate merge (the "
        "sharded->replicated hop + final top_k + result fetch; "
        "sampled batches only)",
        buckets=_metrics.LATENCY_BUCKETS_S,
    )


def _m_mask_refresh():
    return _metrics.get_registry().counter(
        "pio_retrieval_mask_refresh_total",
        "Resident candidacy-mask refreshes by outcome "
        "(refreshed=rebuilt+uploaded, unchanged=skipped)",
        labels=("component", "outcome"),
    )


def _m_mask_age():
    return _metrics.get_registry().gauge(
        "pio_retrieval_mask_age_seconds",
        "Seconds since the resident candidacy mask was last refreshed",
        labels=("component",),
    )


def _m_resident_bytes():
    return _metrics.get_registry().gauge(
        "pio_retrieval_resident_bytes",
        "Bytes of retrieval state resident on device (factors + norms "
        "+ mask)",
        labels=("component",),
    )


def _m_bytes_per_item():
    # the name is bytes PER ITEM — a per-row ratio, deliberately not
    # suffixed `_bytes` (that reads as a footprint total, which is
    # pio_retrieval_resident_bytes); tests/test_lint.py's
    # METRIC_NAME_ALLOWED carries the reviewed deviation
    return _metrics.get_registry().gauge(
        "pio_retrieval_bytes_per_item",
        "Device bytes of resident retrieval factor state per catalog "
        "item (rows + per-row scale + folded norms) by serving "
        "precision — the capacity-planning number behind the "
        "float32/bf16/int8 residency ladder",
        labels=("component", "precision"),
    )


def _m_padding_waste():
    return _metrics.get_registry().gauge(
        "pio_padding_waste_ratio",
        "Fraction of a padded dimension that is padding (0 = no waste): "
        "serving batch rows, top-k ladder width, ALS geometry-bucket "
        "slots — the compile-sharing cost the capacity planning reads",
        labels=("site",),
    )


def _m_shard_skew():
    return _metrics.get_registry().gauge(
        "pio_retrieval_shard_skew",
        "Cross-shard retrieval imbalance on sampled batches: "
        "max-shard / mean-shard of live stage-1 candidates "
        "(kind=candidates) and of final top-n contributions "
        "(kind=results); 1.0 = perfectly even",
        labels=("kind",),
    )


def _m_shard_candidates():
    return _metrics.get_registry().gauge(
        "pio_retrieval_shard_candidates",
        "Live stage-1 candidates contributed per shard on the most "
        "recent sampled batch",
        labels=("shard",),
    )


class ItemRetriever:
    """Device-resident top-N retrieval over one item-factor matrix.

    Upload-once semantics: construct at ``prepare_serving`` (the engine
    server's prepared-serving state owns the instance), after which each
    query batch ships only [B, k] query rows plus the small per-query
    id lists up, and one packed [B, 2n] buffer down.

    With a ``mesh`` the factor rows (and the norm/mask vectors) shard
    over ``axis`` and stay resident between queries; without one (or on
    a 1-device mesh) everything lives on ``device`` (default backend
    device) and retrieval is the fused single-program path. Rows are
    zero-padded so the row count divides the shard count; padding rows
    are permanently masked out.

    ``precision`` selects the residency tier: ``"float32"`` (exact,
    single-stage — the historical path, byte-for-byte), ``"bf16"``, or
    ``"int8"`` (rows + one f32 scale per row). Quantized tiers serve
    through the fused two-stage kernels — stage 1 shortlists the
    top-(``shortlist_mult``·n) candidates from the quantized scores,
    stage 2 rescores the shortlist in exact f32 over the dequantized
    rows before the merge — plus a final host refinement of the merged
    c·n candidates against the ORIGINAL f32 rows (host RAM, zero HBM):
    returned scores are exact over the original matrix, ids match the
    exact path except for whole-shortlist misses, and recall is gated
    (≥ 0.999 in tests/bench).
    """

    def __init__(
        self,
        item_factors: np.ndarray,
        mesh: Optional[Mesh] = None,
        axis: str = "data",
        component: str = "retrieval",
        device=None,
        precision: str = "float32",
        shortlist_mult: int = 4,
    ):
        if precision not in PRECISIONS:
            raise ValueError(
                f"precision must be one of {PRECISIONS}, got {precision!r}"
            )
        if shortlist_mult < 1:
            raise ValueError(
                f"shortlist_mult must be >= 1, got {shortlist_mult}"
            )
        if mesh is not None and mesh.shape[axis] == 1:
            # collapse to the fused single-device path, but KEEP the
            # mesh's device: a `pio deploy --workers` worker pinned to
            # one device arrives here as a 1-device mesh, and dropping
            # it would land every worker's resident factors on the
            # process-default device 0
            if device is None:
                device = mesh.devices.flat[0]
            mesh = None
        self.mesh = mesh
        self._axis = axis
        self.component = component
        self.precision = precision
        self.shortlist_mult = int(shortlist_mult)
        factors = np.asarray(item_factors, np.float32)
        self.n_items, self.rank = factors.shape
        n_shards = mesh.shape[axis] if mesh is not None else 1
        self._n_shards = n_shards
        n_pad = pad_to_multiple(max(self.n_items, 1), n_shards)
        self._n_pad = n_pad
        padded = np.zeros((n_pad, self.rank), np.float32)
        padded[: self.n_items] = factors
        # residency tier: the resident row storage + the f32 matrix the
        # device rescore (and the parity oracle) actually scores
        # against. Norms fold from the DEQUANTIZED rows, so the cosine
        # path is self-consistent with stage 2's exact rescore.
        scale_host: Optional[np.ndarray] = None
        if precision == "int8":
            y_host, scale_host = quantize_rows_int8(padded)
            deq = dequantize_rows_int8(y_host, scale_host)
        elif precision == "bf16":
            y_host = padded.astype(jnp.bfloat16)
            deq = y_host.astype(np.float32)
        else:
            y_host, deq = padded, padded
        self._y_host = y_host
        self._scale_host = scale_host
        # the final exact-rescore stage reads the ORIGINAL f32 rows out
        # of host RAM (every engine keeps item_factors host-resident
        # for pickling anyway) — only the quantized rows occupy HBM
        if precision != "float32":
            self._y_f32_host: Optional[np.ndarray] = padded
            rn_exact = np.zeros(n_pad, np.float32)
            rn_exact[: self.n_items] = _reciprocal_norms(factors)
            self._rn_f32_host: Optional[np.ndarray] = rn_exact
        else:
            self._y_f32_host = None
            self._rn_f32_host = None
        rn = np.zeros(n_pad, np.float32)
        rn[: self.n_items] = _reciprocal_norms(deq[: self.n_items])
        self._valid = np.zeros(n_pad, bool)
        self._valid[: self.n_items] = True
        self._excluded_ids: Optional[np.ndarray] = None
        if mesh is None:
            self._device = device
            put = lambda a: (
                jax.device_put(a, device) if device is not None
                else jax.device_put(a)
            )
            self._y_dev = put(y_host)
            self._scale_dev = (
                put(scale_host) if scale_host is not None else None
            )
            self._rn_dev = put(rn)
            self._allow_dev = put(self._valid)
            self._rep_q = None
        else:
            self._device = None
            self._y_dev = jax.device_put(
                y_host, NamedSharding(mesh, P(axis, None))
            )
            self._scale_dev = (
                jax.device_put(scale_host, NamedSharding(mesh, P(axis)))
                if scale_host is not None else None
            )
            self._rn_dev = jax.device_put(rn, NamedSharding(mesh, P(axis)))
            self._allow_dev = jax.device_put(
                self._valid, NamedSharding(mesh, P(axis))
            )
            self._rep_q = NamedSharding(mesh, P())
            self._rep_out = NamedSharding(mesh, P(None, None))
            # per-(n_local, flags, shortlist) jitted shard_map stage-1
            # executables
            self._stage1_cache: Dict[tuple, object] = {}
        self._batches = 0
        self._freed = False
        # per-(n_local, flags, shapes) executables this instance already
        # compiled (executable-cache accounting for the stage-1 ladder;
        # the jit cache behind it is per-instance via self._stage1_cache)
        self._exec_seen: set = set()
        self._mask_stamp = time.monotonic()
        _m_mask_age().labels(component=component).set(0.0)
        # the gauge reads the ACTUAL device arrays, not the f32 host
        # staging copy — on a quantized deployment those differ by the
        # whole point of this mode
        _m_resident_bytes().labels(component=component).set(
            self.resident_bytes
        )
        # HBM residency ledger: factors+norms (+ per-row scales) under
        # the component name — suffixed /<precision> for quantized
        # deployments so pio_device_ledger_bytes attributes capacity
        # per precision tier — and the constraint-fed candidacy mask
        # under <component>-mask (its lifecycle differs — re-uploaded
        # on constraint change). The per-device footprint maps
        # attribute each shard's bytes to its own device for drift
        # reconciliation; the anchor finalizers are the refcount
        # backstop and free() closes explicitly on the drain/release
        # path.
        factor_arrays = [self._y_dev, self._rn_dev]
        if self._scale_dev is not None:
            factor_arrays.append(self._scale_dev)
        f_label, f_bytes, f_members = _ledger.device_footprint(
            *factor_arrays
        )
        self._ledger_component = (
            component if precision == "float32"
            else f"{component}/{precision}"
        )
        self._ledger_factors = _ledger.get_ledger().register(
            component=self._ledger_component,
            nbytes=f_bytes,
            device=f_label,
            anchor=self,
            members=f_members,
        )
        _m_bytes_per_item().labels(
            component=component, precision=precision
        ).set(f_bytes / max(1, self.n_items))
        m_label, m_bytes, m_members = _ledger.device_footprint(
            self._allow_dev
        )
        self._ledger_mask = _ledger.get_ledger().register(
            component=f"{component}-mask",
            nbytes=m_bytes,
            device=m_label,
            anchor=self,
            members=m_members,
        )
        logger.info(
            "ItemRetriever[%s]: %d items (rank %d, %s) resident %s",
            component, self.n_items, self.rank, precision,
            f"row-sharded over {n_shards} devices" if mesh is not None
            else "on one device",
        )

    # --- resident global mask (the out-of-band-refreshed constraint set) ---

    def set_excluded_ids(self, idx) -> bool:
        """Replace the resident exclusion set (dense item indices, e.g.
        the ecommerce ``unavailableItems`` constraint mapped through the
        item index). Rebuilds and re-uploads the sharded mask only when
        the set actually changed; returns whether it did. Called from
        the constraint cache's background refresh thread — the swap is a
        single reference assignment, so in-flight batches keep the mask
        they started with."""
        idx = np.unique(np.asarray(idx, np.int64)) if len(idx) else np.zeros(
            0, np.int64
        )
        idx = idx[(idx >= 0) & (idx < self.n_items)]
        if self._excluded_ids is not None and np.array_equal(
            idx, self._excluded_ids
        ):
            _m_mask_refresh().labels(
                component=self.component, outcome="unchanged"
            ).inc()
            self._touch_mask()
            return False
        allow = self._valid.copy()
        allow[idx] = False
        if self.mesh is None:
            dev = self._device
            self._allow_dev = (
                jax.device_put(allow, dev) if dev is not None
                else jax.device_put(allow)
            )
        else:
            self._allow_dev = jax.device_put(
                allow, NamedSharding(self.mesh, P(self._axis))
            )
        self._excluded_ids = idx
        # re-`set` from the FRESH device footprint (never the size
        # captured at prepare): on a quantized deployment the prepare-
        # time f32 staging sizes are 2-4x the resident truth, and a
        # stale number here is exactly the reconcile() drift the ledger
        # exists to catch. The resident-bytes gauge re-reads the actual
        # arrays for the same reason.
        _, m_bytes, m_members = _ledger.device_footprint(self._allow_dev)
        self._ledger_mask.set(m_bytes, members=m_members)
        _m_resident_bytes().labels(component=self.component).set(
            self.resident_bytes
        )
        _m_mask_refresh().labels(
            component=self.component, outcome="refreshed"
        ).inc()
        self._touch_mask()
        return True

    def _touch_mask(self) -> None:
        self._mask_stamp = time.monotonic()
        _m_mask_age().labels(component=self.component).set(0.0)

    @property
    def mask_age_s(self) -> float:
        return time.monotonic() - self._mask_stamp

    @property
    def resident_bytes(self) -> int:
        arrays = [self._y_dev, self._rn_dev, self._allow_dev]
        if self._scale_dev is not None:
            arrays.append(self._scale_dev)
        return int(sum(a.nbytes for a in arrays))

    def dequantized_factors(self) -> np.ndarray:
        """Host f32 matrix the device path actually scores against —
        the original factors for float32, the dequantized resident rows
        otherwise. This is the reference the exact-rescore parity
        oracle (tests/bench) feeds to ``naive_topn_reference``."""
        if self.precision == "int8":
            deq = dequantize_rows_int8(self._y_host, self._scale_host)
        elif self.precision == "bf16":
            deq = self._y_host.astype(np.float32)
        else:
            deq = self._y_host
        return deq[: self.n_items]

    # --- the hot path ---

    def _assemble_idx(
        self, lists, b_pad: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Per-query id lists -> a sentinel-padded [b_pad, W] int32 block
        (W the next power of two, so executables bucket O(log) widths)
        plus the has-list flag vector. The sentinel is n_pad: out of
        range on every shard and on the single device, so the mask
        scatter drops it."""
        has = np.zeros(b_pad, bool)
        width = 1
        rows: List[np.ndarray] = []
        for a in lists:
            if a is None:
                rows.append(np.zeros(0, np.int64))
                continue
            a = np.asarray(a, np.int64)
            rows.append(a)
            width = max(width, len(a))
        width = pow2_at_least(width)
        out = np.full((b_pad, width), self._n_pad, np.int32)
        for r, a in enumerate(rows):
            if len(a):
                out[r, : len(a)] = a
            has[r] = lists[r] is not None
        return out, has

    def topn(
        self,
        query_rows: np.ndarray,
        n: int,
        *,
        exclude: Optional[Sequence] = None,
        include: Optional[Sequence] = None,
        positive_only: bool = False,
        normalize: bool = False,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Exact masked top-``n`` for a query batch.

        ``exclude``/``include`` are per-query dense item-index arrays
        (``None`` entries mean no list for that query; an ``include``
        entry restricts the query's candidates to exactly that set —
        an empty array means NO candidates, matching whitelist
        semantics). ``positive_only`` drops non-positive scores (the
        templates' ``scores > 0`` rule); ``normalize`` scores against
        L2-normalized candidates (the cosine/similar-items path).
        Returns (scores [B, n], item idx [B, n]); slots past a query's
        live-candidate count carry ``-inf`` — the k > live-candidates
        edge is the caller filtering those out.
        """
        if self._freed:
            raise RuntimeError(
                "ItemRetriever was freed (release_serving); the owner "
                "must null its reference before freeing"
            )
        q = np.atleast_2d(np.asarray(query_rows, np.float32))
        b = q.shape[0]
        if not (0 < n <= self.n_items):
            raise ValueError(
                f"n must be in [1, {self.n_items}], got {n}"
            )
        # quantized precisions: the DEVICE pipeline returns the full
        # c·n-wide merged candidate list (not just n) and a final host
        # refinement rescores it against the ORIGINAL f32 rows — the
        # dequantized matrix reorders items at the top-n boundary, so
        # taking n on-device would cap recall below the 0.999 gate no
        # matter how wide the shard shortlist is
        n_dev = (
            n if self.precision == "float32"
            else self._shortlist_width(n, self.n_items)
        )
        qp = pad_rows_pow2(q, 8)
        b_pad = qp.shape[0]
        excl, _ = self._assemble_idx(
            list(exclude or []) + [None] * (b_pad - b), b_pad
        )
        incl, has_incl = self._assemble_idx(
            list(include or []) + [None] * (b_pad - b), b_pad
        )
        _m_mask_age().labels(component=self.component).set(self.mask_age_s)
        _m_padding_waste().labels(site="retrieval_batch").set(
            (b_pad - b) / b_pad
        )
        if self.mesh is None:
            t0 = time.perf_counter()
            dev = self._device
            put = lambda a: (
                jax.device_put(a, dev) if dev is not None else jnp.asarray(a)
            )
            # executable-cache accounting: the fused program's jit cache
            # is keyed by shapes + statics; a NEW key here is a compile
            # (cold if it happens under a serving compile_site)
            if self.precision == "float32":
                exec_key = (
                    self._n_pad, self.rank, b_pad,
                    excl.shape[1], incl.shape[1],
                    n, positive_only, normalize,
                )
                with _cc.track_compile(
                    "retrieval-fused", _FUSED_SEEN, exec_key
                ):
                    packed = _fused_topn_single(
                        put(qp), self._y_dev, self._rn_dev,
                        self._allow_dev,
                        put(excl), put(incl), put(has_incl),
                        n, positive_only, normalize,
                    )
            else:
                shortlist = self._shortlist_width(n_dev, self._n_pad)
                exec_key = (
                    self._n_pad, self.rank, b_pad,
                    excl.shape[1], incl.shape[1],
                    n_dev, shortlist, positive_only, normalize,
                    self.precision,
                )
                with _cc.track_compile(
                    "retrieval-fused", _FUSED_SEEN, exec_key
                ):
                    packed = _fused_topn_single_2s(
                        put(qp), self._y_dev, self._scale_operand,
                        self._rn_dev, self._allow_dev,
                        put(excl), put(incl), put(has_incl),
                        n_dev, shortlist, positive_only, normalize,
                        self.precision,
                    )
            host = np.asarray(packed)[:b]
            _m_shard_seconds().observe(time.perf_counter() - t0)
            if self.precision != "float32":
                return self._refine_exact(
                    q, host, n_dev, n, positive_only, normalize
                )
            return unpack_topn(host, n)

        rep = self._rep_q
        q_dev = jax.device_put(qp, rep)
        excl_dev = jax.device_put(excl, rep)
        incl_dev = jax.device_put(incl, rep)
        has_dev = jax.device_put(has_incl, rep)
        n_local = min(n_dev, self._n_pad // self._n_shards)
        shortlist = (
            None if self.precision == "float32"
            else self._shortlist_width(
                n_local, self._n_pad // self._n_shards
            )
        )
        stage1 = self._stage1(n_local, positive_only, normalize, shortlist)
        # the shard-vs-merge timing split needs a host sync between the
        # two programs, which would serialize an otherwise back-to-back
        # dispatch on EVERY batch — so the split is SAMPLED (first
        # batch, then every _SPLIT_SAMPLE_EVERY-th); unsampled batches
        # run barrier-free and record nothing in these families
        self._batches += 1
        split = self._batches % _SPLIT_SAMPLE_EVERY == 1
        exec_key = (
            n_local, positive_only, normalize, b_pad,
            excl.shape[1], incl.shape[1], shortlist, self.precision,
        )
        if shortlist is None:
            args = (
                q_dev, self._y_dev, self._rn_dev, self._allow_dev,
                excl_dev, incl_dev, has_dev,
            )
        else:
            args = (
                q_dev, self._y_dev, self._scale_operand, self._rn_dev,
                self._allow_dev, excl_dev, incl_dev, has_dev,
            )
        t0 = time.perf_counter()
        with _cc.track_compile("retrieval-stage1", self._exec_seen, exec_key):
            cand = stage1(*args)
        if split:
            jax.block_until_ready(cand)
            t1 = time.perf_counter()
            _m_shard_seconds().observe(t1 - t0)
        packed = _merge_candidates(cand, n_dev, n_local, self._rep_out)
        host = np.asarray(packed)[:b]
        if split:
            _m_merge_seconds().observe(time.perf_counter() - t1)
            # sampled skew: the candidate buffer is already synced (the
            # split's block_until_ready), so the extra fetch costs one
            # host copy on 1/_SPLIT_SAMPLE_EVERY batches only
            self._record_skew(np.asarray(cand)[:b], host, n_dev, n_local)
        if self.precision != "float32":
            return self._refine_exact(
                q, host, n_dev, n, positive_only, normalize
            )
        return unpack_topn(host, n)

    def _refine_exact(
        self,
        q: np.ndarray,
        packed: np.ndarray,
        n_dev: int,
        n: int,
        positive_only: bool,
        normalize: bool,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Final exact rescore of the device's merged c·n candidates
        against the ORIGINAL float32 rows (host RAM — the engines keep
        ``item_factors`` host-resident anyway, so this costs zero HBM).
        B·c·n·k host FLOPs per batch, negligible next to the B·N·k the
        device just did; recall@n is then limited only by whole-shortlist
        misses and id parity vs the exact path holds by construction."""
        s_d, i_d = unpack_topn(packed, n_dev)
        rows = self._y_f32_host[i_d]  # [B, n_dev, k] gather, host RAM
        sc = np.einsum(
            "bk,bnk->bn", q, rows, optimize=True
        ).astype(np.float32)
        if normalize:
            sc = sc * self._rn_f32_host[i_d]
        if positive_only:
            sc = np.where(sc > 0, sc, -np.inf)
        # dead device slots (masked / past live-candidate count) stay
        # dead regardless of what their placeholder id rescores to
        sc = np.where(s_d == -np.inf, -np.inf, sc)
        # descending exact score, ties broken by LOWEST global id — the
        # same order naive_topn_reference's stable sort produces
        order = np.lexsort((i_d, -sc), axis=1)[:, :n]
        return (
            np.take_along_axis(sc, order, axis=1),
            np.take_along_axis(i_d, order, axis=1),
        )

    def _record_skew(
        self, cand: np.ndarray, host: np.ndarray, n: int, n_local: int
    ) -> None:
        """Cross-shard imbalance from one sampled batch: live stage-1
        candidates per shard, and which shard each final top-n row came
        from. Uniform shapes make per-shard FLOPs equal, so imbalance —
        the thing that stretches the merge's critical path — shows up
        here, not in timers."""
        S = self._n_shards
        if S <= 1 or not len(cand):
            return
        arr = cand.reshape(cand.shape[0], S, 2, n_local)
        live = (arr[:, :, 0, :] > -np.inf).sum(axis=(0, 2)).astype(float)
        g = _m_shard_candidates()
        for s in range(S):
            g.labels(shard=str(s)).set(float(live[s]))
        if live.mean() > 0:
            _m_shard_skew().labels(kind="candidates").set(
                float(live.max() / live.mean())
            )
        idx = np.ascontiguousarray(host[:, n:]).view(np.int32)
        scores = host[:, :n]
        owners = idx[scores > -np.inf] // (self._n_pad // S)
        counts = np.bincount(owners, minlength=S).astype(float)
        if counts.mean() > 0:
            _m_shard_skew().labels(kind="results").set(
                float(counts.max() / counts.mean())
            )

    @property
    def _scale_operand(self):
        """The per-row scale operand of the two-stage kernels. bf16 has
        no scales; the norm vector rides in the slot (same shape and
        sharding spec) and the kernel — static on precision — never
        reads it, so XLA DCEs the input instead of us shipping a dummy
        catalog-length buffer."""
        return (
            self._scale_dev if self._scale_dev is not None
            else self._rn_dev
        )

    def _shortlist_width(self, n: int, rows: int) -> int:
        """Stage-1 shortlist width for a final top-``n`` over ``rows``
        candidate rows: ``shortlist_mult``·n, pow2-bucketed through the
        shared ladder (O(log) compiled widths) and clamped to the row
        count — never below ``n``, so the stage-2 top_k is always
        satisfiable."""
        return pow2_topk_width(
            min(self.shortlist_mult * n, rows), rows,
            site="retrieval_shortlist",
        )

    def _stage1(
        self,
        n_local: int,
        positive_only: bool,
        normalize: bool,
        shortlist: Optional[int] = None,
    ):
        key = (n_local, positive_only, normalize, shortlist)
        fn = self._stage1_cache.get(key)
        if fn is None:
            axis = self._axis
            if shortlist is None:
                kernel = functools.partial(
                    _shard_topk_kernel,
                    axis=self._axis, n_local=n_local,
                    positive_only=positive_only, normalize=normalize,
                )
                in_specs = (
                    P(None, None),  # q: replicated
                    P(axis, None),  # Y: row-sharded
                    P(axis),        # rn
                    P(axis),        # allow
                    P(None, None),  # excl (global ids, replicated)
                    P(None, None),  # incl
                    P(None,),       # has_incl
                )
            else:
                kernel = functools.partial(
                    _shard_topk_kernel_2s,
                    axis=self._axis, n_local=n_local,
                    shortlist=shortlist,
                    positive_only=positive_only, normalize=normalize,
                    precision=self.precision,
                )
                in_specs = (
                    P(None, None),  # q: replicated
                    P(axis, None),  # Yq: row-sharded quantized rows
                    P(axis),        # per-row scales
                    P(axis),        # rn
                    P(axis),        # allow
                    P(None, None),  # excl (global ids, replicated)
                    P(None, None),  # incl
                    P(None,),       # has_incl
                )
            fn = jax.jit(
                shard_map(
                    kernel,
                    mesh=self.mesh,
                    in_specs=in_specs,
                    # per-shard candidate blocks concatenate along the
                    # candidate dim: the stage-1 output STAYS sharded
                    out_specs=P(None, axis),
                    check_rep=False,
                )
            )
            self._stage1_cache[key] = fn
        return fn

    def free(self) -> None:
        """Drop the device-resident buffers (factors, norms, mask) and
        the compiled stage cache. Owner contract (the engines'
        ``release_serving``): null the model's retriever reference FIRST
        and only call this after the last in-flight batch drained — a
        subsequent ``topn`` raises rather than computing on half state.
        The buffers' device memory is freed by refcount: a wedged
        straggler still holding them keeps them alive until it resolves,
        so nothing is ever freed underneath a running batch."""
        self._freed = True
        self._y_dev = None
        self._scale_dev = None
        self._rn_dev = None
        self._allow_dev = None
        self._y_f32_host = None
        self._rn_f32_host = None
        if self.mesh is not None:
            self._stage1_cache = {}
        _m_resident_bytes().labels(component=self.component).set(0.0)
        _m_bytes_per_item().labels(
            component=self.component, precision=self.precision
        ).set(0.0)
        self._ledger_factors.close()
        self._ledger_mask.close()

    def warm(
        self,
        n: int = 16,
        max_batch: int = 128,
        flag_combos: Sequence[Tuple[bool, bool]] = ((True, False),),
        exclude_widths: Sequence[int] = (1, 16, 64),
    ) -> None:
        """Deploy-time compile of the padded-batch executables the
        serving path can hit (O(log) per flag combo x exclude width;
        see BaseAlgorithm.warm). ``flag_combos`` lists the
        (positive_only, normalize) pairs the engine serves with;
        ``exclude_widths`` the per-query exclusion-list widths to
        pre-trace — the id-list block pads to a power of two, so a
        query arriving with a blacklist/seen set is a DIFFERENT traced
        shape than a bare query, and without warming it the first such
        query would pay an XLA compile inside a live batch. 1/16/64
        cover bare queries and the common seen/blacklist sizes; rarer
        widths (and whitelists) still compile on first use.

        The top-k width itself LADDERS (16 doubling to ``n``): each
        pow2 tier the pow2_topk_width router can request is a distinct
        executable, and on a quantized retriever each tier also pins
        its derived stage-1 shortlist width — so the whole
        precision x shortlist combination space this instance can
        serve compiles here, never inside the first live batch that
        asks for a wider ``num``."""
        k = self.rank
        tiers: List[int] = []
        w = 16
        while True:
            tiers.append(min(w, self.n_items))
            if w >= min(n, self.n_items):
                break
            w *= 2
        for nn in sorted(set(tiers)):
            for positive_only, normalize in flag_combos:
                for ew in exclude_widths:
                    excl_row = np.zeros(ew, np.int64) if ew > 1 else None
                    b = 8
                    while True:
                        self.topn(
                            np.zeros((b, k), np.float32), nn,
                            exclude=(
                                [excl_row] * b
                                if excl_row is not None else None
                            ),
                            positive_only=positive_only,
                            normalize=normalize,
                        )
                        if b >= max_batch:
                            break
                        b *= 2


def naive_topn_reference(
    item_factors: np.ndarray,
    query_rows: np.ndarray,
    n: int,
    *,
    exclude: Optional[Sequence] = None,
    include: Optional[Sequence] = None,
    positive_only: bool = False,
    normalize: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """The naive path the sharded retriever must match id-for-id: ONE
    full [B, N] score matrix (device matmul — the same contraction the
    sharded kernel runs per slice), then a HOST post-filter and sort per
    query. This is both the parity oracle for tests and the
    ``retrieval_vs_naive_speedup`` denominator in the saturation bench —
    it is what serving did before round 12."""
    Y = np.asarray(item_factors, np.float32)
    q = np.atleast_2d(np.asarray(query_rows, np.float32))
    scores = np.asarray(
        jnp.dot(jnp.asarray(q), jnp.asarray(Y).T,
                preferred_element_type=jnp.float32)
    ).copy()
    if normalize:
        scores *= _reciprocal_norms(Y)[None, :]
    b, N = scores.shape
    out_s = np.full((b, n), -np.inf, np.float32)
    out_i = np.zeros((b, n), np.int32)
    for r in range(b):
        row = scores[r]
        allow = np.ones(N, bool)
        inc_list = include[r] if include is not None else None
        if inc_list is not None:
            wl = np.zeros(N, bool)
            wl[np.asarray(inc_list, np.int64)] = True
            allow &= wl
        exc_list = exclude[r] if exclude is not None else None
        if exc_list is not None and len(exc_list):
            allow[np.asarray(exc_list, np.int64)] = False
        if positive_only:
            allow &= row > 0
        masked = np.where(allow, row, -np.inf)
        order = np.argsort(-masked, kind="stable")[:n]
        out_s[r, : len(order)] = masked[order]
        out_i[r, : len(order)] = order
    return out_s, out_i
