"""Sharded on-device top-N retrieval: mesh-resident item factors, fused
score+top-k per shard, cross-shard merge, and on-device candidacy masks.

This is the ALX serving recipe (PAPERS.md, arXiv:2112.02194) applied to
the query path: where ``ServingFactors`` (ops/als.py) REPLICATES the
catalog on every device and data-parallelizes over query rows, this
module ROW-SHARDS the item-factor matrix over the mesh — the layout that
keeps scaling once the catalog outgrows a single device's HBM — and
never materializes the full [B, N] score matrix anywhere:

1. **Per-shard fused score+top-k** (``shard_map``): every device holds
   its factor rows resident between queries, scores the whole query
   batch against its slice with one [B, k] x [k, N/S] matmul, applies
   the candidacy masks as ``-inf`` IN the same program, and runs
   ``lax.top_k`` over its slice. No collective in this stage.
2. **Cross-shard merge**: each shard contributes its top
   ``min(n, rows_per_shard)`` candidates (score + global-id bits packed
   in one buffer); only those B x S x n_local rows cross the
   interconnect (sharded→replicated constraint), and one final
   ``top_k`` over the concatenated candidates yields the EXACT global
   top-N — every global top-n element is by construction within its own
   shard's top-n, so the merge loses nothing. Tie-breaking matches a
   full-matrix ``top_k`` (lowest index wins): within a shard ``top_k``
   orders ties by local index, and the merge concatenates shards in
   ascending-offset order.
3. **Candidacy as on-device masks**: business rules (ecommerce's
   unavailable/blacklist/seen sets, similarproduct's query-item
   exclusion) stop being a host post-filter over the full score row.
   A RESIDENT global mask (refreshed out-of-band on constraint-entity
   change, see data/constraints.py) plus small per-query
   inclusion/exclusion id lists travel as indices and scatter into the
   mask on device; masked scores become ``-inf`` before ``top_k``.

The single-device fallback is the SAME kernel fused into one jit
(score + mask + top_k, one dispatch) — 1-device serving no longer
materializes the full score row per query on host, and the parity tests
cover both shapes. The final packed buffer rides the
``_topn_packed``-style score+index-bits layout (and the row-sharded
output pinning lesson of ``_topn_packed_sharded``): one fetch per batch,
indices as raw int32 bits so ids >= 2^24 survive.

Metrics (utils/metrics.py conventions, visible in ``pio top``):
``pio_retrieval_shard_topk_seconds`` / ``pio_retrieval_merge_seconds``
(every batch off-mesh; SAMPLED on the sharded path — the split needs a
host sync), ``pio_retrieval_mask_refresh_total{component,outcome}``,
``pio_retrieval_mask_age_seconds{component}``, and
``pio_retrieval_resident_bytes{component}``.

Device-observability round: the resident factors/norms and the
candidacy mask register in the HBM residency ledger
(``pio_device_ledger_bytes{device,component,owner}``,
utils/device_ledger.py) — component ``<component>`` for factors+norms,
``<component>-mask`` for the constraint-fed mask; executable compiles
(the fused single-device program and the per-shard stage-1 ladder)
report through utils/compilation_cache.py's executable-cache
accounting, so one compiling inside a live serving batch is counted in
``pio_cold_compiles_total{site="serving"}`` and annotated on the
serving trace. Sampled batches also record padding waste
(``pio_padding_waste_ratio{site}``) and cross-shard skew
(``pio_retrieval_shard_skew{kind}`` — candidate-count and final-result
imbalance over the mesh, the stage-1 load-imbalance proxy: per-shard
scoring work is shape-uniform, so imbalance shows up in candidate
survival, not FLOPs).
"""

from __future__ import annotations

import functools
import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.ops.similarity import pad_rows_pow2, pow2_at_least
from predictionio_tpu.parallel.mesh import pad_to_multiple
from predictionio_tpu.utils import compilation_cache as _cc
from predictionio_tpu.utils import device_ledger as _ledger
from predictionio_tpu.utils import metrics as _metrics

logger = logging.getLogger(__name__)

# how often the sharded path takes the host sync that splits shard-topk
# vs merge timing (see ItemRetriever.topn)
_SPLIT_SAMPLE_EVERY = 16

# executable keys this process already compiled on the SHARED
# single-device fused-program jit cache (executable-cache accounting:
# the cache is process-global, so the seen-set must be too — a second
# retriever with identical shapes hits jit's cache, not a compile)
_FUSED_SEEN: set = set()


def _reciprocal_norms(factors: np.ndarray) -> np.ndarray:
    """1/||y|| per row, 0 for zero rows — multiplying raw dot scores by
    this yields cosine-against-normalized-candidates, so ONE resident
    factor matrix serves both raw-dot (known-user) and cosine
    (similar-items) scoring instead of two catalog-sized copies."""
    norms = np.linalg.norm(np.asarray(factors, np.float32), axis=1)
    return np.where(norms > 0, 1.0 / np.where(norms == 0, 1.0, norms), 0.0).astype(
        np.float32
    )


def _mask_scores(scores, allow0, excl, incl, has_incl, positive_only):
    """Shared mask application: ``allow0`` is the resident [rows] mask,
    ``excl``/``incl`` are per-query id lists already mapped into THIS
    score block's index space with out-of-range values pointing past the
    last row (``mode="drop"`` discards them — sentinel-padded slots and,
    on a shard, ids owned by other shards). ``has_incl`` flags queries
    with a whitelist: only their rows intersect with the scattered
    inclusion mask."""
    b = jnp.arange(scores.shape[0], dtype=jnp.int32)[:, None]
    allow = jnp.broadcast_to(allow0[None, :], scores.shape)
    allow = allow.at[b, excl].set(False, mode="drop")
    inc = jnp.zeros(scores.shape, bool).at[b, incl].set(True, mode="drop")
    allow = allow & (inc | ~has_incl[:, None])
    if positive_only:
        allow = allow & (scores > 0)
    return jnp.where(allow, scores, -jnp.inf)


def _pack(scores, idx):
    # scores + raw int32 index bits in ONE buffer: one device->host fetch
    # per batch, no float cast of ids (2^24 mantissa cliff on large
    # catalogs) — the _topn_packed layout from ops/als.py
    return jnp.concatenate(
        [scores, jax.lax.bitcast_convert_type(idx, jnp.float32)], axis=1
    )


def unpack_topn(packed: np.ndarray, n: int) -> Tuple[np.ndarray, np.ndarray]:
    """(scores [B, n], global item idx [B, n]) from the packed buffer."""
    packed = np.asarray(packed)
    return (
        packed[:, :n],
        np.ascontiguousarray(packed[:, n:]).view(np.int32),
    )


def pow2_topk_width(max_num: int, n_items: int) -> int:
    """The top-k width to request for a batch whose largest query wants
    ``max_num`` results: a power of two (min 16) so varying ``num``s
    share O(log) compiled executables, clamped to the catalog. Records
    the ladder's padding waste (requested vs padded width) in
    ``pio_padding_waste_ratio{site="retrieval_topk"}``."""
    w = min(max(16, pow2_at_least(max_num)), n_items)
    if w > 0:
        _m_padding_waste().labels(site="retrieval_topk").set(
            (w - min(max_num, w)) / w
        )
    return w


def trimmed_results(
    scores: np.ndarray, idx: np.ndarray, nums: Sequence[int]
) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Per-query ``(item idx, scores)`` pairs from a ``topn`` result,
    trimmed to each query's ``num`` and to its live candidates (masked
    slots carry ``-inf`` and sort to the tail, so the live rows are a
    prefix — this is the k > live-candidate-count edge)."""
    out = []
    for r, num in enumerate(nums):
        row_s, row_i = scores[r], idx[r]
        take = min(int(num), int((row_s > -np.inf).sum()))
        out.append((row_i[:take], row_s[:take]))
    return out


def build_category_index(items) -> Dict[str, np.ndarray]:
    """items dict (dense idx -> object with ``.categories``) inverted
    to category -> sorted dense indices: the host category loop of the
    templates' candidate masks, precomputed once and consumed as an
    on-device inclusion list."""
    by_cat: Dict[str, list] = {}
    for idx, item in items.items():
        for c in item.categories:
            by_cat.setdefault(c, []).append(idx)
    return {c: np.asarray(sorted(v), np.int64) for c, v in by_cat.items()}


def category_candidates(
    index: Dict[str, np.ndarray], categories
) -> np.ndarray:
    """Union of the index rows for the given categories (empty array =
    no item carries any of them, i.e. NO candidates)."""
    arrs = [index[c] for c in categories if c in index]
    if not arrs:
        return np.zeros(0, np.int64)
    return np.unique(np.concatenate(arrs))


def include_candidates(
    item_index, white_list, categories, category_items
) -> Optional[np.ndarray]:
    """The per-query inclusion list both templates share: the
    ``whiteList`` mapped through the item index, intersected with the
    category candidates (``category_items`` is the model's cached
    inverted-index lookup). ``None`` = unrestricted; an EMPTY array =
    NO candidates — matching the host paths' all-False whitelist
    mask."""
    wl: Optional[np.ndarray] = None
    if white_list is not None:
        wl = np.asarray(
            [item_index[i] for i in white_list if i in item_index],
            np.int64,
        )
    if categories is not None:
        cat = category_items(categories)
        wl = cat if wl is None else np.intersect1d(wl, cat)
    return wl


@functools.partial(
    jax.jit, static_argnames=("n", "positive_only", "normalize")
)
def _fused_topn_single(
    q, Y, rn, allow0, excl, incl, has_incl, n, positive_only, normalize
):
    """The single-device path as ONE program: matmul + optional cosine
    scaling + mask scatter + top_k, no [B, N] score materialization on
    host and no host post-filter (the pre-round-12 ecommerce predict
    computed the full score row in numpy and masked it in Python)."""
    scores = jnp.dot(q, Y.T, preferred_element_type=jnp.float32)
    if normalize:
        scores = scores * rn[None, :]
    scores = _mask_scores(scores, allow0, excl, incl, has_incl, positive_only)
    s, i = jax.lax.top_k(scores, n)
    return _pack(s, i)


def _shard_topk_kernel(
    q, Y, rn, allow0, excl, incl, has_incl,
    *, axis, n_local, positive_only, normalize,
):
    """Per-shard body (runs under shard_map): local slice views of the
    resident arrays, replicated query block, NO collective — each shard
    emits its own packed top-n_local candidates with GLOBAL ids."""
    rows_l = Y.shape[0]
    off = jax.lax.axis_index(axis).astype(jnp.int32) * rows_l

    def localize(g):
        # ids owned by other shards map to rows_l (out of range, dropped
        # by the scatter) rather than subtracting into negative values,
        # which .at[] would WRAP NumPy-style back into this shard
        return jnp.where((g >= off) & (g < off + rows_l), g - off, rows_l)

    scores = jnp.dot(q, Y.T, preferred_element_type=jnp.float32)
    if normalize:
        scores = scores * rn[None, :]
    scores = _mask_scores(
        scores, allow0, localize(excl), localize(incl), has_incl,
        positive_only,
    )
    s, i = jax.lax.top_k(scores, n_local)
    return _pack(s, i + off)


@functools.partial(jax.jit, static_argnames=("n", "n_local", "rep_s"))
def _merge_candidates(packed, n, n_local, rep_s):
    """Cross-shard merge: the ONLY sharded→replicated hop, and it moves
    just the B x S x n_local candidate rows (scores + id bits), never
    the score matrix. One final top_k over the concatenation is exact
    (each shard already surfaced every global-top-n element it owns).
    ``rep_s`` pins the output replicated the same way
    ``_topn_packed_sharded`` pins its output row-sharded: as a hashable
    static, so XLA's propagation cannot choose a different layout on
    some backend/core-count combination."""
    x = jax.lax.with_sharding_constraint(packed, rep_s)
    B = x.shape[0]
    S = x.shape[1] // (2 * n_local)
    x = x.reshape(B, S, 2, n_local)
    s_cand = x[:, :, 0, :].reshape(B, S * n_local)
    i_cand = jax.lax.bitcast_convert_type(
        x[:, :, 1, :], jnp.int32
    ).reshape(B, S * n_local)
    s, j = jax.lax.top_k(s_cand, n)
    return _pack(s, jnp.take_along_axis(i_cand, j, axis=1))


# --- metric families (get-or-create per call: dict lookups at batch
# granularity, following the utils/metrics conventions) ---


def _m_shard_seconds():
    return _metrics.get_registry().histogram(
        "pio_retrieval_shard_topk_seconds",
        "Device time of the fused per-shard score+mask+top_k stage "
        "(single-device: the whole fused retrieval program, every "
        "batch; sharded: sampled batches only — the split needs a "
        "host sync)",
        buckets=_metrics.LATENCY_BUCKETS_S,
    )


def _m_merge_seconds():
    return _metrics.get_registry().histogram(
        "pio_retrieval_merge_seconds",
        "Time of the cross-shard candidate merge (the "
        "sharded->replicated hop + final top_k + result fetch; "
        "sampled batches only)",
        buckets=_metrics.LATENCY_BUCKETS_S,
    )


def _m_mask_refresh():
    return _metrics.get_registry().counter(
        "pio_retrieval_mask_refresh_total",
        "Resident candidacy-mask refreshes by outcome "
        "(refreshed=rebuilt+uploaded, unchanged=skipped)",
        labels=("component", "outcome"),
    )


def _m_mask_age():
    return _metrics.get_registry().gauge(
        "pio_retrieval_mask_age_seconds",
        "Seconds since the resident candidacy mask was last refreshed",
        labels=("component",),
    )


def _m_resident_bytes():
    return _metrics.get_registry().gauge(
        "pio_retrieval_resident_bytes",
        "Bytes of retrieval state resident on device (factors + norms "
        "+ mask)",
        labels=("component",),
    )


def _m_padding_waste():
    return _metrics.get_registry().gauge(
        "pio_padding_waste_ratio",
        "Fraction of a padded dimension that is padding (0 = no waste): "
        "serving batch rows, top-k ladder width, ALS geometry-bucket "
        "slots — the compile-sharing cost the capacity planning reads",
        labels=("site",),
    )


def _m_shard_skew():
    return _metrics.get_registry().gauge(
        "pio_retrieval_shard_skew",
        "Cross-shard retrieval imbalance on sampled batches: "
        "max-shard / mean-shard of live stage-1 candidates "
        "(kind=candidates) and of final top-n contributions "
        "(kind=results); 1.0 = perfectly even",
        labels=("kind",),
    )


def _m_shard_candidates():
    return _metrics.get_registry().gauge(
        "pio_retrieval_shard_candidates",
        "Live stage-1 candidates contributed per shard on the most "
        "recent sampled batch",
        labels=("shard",),
    )


class ItemRetriever:
    """Device-resident top-N retrieval over one item-factor matrix.

    Upload-once semantics: construct at ``prepare_serving`` (the engine
    server's prepared-serving state owns the instance), after which each
    query batch ships only [B, k] query rows plus the small per-query
    id lists up, and one packed [B, 2n] buffer down.

    With a ``mesh`` the factor rows (and the norm/mask vectors) shard
    over ``axis`` and stay resident between queries; without one (or on
    a 1-device mesh) everything lives on ``device`` (default backend
    device) and retrieval is the fused single-program path. Rows are
    zero-padded so the row count divides the shard count; padding rows
    are permanently masked out.
    """

    def __init__(
        self,
        item_factors: np.ndarray,
        mesh: Optional[Mesh] = None,
        axis: str = "data",
        component: str = "retrieval",
        device=None,
    ):
        if mesh is not None and mesh.shape[axis] == 1:
            # collapse to the fused single-device path, but KEEP the
            # mesh's device: a `pio deploy --workers` worker pinned to
            # one device arrives here as a 1-device mesh, and dropping
            # it would land every worker's resident factors on the
            # process-default device 0
            if device is None:
                device = mesh.devices.flat[0]
            mesh = None
        self.mesh = mesh
        self._axis = axis
        self.component = component
        factors = np.asarray(item_factors, np.float32)
        self.n_items, self.rank = factors.shape
        n_shards = mesh.shape[axis] if mesh is not None else 1
        self._n_shards = n_shards
        n_pad = pad_to_multiple(max(self.n_items, 1), n_shards)
        self._n_pad = n_pad
        padded = np.zeros((n_pad, self.rank), np.float32)
        padded[: self.n_items] = factors
        rn = np.zeros(n_pad, np.float32)
        rn[: self.n_items] = _reciprocal_norms(factors)
        self._valid = np.zeros(n_pad, bool)
        self._valid[: self.n_items] = True
        self._excluded_ids: Optional[np.ndarray] = None
        if mesh is None:
            self._device = device
            put = lambda a: (
                jax.device_put(a, device) if device is not None
                else jax.device_put(a)
            )
            self._y_dev = put(padded)
            self._rn_dev = put(rn)
            self._allow_dev = put(self._valid)
            self._rep_q = None
        else:
            self._device = None
            self._y_dev = jax.device_put(
                padded, NamedSharding(mesh, P(axis, None))
            )
            self._rn_dev = jax.device_put(rn, NamedSharding(mesh, P(axis)))
            self._allow_dev = jax.device_put(
                self._valid, NamedSharding(mesh, P(axis))
            )
            self._rep_q = NamedSharding(mesh, P())
            self._rep_out = NamedSharding(mesh, P(None, None))
            # per-(n_local, flags) jitted shard_map stage-1 executables
            self._stage1_cache: Dict[tuple, object] = {}
        self._batches = 0
        self._freed = False
        # per-(n_local, flags, shapes) executables this instance already
        # compiled (executable-cache accounting for the stage-1 ladder;
        # the jit cache behind it is per-instance via self._stage1_cache)
        self._exec_seen: set = set()
        self._mask_stamp = time.monotonic()
        _m_mask_age().labels(component=component).set(0.0)
        _m_resident_bytes().labels(component=component).set(
            padded.nbytes + rn.nbytes + self._valid.nbytes
        )
        # HBM residency ledger: factors+norms under the component name,
        # the constraint-fed candidacy mask under <component>-mask (its
        # lifecycle differs — re-uploaded on constraint change). The
        # per-device footprint maps attribute each shard's bytes to its
        # own device for drift reconciliation; the anchor finalizers
        # are the refcount backstop and free() closes explicitly on the
        # drain/release path.
        f_label, f_bytes, f_members = _ledger.device_footprint(
            self._y_dev, self._rn_dev
        )
        self._ledger_factors = _ledger.get_ledger().register(
            component=component,
            nbytes=f_bytes,
            device=f_label,
            anchor=self,
            members=f_members,
        )
        m_label, m_bytes, m_members = _ledger.device_footprint(
            self._allow_dev
        )
        self._ledger_mask = _ledger.get_ledger().register(
            component=f"{component}-mask",
            nbytes=m_bytes,
            device=m_label,
            anchor=self,
            members=m_members,
        )
        logger.info(
            "ItemRetriever[%s]: %d items (rank %d) resident %s",
            component, self.n_items, self.rank,
            f"row-sharded over {n_shards} devices" if mesh is not None
            else "on one device",
        )

    # --- resident global mask (the out-of-band-refreshed constraint set) ---

    def set_excluded_ids(self, idx) -> bool:
        """Replace the resident exclusion set (dense item indices, e.g.
        the ecommerce ``unavailableItems`` constraint mapped through the
        item index). Rebuilds and re-uploads the sharded mask only when
        the set actually changed; returns whether it did. Called from
        the constraint cache's background refresh thread — the swap is a
        single reference assignment, so in-flight batches keep the mask
        they started with."""
        idx = np.unique(np.asarray(idx, np.int64)) if len(idx) else np.zeros(
            0, np.int64
        )
        idx = idx[(idx >= 0) & (idx < self.n_items)]
        if self._excluded_ids is not None and np.array_equal(
            idx, self._excluded_ids
        ):
            _m_mask_refresh().labels(
                component=self.component, outcome="unchanged"
            ).inc()
            self._touch_mask()
            return False
        allow = self._valid.copy()
        allow[idx] = False
        if self.mesh is None:
            dev = self._device
            self._allow_dev = (
                jax.device_put(allow, dev) if dev is not None
                else jax.device_put(allow)
            )
        else:
            self._allow_dev = jax.device_put(
                allow, NamedSharding(self.mesh, P(self._axis))
            )
        self._excluded_ids = idx
        _, m_bytes, m_members = _ledger.device_footprint(self._allow_dev)
        self._ledger_mask.set(m_bytes, members=m_members)
        _m_mask_refresh().labels(
            component=self.component, outcome="refreshed"
        ).inc()
        self._touch_mask()
        return True

    def _touch_mask(self) -> None:
        self._mask_stamp = time.monotonic()
        _m_mask_age().labels(component=self.component).set(0.0)

    @property
    def mask_age_s(self) -> float:
        return time.monotonic() - self._mask_stamp

    @property
    def resident_bytes(self) -> int:
        return int(
            self._y_dev.nbytes + self._rn_dev.nbytes + self._allow_dev.nbytes
        )

    # --- the hot path ---

    def _assemble_idx(
        self, lists, b_pad: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Per-query id lists -> a sentinel-padded [b_pad, W] int32 block
        (W the next power of two, so executables bucket O(log) widths)
        plus the has-list flag vector. The sentinel is n_pad: out of
        range on every shard and on the single device, so the mask
        scatter drops it."""
        has = np.zeros(b_pad, bool)
        width = 1
        rows: List[np.ndarray] = []
        for a in lists:
            if a is None:
                rows.append(np.zeros(0, np.int64))
                continue
            a = np.asarray(a, np.int64)
            rows.append(a)
            width = max(width, len(a))
        width = pow2_at_least(width)
        out = np.full((b_pad, width), self._n_pad, np.int32)
        for r, a in enumerate(rows):
            if len(a):
                out[r, : len(a)] = a
            has[r] = lists[r] is not None
        return out, has

    def topn(
        self,
        query_rows: np.ndarray,
        n: int,
        *,
        exclude: Optional[Sequence] = None,
        include: Optional[Sequence] = None,
        positive_only: bool = False,
        normalize: bool = False,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Exact masked top-``n`` for a query batch.

        ``exclude``/``include`` are per-query dense item-index arrays
        (``None`` entries mean no list for that query; an ``include``
        entry restricts the query's candidates to exactly that set —
        an empty array means NO candidates, matching whitelist
        semantics). ``positive_only`` drops non-positive scores (the
        templates' ``scores > 0`` rule); ``normalize`` scores against
        L2-normalized candidates (the cosine/similar-items path).
        Returns (scores [B, n], item idx [B, n]); slots past a query's
        live-candidate count carry ``-inf`` — the k > live-candidates
        edge is the caller filtering those out.
        """
        if self._freed:
            raise RuntimeError(
                "ItemRetriever was freed (release_serving); the owner "
                "must null its reference before freeing"
            )
        q = np.atleast_2d(np.asarray(query_rows, np.float32))
        b = q.shape[0]
        if not (0 < n <= self.n_items):
            raise ValueError(
                f"n must be in [1, {self.n_items}], got {n}"
            )
        qp = pad_rows_pow2(q, 8)
        b_pad = qp.shape[0]
        excl, _ = self._assemble_idx(
            list(exclude or []) + [None] * (b_pad - b), b_pad
        )
        incl, has_incl = self._assemble_idx(
            list(include or []) + [None] * (b_pad - b), b_pad
        )
        _m_mask_age().labels(component=self.component).set(self.mask_age_s)
        _m_padding_waste().labels(site="retrieval_batch").set(
            (b_pad - b) / b_pad
        )
        if self.mesh is None:
            t0 = time.perf_counter()
            dev = self._device
            put = lambda a: (
                jax.device_put(a, dev) if dev is not None else jnp.asarray(a)
            )
            # executable-cache accounting: the fused program's jit cache
            # is keyed by shapes + statics; a NEW key here is a compile
            # (cold if it happens under a serving compile_site)
            exec_key = (
                self._n_pad, self.rank, b_pad,
                excl.shape[1], incl.shape[1],
                n, positive_only, normalize,
            )
            with _cc.track_compile("retrieval-fused", _FUSED_SEEN, exec_key):
                packed = _fused_topn_single(
                    put(qp), self._y_dev, self._rn_dev, self._allow_dev,
                    put(excl), put(incl), put(has_incl),
                    n, positive_only, normalize,
                )
            host = np.asarray(packed)[:b]
            _m_shard_seconds().observe(time.perf_counter() - t0)
            return unpack_topn(host, n)

        rep = self._rep_q
        q_dev = jax.device_put(qp, rep)
        excl_dev = jax.device_put(excl, rep)
        incl_dev = jax.device_put(incl, rep)
        has_dev = jax.device_put(has_incl, rep)
        n_local = min(n, self._n_pad // self._n_shards)
        stage1 = self._stage1(n_local, positive_only, normalize)
        # the shard-vs-merge timing split needs a host sync between the
        # two programs, which would serialize an otherwise back-to-back
        # dispatch on EVERY batch — so the split is SAMPLED (first
        # batch, then every _SPLIT_SAMPLE_EVERY-th); unsampled batches
        # run barrier-free and record nothing in these families
        self._batches += 1
        split = self._batches % _SPLIT_SAMPLE_EVERY == 1
        exec_key = (
            n_local, positive_only, normalize, b_pad,
            excl.shape[1], incl.shape[1],
        )
        t0 = time.perf_counter()
        with _cc.track_compile("retrieval-stage1", self._exec_seen, exec_key):
            cand = stage1(
                q_dev, self._y_dev, self._rn_dev, self._allow_dev,
                excl_dev, incl_dev, has_dev,
            )
        if split:
            jax.block_until_ready(cand)
            t1 = time.perf_counter()
            _m_shard_seconds().observe(t1 - t0)
        packed = _merge_candidates(cand, n, n_local, self._rep_out)
        host = np.asarray(packed)[:b]
        if split:
            _m_merge_seconds().observe(time.perf_counter() - t1)
            # sampled skew: the candidate buffer is already synced (the
            # split's block_until_ready), so the extra fetch costs one
            # host copy on 1/_SPLIT_SAMPLE_EVERY batches only
            self._record_skew(np.asarray(cand)[:b], host, n, n_local)
        return unpack_topn(host, n)

    def _record_skew(
        self, cand: np.ndarray, host: np.ndarray, n: int, n_local: int
    ) -> None:
        """Cross-shard imbalance from one sampled batch: live stage-1
        candidates per shard, and which shard each final top-n row came
        from. Uniform shapes make per-shard FLOPs equal, so imbalance —
        the thing that stretches the merge's critical path — shows up
        here, not in timers."""
        S = self._n_shards
        if S <= 1 or not len(cand):
            return
        arr = cand.reshape(cand.shape[0], S, 2, n_local)
        live = (arr[:, :, 0, :] > -np.inf).sum(axis=(0, 2)).astype(float)
        g = _m_shard_candidates()
        for s in range(S):
            g.labels(shard=str(s)).set(float(live[s]))
        if live.mean() > 0:
            _m_shard_skew().labels(kind="candidates").set(
                float(live.max() / live.mean())
            )
        idx = np.ascontiguousarray(host[:, n:]).view(np.int32)
        scores = host[:, :n]
        owners = idx[scores > -np.inf] // (self._n_pad // S)
        counts = np.bincount(owners, minlength=S).astype(float)
        if counts.mean() > 0:
            _m_shard_skew().labels(kind="results").set(
                float(counts.max() / counts.mean())
            )

    def _stage1(self, n_local: int, positive_only: bool, normalize: bool):
        key = (n_local, positive_only, normalize)
        fn = self._stage1_cache.get(key)
        if fn is None:
            kernel = functools.partial(
                _shard_topk_kernel,
                axis=self._axis, n_local=n_local,
                positive_only=positive_only, normalize=normalize,
            )
            axis = self._axis
            fn = jax.jit(
                shard_map(
                    kernel,
                    mesh=self.mesh,
                    in_specs=(
                        P(None, None),  # q: replicated
                        P(axis, None),  # Y: row-sharded
                        P(axis),        # rn
                        P(axis),        # allow
                        P(None, None),  # excl (global ids, replicated)
                        P(None, None),  # incl
                        P(None,),       # has_incl
                    ),
                    # per-shard candidate blocks concatenate along the
                    # candidate dim: the stage-1 output STAYS sharded
                    out_specs=P(None, axis),
                    check_rep=False,
                )
            )
            self._stage1_cache[key] = fn
        return fn

    def free(self) -> None:
        """Drop the device-resident buffers (factors, norms, mask) and
        the compiled stage cache. Owner contract (the engines'
        ``release_serving``): null the model's retriever reference FIRST
        and only call this after the last in-flight batch drained — a
        subsequent ``topn`` raises rather than computing on half state.
        The buffers' device memory is freed by refcount: a wedged
        straggler still holding them keeps them alive until it resolves,
        so nothing is ever freed underneath a running batch."""
        self._freed = True
        self._y_dev = None
        self._rn_dev = None
        self._allow_dev = None
        if self.mesh is not None:
            self._stage1_cache = {}
        _m_resident_bytes().labels(component=self.component).set(0.0)
        self._ledger_factors.close()
        self._ledger_mask.close()

    def warm(
        self,
        n: int = 16,
        max_batch: int = 128,
        flag_combos: Sequence[Tuple[bool, bool]] = ((True, False),),
        exclude_widths: Sequence[int] = (1, 16, 64),
    ) -> None:
        """Deploy-time compile of the padded-batch executables the
        serving path can hit (O(log max_batch) per flag combo x
        exclude width; see BaseAlgorithm.warm). ``flag_combos`` lists
        the (positive_only, normalize) pairs the engine serves with;
        ``exclude_widths`` the per-query exclusion-list widths to
        pre-trace — the id-list block pads to a power of two, so a
        query arriving with a blacklist/seen set is a DIFFERENT traced
        shape than a bare query, and without warming it the first such
        query would pay an XLA compile inside a live batch. 1/16/64
        cover bare queries and the common seen/blacklist sizes; rarer
        widths (and whitelists) still compile on first use."""
        n = min(n, self.n_items)
        k = self.rank
        for positive_only, normalize in flag_combos:
            for w in exclude_widths:
                excl_row = np.zeros(w, np.int64) if w > 1 else None
                b = 8
                while True:
                    self.topn(
                        np.zeros((b, k), np.float32), n,
                        exclude=(
                            [excl_row] * b if excl_row is not None else None
                        ),
                        positive_only=positive_only, normalize=normalize,
                    )
                    if b >= max_batch:
                        break
                    b *= 2


def naive_topn_reference(
    item_factors: np.ndarray,
    query_rows: np.ndarray,
    n: int,
    *,
    exclude: Optional[Sequence] = None,
    include: Optional[Sequence] = None,
    positive_only: bool = False,
    normalize: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """The naive path the sharded retriever must match id-for-id: ONE
    full [B, N] score matrix (device matmul — the same contraction the
    sharded kernel runs per slice), then a HOST post-filter and sort per
    query. This is both the parity oracle for tests and the
    ``retrieval_vs_naive_speedup`` denominator in the saturation bench —
    it is what serving did before round 12."""
    Y = np.asarray(item_factors, np.float32)
    q = np.atleast_2d(np.asarray(query_rows, np.float32))
    scores = np.asarray(
        jnp.dot(jnp.asarray(q), jnp.asarray(Y).T,
                preferred_element_type=jnp.float32)
    ).copy()
    if normalize:
        scores *= _reciprocal_norms(Y)[None, :]
    b, N = scores.shape
    out_s = np.full((b, n), -np.inf, np.float32)
    out_i = np.zeros((b, n), np.int32)
    for r in range(b):
        row = scores[r]
        allow = np.ones(N, bool)
        inc_list = include[r] if include is not None else None
        if inc_list is not None:
            wl = np.zeros(N, bool)
            wl[np.asarray(inc_list, np.int64)] = True
            allow &= wl
        exc_list = exclude[r] if exclude is not None else None
        if exc_list is not None and len(exc_list):
            allow[np.asarray(exc_list, np.int64)] = False
        if positive_only:
            allow &= row > 0
        masked = np.where(allow, row, -np.inf)
        order = np.argsort(-masked, kind="stable")[:n]
        out_s[r, : len(order)] = masked[order]
        out_i[r, : len(order)] = order
    return out_s, out_i
