"""TPU compute kernels: ALS, NaiveBayes reductions, cosine top-N.

This package is the in-tree replacement for Spark MLlib's role in the
reference (SURVEY.md §0): the numerical algorithms engine templates call.
Everything here is jit/shard_map-compatible JAX with static shapes —
host-side preprocessing produces padded, fixed-width segment arrays;
device code is
pure functional XLA programs over a `jax.sharding.Mesh`.
"""
