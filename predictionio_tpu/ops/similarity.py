"""Cosine-similarity scoring over factor matrices.

The kernel behind the similarproduct template (reference
examples/scala-parallel-similarproduct/multi/src/main/scala/
ALSAlgorithm.scala predict: per-candidate ``sum over query items of
cosine(queryFactor, candidateFactor)``, computed there as an RDD
mapValues over every product). Here the factor matrix is L2-normalized
once at model build, so a whole query batch scores as ONE [Q, k] x [k, N]
MXU matmul summed over the query axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def normalize_rows(factors: np.ndarray) -> np.ndarray:
    """L2-normalize rows; zero rows stay zero (cosine with a zero vector
    is 0 in the reference's cosine helper)."""
    f = np.asarray(factors, np.float32)
    norms = np.linalg.norm(f, axis=1, keepdims=True)
    return np.where(norms > 0, f / np.where(norms == 0, 1, norms), 0.0)


@jax.jit
def _cosine_sum(query_normed, all_normed):
    # [Q, k] x [k, N] -> sum over Q -> [N]
    sims = jnp.dot(query_normed, all_normed.T, preferred_element_type=jnp.float32)
    return sims.sum(axis=0)


class SimilarityScorer:
    """Device-resident normalized factors; each call ships only the query
    rows up and one score vector down."""

    def __init__(self, factors: np.ndarray):
        self.normed = normalize_rows(factors)
        self._dev = jax.device_put(jnp.asarray(self.normed))

    @property
    def n(self) -> int:
        return self.normed.shape[0]

    def cosine_sum(self, query_rows: np.ndarray) -> np.ndarray:
        """Sum of cosine similarities of every row of the matrix against
        the (already-normalized) query rows: [N] scores."""
        q = jnp.asarray(np.atleast_2d(np.asarray(query_rows, np.float32)))
        return np.asarray(_cosine_sum(q, self._dev))
