"""Cosine-similarity scoring over factor matrices.

The kernel behind the similarproduct template (reference
examples/scala-parallel-similarproduct/multi/src/main/scala/
ALSAlgorithm.scala predict: per-candidate ``sum over query items of
cosine(queryFactor, candidateFactor)``, computed there as an RDD
mapValues over every product). Here the factor matrix is L2-normalized
once at model build, so a whole query batch scores as ONE [Q, k] x [k, N]
MXU matmul summed over the query axis.

Multi-chip: with a ``mesh``, the [N, k] candidate matrix shards rows over
the mesh's data axis (the catalog is the big operand); the small query
block replicates, each device scores its candidate shard locally, and the
[N] score vector comes back row-sharded — no collective on the hot path.
This is the TPU analog of the reference scoring candidates with an RDD
mapValues over cluster partitions.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.parallel.mesh import shard_batch


def pow2_at_least(n: int, floor: int = 1) -> int:
    """Next power of two >= n (and >= floor) — THE serving bucketing
    rule (cosine-sum rows, ALS top-N batches, retrieval top-k and
    id-list widths), centralized so executables bucket identically
    everywhere and the rule can't drift."""
    return max(floor, 1 << (max(n, 1) - 1).bit_length())


def pad_rows_pow2(rows: np.ndarray, min_rows: int) -> np.ndarray:
    """Pad the leading axis with zero rows to the next power of two
    (>= min_rows), so executables bucket by O(log) widths instead of one
    per distinct size. Shared by the cosine-sum path here and the ALS
    serving top-N (ops/als.py) so the bucketing rule can't drift."""
    rows = np.asarray(rows, np.float32)
    n = rows.shape[0]
    n_pad = pow2_at_least(n, min_rows)
    if n_pad == n:
        return rows
    return np.concatenate(
        [rows, np.zeros((n_pad - n, rows.shape[1]), np.float32)]
    )


def normalize_rows(factors: np.ndarray) -> np.ndarray:
    """L2-normalize rows; zero rows stay zero (cosine with a zero vector
    is 0 in the reference's cosine helper)."""
    f = np.asarray(factors, np.float32)
    norms = np.linalg.norm(f, axis=1, keepdims=True)
    return np.where(norms > 0, f / np.where(norms == 0, 1, norms), 0.0)


@jax.jit
def _cosine_sum(query_normed, all_normed):
    # [Q, k] x [k, N] -> sum over Q -> [N]
    sims = jnp.dot(query_normed, all_normed.T, preferred_element_type=jnp.float32)
    return sims.sum(axis=0)


class SimilarityScorer:
    """Device-resident normalized factors; each call ships only the query
    rows up and one score vector down.

    With a ``mesh``, the candidate matrix is row-sharded over the mesh's
    ``axis`` (zero-padded so rows divide the axis size — zero rows score
    cosine 0 and are sliced off the result)."""

    def __init__(
        self,
        factors: np.ndarray,
        mesh: Optional[Mesh] = None,
        axis: str = "data",
    ):
        self.normed = normalize_rows(factors)
        if mesh is not None and mesh.shape[axis] == 1:
            mesh = None
        self.mesh = mesh
        if mesh is None:
            self._dev = jax.device_put(jnp.asarray(self.normed))
        else:
            self._dev, _ = shard_batch(mesh, self.normed, axis)
        # HBM residency ledger: released by refcount (no explicit free
        # path), so the anchor finalizer is the close
        from predictionio_tpu.utils import device_ledger as _ledger

        label, nbytes, members = _ledger.device_footprint(self._dev)
        self._ledger = _ledger.get_ledger().register(
            component="similarity-factors",
            nbytes=nbytes,
            device=label,
            anchor=self,
            members=members,
        )

    @property
    def n(self) -> int:
        return self.normed.shape[0]

    def cosine_sum(self, query_rows: np.ndarray) -> np.ndarray:
        """Sum of cosine similarities of every row of the matrix against
        the (already-normalized) query rows: [N] scores.

        The query axis pads to a power of two (min 4) with zero rows —
        a zero row contributes cosine 0 to every sum, so results are
        unchanged while serving workloads with varying query-item counts
        share O(log max_q) compiled executables instead of one per
        distinct count (a cold compile on live traffic costs seconds)."""
        q = pad_rows_pow2(np.atleast_2d(query_rows), 4)
        if self.mesh is not None:
            q_dev = jax.device_put(q, NamedSharding(self.mesh, P(None, None)))
        else:
            q_dev = jnp.asarray(q)
        return np.asarray(_cosine_sum(q_dev, self._dev))[: self.n]

    def warm(self, max_q: int = 16) -> None:
        """Compile every padded-query-width executable a query of up to
        ``max_q`` items can hit — including the bucket a non-power-of-two
        max_q pads INTO (deploy-time warm-up; see BaseAlgorithm.warm).
        Routes through ``cosine_sum`` so the warmed executables carry the
        SAME input shardings serving traffic will present (a direct
        `_cosine_sum` call with an uncommitted query would warm a
        different jit cache entry on mesh-backed scorers)."""
        k = self.normed.shape[1]
        q = 4
        while True:
            self.cosine_sum(np.zeros((q, k), np.float32))
            if q >= max_q:
                break
            q *= 2
