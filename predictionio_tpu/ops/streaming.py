"""Streaming store→device ALS training pipeline.

Replaces the materialize-everything-then-train path for event-store
training with a chunked pipeline in which the serial phase chain of the
monolithic path — store scan, host pack, host→device transfer, XLA
compile — overlaps:

- the store scan (``data.store.PEventStore.stream_columns``) runs on a
  background thread, pushing fixed-size columnar batches through a
  BOUNDED queue;
- each batch is folded into incremental pack state (dense per-side row
  ids, per-row observation counts, a per-batch stable presort by user)
  while the scan of the next batch is still running;
- the moment the scan ends, bucket geometry is known and the iteration
  executable starts compiling on its own thread
  (``als.start_compile_async``), hiding XLA compile under the remaining
  host work;
- the presorted batches merge into the final :class:`als.HostWire` with
  one vectorized counting-sort scatter (no global 20M-element argsort on
  the critical path — the per-batch sorts already happened under the
  scan);
- the wire ships with chunked, double-buffered async ``device_put``:
  transfer of chunk k+1 overlaps the device-side nibble unpack of chunk
  k, and factor-state placement overlaps both.

This is the shape of ALX's pre-bucketed TPU input pipeline
(PAPERS.md — arXiv:2112.02194) and of the GPU MF literature's
transfer/compute overlap (arXiv:1603.03820), applied to the event-store
flagship flow. The wire produced here is byte-identical to the
monolithic ``als.build_host_wire`` output for the same scan, so the
device program — and the trained factors — match the monolithic path.

A process-global **pack-artifact cache** keyed by the store's cheap
state fingerprint (``LEvents.store_fingerprint``: event counts, max
ids/times, tombstone populations) makes a repeat train over an
unchanged store skip scan+pack entirely: the cached wire goes straight
to device. The fingerprint is read BEFORE the scan starts, so an entry
can only ever be labeled with a state at least as old as its data — a
write racing the scan makes the next lookup miss, never hit stale. The
producing DAO is held by weakref and compared by identity, so a
different storage universe (or a GC'd-and-reused object address) can
never satisfy a lookup.
"""

from __future__ import annotations

import dataclasses
import logging
import queue as _queue
import threading
import time
import weakref
from collections import OrderedDict
from typing import Optional, Tuple

import numpy as np

from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.ops import als as _als

logger = logging.getLogger(__name__)


# --- pack-artifact cache ---


@dataclasses.dataclass
class _PackEntry:
    scope_ref: "weakref.ref"  # the producing events DAO, by identity
    fingerprint: tuple  # store state the wire was packed from
    wire: "_als.HostWire"
    user_index: BiMap
    item_index: BiMap


_PACK_CACHE: "OrderedDict[tuple, _PackEntry]" = OrderedDict()
_PACK_CACHE_LOCK = threading.Lock()
# wires are ~50 MB at ML-20M scale; a small LRU covers the retrain and
# warm-bench cases without growing with app count
PACK_CACHE_MAX_ENTRIES = 4


def pack_cache_clear() -> None:
    with _PACK_CACHE_LOCK:
        _PACK_CACHE.clear()


def _cache_key(stream, config) -> Optional[tuple]:
    # the wire depends on config only through its pack geometry knobs
    if (
        stream.cache_key is None
        or stream.cache_scope is None
        or stream.fingerprint is None
    ):
        return None
    return (stream.cache_key, config.segment_length, config.chunk_slots)


def _cache_get(stream, config) -> Optional[_PackEntry]:
    key = _cache_key(stream, config)
    if key is None:
        return None
    with _PACK_CACHE_LOCK:
        entry = _PACK_CACHE.get(key)
        if entry is None:
            return None
        # identity, not id(): the weakref keeps a dead DAO's entry from
        # ever matching a new object that reused its address
        if (
            entry.scope_ref() is not stream.cache_scope
            or entry.fingerprint != stream.fingerprint
        ):
            return None
        _PACK_CACHE.move_to_end(key)
        return entry


def _cache_put(stream, config, wire, user_index, item_index) -> None:
    key = _cache_key(stream, config)
    if key is None:
        return
    try:
        ref = weakref.ref(stream.cache_scope)
    except TypeError:  # unweakrefable DAO: no caching
        return
    with _PACK_CACHE_LOCK:
        _PACK_CACHE[key] = _PackEntry(
            ref, stream.fingerprint, wire, user_index, item_index
        )
        _PACK_CACHE.move_to_end(key)
        while len(_PACK_CACHE) > PACK_CACHE_MAX_ENTRIES:
            _PACK_CACHE.popitem(last=False)


# --- incremental pack state ---


class _SideCodes:
    """Dense per-side row ids over the stream's SHARED code space.

    The stream's batches carry codes from one table-global dictionary
    (users and items mixed); each solve side needs its own dense 0..n-1
    id space. Dense ids are assigned in first-appearance order as
    batches fold in, and the shared code of each dense id is kept so the
    stream's post-scan ``names`` array resolves dense ids to id strings.
    """

    def __init__(self):
        self._dense_of = np.full(1024, -1, np.int64)
        self._code_chunks = []
        self.n = 0

    def fold(self, codes: np.ndarray) -> np.ndarray:
        codes = np.asarray(codes)
        if not len(codes):
            return np.empty(0, np.int32)
        hi = int(codes.max()) + 1
        if hi > len(self._dense_of):
            grown = np.full(max(hi, 2 * len(self._dense_of)), -1, np.int64)
            grown[: len(self._dense_of)] = self._dense_of
            self._dense_of = grown
        dense = self._dense_of[codes]
        miss = dense < 0
        if miss.any():
            new_codes = codes[miss]
            uniq, first = np.unique(new_codes, return_index=True)
            uniq = uniq[np.argsort(first, kind="stable")]  # appearance order
            self._dense_of[uniq] = np.arange(
                self.n, self.n + len(uniq), dtype=np.int64
            )
            self._code_chunks.append(uniq)
            self.n += len(uniq)
            dense = self._dense_of[codes]
        return dense.astype(np.int32)

    def codes(self) -> np.ndarray:
        """Shared code of each dense id (dense-id order)."""
        if not self._code_chunks:
            return np.empty(0, np.int64)
        return np.concatenate(self._code_chunks)


def _grow_add(acc: np.ndarray, add: np.ndarray) -> np.ndarray:
    if len(add) > len(acc):
        grown = np.zeros(len(add), np.int64)
        grown[: len(acc)] = acc
        acc = grown
    acc[: len(add)] += add
    return acc


def _scan_worker(stream, q: "_queue.Queue", box: dict) -> None:
    """Drive the store scan, pushing batches through the bounded queue.
    Runs the generator ON THIS THREAD (the sqlite backend reads through
    per-thread WAL snapshot connections, so the scan never contends with
    the consumer); resolves ``stream.names`` here too, since it is only
    valid after exhaustion."""
    busy = 0.0
    try:
        it = iter(stream)
        while True:
            t0 = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                break
            busy += time.perf_counter() - t0
            q.put(batch)
        t0 = time.perf_counter()
        box["names"] = stream.names
        busy += time.perf_counter() - t0
    except BaseException as e:
        box["error"] = e
    finally:
        box["scan_s"] = busy
        box["done_at"] = time.perf_counter()
        q.put(None)


def _scan_and_pack(stream, config, timings: dict, queue_batches: int):
    """Consume a ColumnarStream into a HostWire + id indexes, folding
    each batch while the scan of the next runs on the producer thread.

    Returns ``(wire, user_index, item_index, compile_wait)`` or None for
    an empty scan (callers fall back to the materialized path, whose
    sanity check owns the user-facing error)."""
    q: "_queue.Queue" = _queue.Queue(maxsize=max(1, queue_batches))
    box: dict = {}
    th = threading.Thread(
        target=_scan_worker, args=(stream, q, box),
        daemon=True, name="als-stream-scan",
    )
    th.start()

    uspace, ispace = _SideCodes(), _SideCodes()
    counts_u = np.zeros(0, np.int64)
    counts_i = np.zeros(0, np.int64)
    batches = []
    n = 0
    fold_busy = 0.0
    while True:
        batch = q.get()
        if batch is None:
            break
        e_codes, t_codes, values = batch
        t0 = time.perf_counter()
        u = uspace.fold(e_codes)
        i = ispace.fold(t_codes)
        # stable presort by user NOW, under the scan of the next batch;
        # the merge below then only scatters — no global argsort on the
        # exposed critical path
        order = np.argsort(u, kind="stable")
        u, i = u[order], i[order]
        v = np.asarray(values, np.float32)[order]
        counts_u = _grow_add(counts_u, np.bincount(u, minlength=uspace.n))
        counts_i = _grow_add(counts_i, np.bincount(i, minlength=ispace.n))
        batches.append((u, i, v))
        n += len(v)
        fold_busy += time.perf_counter() - t0
    th.join()
    if "error" in box:
        raise box["error"]
    timings["scan_s"] = box.get("scan_s", 0.0)
    timings["fold_s"] = fold_busy
    if n == 0:
        return None
    t_scan_done = box["done_at"]

    # Final dense ids relabel the provisional (first-appearance) ids
    # into SORTED-NAME order — the order every monolithic scan
    # (presence-bitmap page remap, np.unique concat, BiMap.string_int)
    # assigns — so the wire below is byte-identical to the monolithic
    # packer's and the trained factors match it exactly, not just up to
    # a row permutation. The relabeling is catalog-sized, not
    # event-sized.
    names = box["names"]
    u_names = names[uspace.codes()]
    i_names = names[ispace.codes()]
    n_users, n_items = uspace.n, ispace.n
    perm_u = np.argsort(u_names)
    perm_i = np.argsort(i_names)
    remap_u = np.empty(n_users, np.int32)
    remap_u[perm_u] = np.arange(n_users, dtype=np.int32)
    remap_i = np.empty(n_items, np.int32)
    remap_i[perm_i] = np.arange(n_items, dtype=np.int32)
    counts_u32 = np.zeros(n_users, np.int64)
    counts_u32[: len(counts_u)] = counts_u
    counts_u32 = counts_u32[perm_u].astype(np.int32)
    counts_i32 = np.zeros(n_items, np.int64)
    counts_i32[: len(counts_i)] = counts_i
    counts_i32 = counts_i32[perm_i].astype(np.int32)
    L_u = _als.auto_segment_length(
        None, n_users, config.segment_length, counts=counts_u32
    )
    L_i = _als.auto_segment_length(
        None, n_items, config.segment_length, counts=counts_i32
    )
    geo_u = _als._segment_geometry(
        counts_u32, n_users, L_u, 1, config.chunk_slots
    )
    geo_i = _als._segment_geometry(
        counts_i32, n_items, L_i, 1, config.chunk_slots
    )
    # geometry known: compile starts NOW, under merge+narrow+transfer
    compile_wait = _als.start_compile_async(
        n_users, n_items, geo_u, geo_i, L_u, L_i, config
    )

    # Counting-sort merge. Each batch is presorted by PROVISIONAL user
    # id; relabeling is injective, so equal-user runs stay contiguous
    # and the within-batch occurrence rank computed from the provisional
    # grouping is also the rank under final ids. Scattering batch b's
    # run of user u right after the runs batches 0..b-1 wrote
    # reproduces EXACTLY the stable global argsort of the monolithic
    # packer: per user, batches in scan order, original order within.
    pad = (_als._bucket_count(n) - n) if n else 1
    iw = np.full(n + pad, n_items, np.int32)  # padding -> sentinel id
    vw = np.zeros(n + pad, np.float32)
    cursor = geo_u.starts[:-1].copy()  # [n_users] int64 write heads
    for u, i, v in batches:
        m = len(u)
        if not m:
            continue
        idx = np.arange(m, dtype=np.int64)
        newgrp = np.empty(m, bool)
        newgrp[0] = True
        np.not_equal(u[1:], u[:-1], out=newgrp[1:])
        first = np.maximum.accumulate(np.where(newgrp, idx, 0))
        u_f = remap_u[u]
        pos = cursor[u_f] + (idx - first)
        iw[pos] = remap_i[i]
        vw[pos] = v
        cursor += np.bincount(u_f, minlength=n_users)
    batches.clear()

    wire = _als.finish_wire(
        iw, vw, n_users, n_items, L_u, L_i, geo_u, geo_i,
        counts_u32, counts_i32,
    )
    user_index = BiMap(
        {str(nm): j for j, nm in enumerate(u_names[perm_u])}
    )
    item_index = BiMap(
        {str(nm): j for j, nm in enumerate(i_names[perm_i])}
    )
    now = time.perf_counter()
    # exposed = the tail the scan could not hide: late folds + geometry
    # + merge + narrow/nibble + index build
    timings["pack_exposed_s"] = max(0.0, now - t_scan_done)
    timings["pack_s"] = fold_busy + timings["pack_exposed_s"]
    return wire, user_index, item_index, compile_wait


# --- transfer ---


def _ship_wire(wire: "_als.HostWire", n_chunks: int = 2) -> tuple:
    """Double-buffered wire transfer: the COO planes split into chunks
    whose async ``device_put``s pipeline, and each value chunk's
    device-side nibble unpack dispatches as soon as its bytes are
    enqueued — so transfer of chunk k+1 overlaps unpack of chunk k.
    Returns the ``(i_dev, v_dev, aux_dev)`` pre-shipped wire
    ``als.device_pack_from_wire`` consumes."""
    import jax
    import jax.numpy as jnp

    def parts(a: np.ndarray):
        if n_chunks <= 1 or len(a) < 2 * n_chunks:
            return [a]
        step = -(-len(a) // n_chunks)
        step += step % 2  # even boundary: value pairs stay byte-aligned
        return [a[s : s + step] for s in range(0, len(a), step)]

    dev_i = [jax.device_put(p) for p in parts(wire.iw)]
    dev_v = []
    for p in parts(wire.vw):
        d = jax.device_put(p)
        dev_v.append(_als._unpack_nibbles(d) if wire.nibble else d)
    i_dev = dev_i[0] if len(dev_i) == 1 else jnp.concatenate(dev_i)
    v_dev = dev_v[0] if len(dev_v) == 1 else jnp.concatenate(dev_v)
    aux_dev = jax.device_put(wire.aux)  # enqueued last: fences the queue
    return i_dev, v_dev, aux_dev


# --- the pipeline entry ---


@dataclasses.dataclass
class StreamTrainResult:
    arrays: "_als.ALSModelArrays"
    user_index: BiMap
    item_index: BiMap
    timings: dict


def _attribute_phases(timer, timings: dict) -> None:
    """Record the pipeline's sub-phases on the workflow PhaseTimer,
    marking the ones that ran UNDER another phase as overlapped so the
    run summary's wall-clock accounting stays honest."""
    add = getattr(timer, "add", None)
    if add is None:
        return
    for name, key, overlapped in (
        ("stream:scan", "scan_s", True),
        ("stream:fold", "fold_s", True),
        ("stream:pack-exposed", "pack_exposed_s", False),
        ("stream:device-put-exposed", "device_put_exposed_s", False),
        ("stream:compile", "compile_s", True),
        ("stream:compile-exposed", "compile_exposed_s", False),
        ("stream:device-loop", "device_loop_s", False),
    ):
        if timings.get(key):
            add(name, timings[key], overlapped=overlapped)


def train_als_streaming(
    stream,
    config: "_als.ALSConfig",
    *,
    timings: Optional[dict] = None,
    timer=None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 5,
    profile_dir: Optional[str] = None,
    queue_batches: int = 4,
    ship_chunks: int = 2,
    cache: bool = True,
) -> Optional[StreamTrainResult]:
    """Train ALS from a ``ColumnarStream`` through the overlapped
    pipeline (module docstring). Returns None when ``stream`` is None or
    the scan is empty — callers fall back to the materialized
    ``train_als`` path and its error reporting.

    ``timings`` gains the pipeline's phase split: ``scan_s``/``fold_s``/
    ``compile_s`` (busy, overlapped), ``pack_exposed_s``/
    ``device_put_exposed_s``/``compile_exposed_s`` (critical-path wall),
    ``pack_cache`` ("hit"/"miss"/"off"), plus the usual
    ``device_loop_s``/``padded_slots``/``wire_mb`` from the shared
    training tail.
    """
    if stream is None:
        return None
    timings = {} if timings is None else timings
    t_start = time.perf_counter()

    entry = _cache_get(stream, config) if cache else None
    if entry is not None:
        timings["pack_cache"] = "hit"
        timings["scan_s"] = timings["fold_s"] = 0.0
        timings["pack_exposed_s"] = 0.0
        wire = entry.wire
        user_index, item_index = entry.user_index, entry.item_index
        compile_wait = _als.start_compile_async(
            wire.n_users, wire.n_items, wire.geo_u, wire.geo_i,
            wire.L_u, wire.L_i, config,
        )
        logger.info(
            "streaming ALS: pack cache HIT (%d users, %d items, %.1f MB "
            "wire) — skipping scan+pack", wire.n_users, wire.n_items,
            wire.wire_mb,
        )
    else:
        timings["pack_cache"] = "miss" if cache else "off"
        packed = _scan_and_pack(stream, config, timings, queue_batches)
        if packed is None:
            return None
        wire, user_index, item_index, compile_wait = packed
        if cache:
            _cache_put(stream, config, wire, user_index, item_index)

    # ship (async) first, then factor-state init: the RNG + small
    # factor/regularizer puts run while the wire chunks are in flight
    device_wire = _ship_wire(wire, n_chunks=ship_chunks)
    factor_state = _als.init_factor_state_single(
        wire.counts_u, wire.counts_i, wire.n_users, wire.n_items, config
    )
    t0 = time.perf_counter()
    # aux was enqueued last: fetching it (small) fences the serialized
    # transfer queue behind the COO chunks; the 1-element fence then
    # waits out the concat/unpack tail
    _als._sync_fetch(device_wire[2])
    _als._fence((device_wire[0], device_wire[1]))
    timings["device_put_exposed_s"] = time.perf_counter() - t0

    arrays = _als.train_from_wire(
        wire, config,
        device_wire=device_wire,
        timings=timings,
        checkpoint_dir=checkpoint_dir,
        checkpoint_every=checkpoint_every,
        profile_dir=profile_dir,
        compile_wait=compile_wait,
        factor_state=factor_state,
    )
    timings["stream_wall_s"] = time.perf_counter() - t_start
    if timer is not None:
        _attribute_phases(timer, timings)
    return StreamTrainResult(
        arrays=arrays, user_index=user_index, item_index=item_index,
        timings=timings,
    )
