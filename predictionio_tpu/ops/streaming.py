"""Streaming store→device ALS training pipeline.

Replaces the materialize-everything-then-train path for event-store
training with a chunked pipeline in which the serial phase chain of the
monolithic path — store scan, host pack, host→device transfer, XLA
compile — overlaps:

- the store scan (``data.store.PEventStore.stream_columns``) runs on a
  background thread, pushing fixed-size columnar batches through a
  BOUNDED queue;
- each batch is folded into incremental pack state (dense per-side row
  ids, per-row observation counts, a per-batch stable presort by user)
  while the scan of the next batch is still running;
- the moment the scan ends, bucket geometry is known and the iteration
  executable starts compiling on its own thread
  (``als.start_compile_async``), hiding XLA compile under the remaining
  host work;
- the presorted batches merge into the final :class:`als.HostWire` with
  one vectorized counting-sort scatter (no global 20M-element argsort on
  the critical path — the per-batch sorts already happened under the
  scan);
- the wire ships with chunked, double-buffered async ``device_put``:
  transfer of chunk k+1 overlaps the device-side nibble unpack of chunk
  k, and factor-state placement overlaps both.

This is the shape of ALX's pre-bucketed TPU input pipeline
(PAPERS.md — arXiv:2112.02194) and of the GPU MF literature's
transfer/compute overlap (arXiv:1603.03820), applied to the event-store
flagship flow. The wire produced here is byte-identical to the
monolithic ``als.build_host_wire`` output for the same scan, so the
device program — and the trained factors — match the monolithic path.

A process-global **pack-artifact cache** keyed by the store's cheap
state fingerprint (``LEvents.store_fingerprint``: event counts, max
ids/times, tombstone populations) makes a repeat train over an
unchanged store skip scan+pack entirely: the cached wire goes straight
to device. The fingerprint is read BEFORE the scan starts, so an entry
can only ever be labeled with a state at least as old as its data — a
write racing the scan makes the next lookup miss, never hit stale. The
producing DAO is held by weakref and compared by identity, so a
different storage universe (or a GC'd-and-reused object address) can
never satisfy a lookup.
"""

from __future__ import annotations

import dataclasses
import logging
import queue as _queue
import threading
import time
import weakref
from collections import OrderedDict
from typing import Optional, Tuple

import numpy as np

from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.ops import als as _als

logger = logging.getLogger(__name__)


# --- pack-artifact cache ---


@dataclasses.dataclass
class _PackEntry:
    scope_ref: "weakref.ref"  # the producing events DAO, by identity
    fingerprint: tuple  # store state the wire was packed from
    wire: "_als.HostWire"
    user_index: BiMap
    item_index: BiMap
    # --- delta-fold state (round 9): a pack entry IS the foldable
    # checkpoint — the cached wire losslessly inverts to the old COO
    # (als.wire_coo), the cursor says which store prefix it covers, and
    # the trained factors seed the next round's warm start. No extra
    # event-sized buffers beyond the wire the cache already held.
    cursor: Optional[tuple] = None  # storage delta cursor, None: no delta
    arrays: Optional["_als.ALSModelArrays"] = None  # factors of this wire
    # HBM residency ledger entry (device="host": cached wires are host
    # RAM, but they are long-lived residency the capacity view must see)
    ledger: Optional[object] = None
    # device-resident arm (round 17): when set, the wire's COO planes +
    # factor state live in HBM under this handle and ``wire`` is the
    # STRIPPED metadata shell (wire.stripped) — delta rounds scatter
    # onto the resident buffers instead of re-shipping the store
    resident: Optional["ResidentPack"] = None

    def resident_bytes(self) -> int:
        wire = self.wire
        total = (
            wire.iw.nbytes
            + wire.vw.nbytes
            + wire.counts_u.nbytes
            + wire.counts_i.nbytes
            + sum(int(a.nbytes) for a in wire.aux.values())
        )
        if self.arrays is not None:
            total += (
                self.arrays.user_factors.nbytes
                + self.arrays.item_factors.nbytes
            )
        return int(total)


_PACK_CACHE: "OrderedDict[tuple, _PackEntry]" = OrderedDict()
_PACK_CACHE_LOCK = threading.Lock()
# wires are ~50 MB at ML-20M scale; a small LRU covers the retrain and
# warm-bench cases without growing with app count
PACK_CACHE_MAX_ENTRIES = 4


def _cache_counter():
    """The registry family behind the hit/miss/fold counters — one
    ``pio_pack_cache_total{outcome=...}`` counter per outcome, visible
    in every server's /metrics, not just the PhaseTimer text summary."""
    from predictionio_tpu.utils import metrics as _metrics

    return _metrics.get_registry().counter(
        "pio_pack_cache_total",
        "Pack-artifact cache lookups by outcome (hit/miss/fold)",
        labels=("outcome",),
    )


def pack_cache_clear() -> None:
    """Drop every cached wire AND its cursor-keyed fold state (the
    delta-training checkpoint rides in the same entry), and reset the
    hit/miss/fold counters."""
    with _PACK_CACHE_LOCK:
        evicted = list(_PACK_CACHE.values())
        _PACK_CACHE.clear()
    for entry in evicted:
        _release_resident(entry)
        if entry.ledger is not None:
            entry.ledger.close()
    _cache_counter().reset()


def pack_cache_stats() -> dict:
    """Lifetime {'hit', 'miss', 'fold'} counters (reset by
    pack_cache_clear), read from the metrics registry."""
    c = _cache_counter()
    return {
        k: int(c.labels(outcome=k).value) for k in ("hit", "miss", "fold")
    }


def _stat_bump(kind: str) -> None:
    _cache_counter().labels(outcome=kind).inc()


def _cache_key(stream, config) -> Optional[tuple]:
    # the wire depends on config only through its pack geometry knobs
    if (
        stream.cache_key is None
        or stream.cache_scope is None
        or stream.fingerprint is None
    ):
        return None
    return (stream.cache_key, config.segment_length, config.chunk_slots)


def _cache_lookup(stream, config, any_fingerprint: bool):
    key = _cache_key(stream, config)
    if key is None:
        return None
    with _PACK_CACHE_LOCK:
        entry = _PACK_CACHE.get(key)
        if entry is None:
            return None
        # identity, not id(): the weakref keeps a dead DAO's entry from
        # ever matching a new object that reused its address
        if entry.scope_ref() is not stream.cache_scope:
            return None
        if not any_fingerprint and entry.fingerprint != stream.fingerprint:
            return None
        _PACK_CACHE.move_to_end(key)
        return entry


def _cache_get(stream, config) -> Optional[_PackEntry]:
    """Exact-state lookup: same DAO identity AND same fingerprint."""
    return _cache_lookup(stream, config, any_fingerprint=False)


def _cache_get_foldable(stream, config) -> Optional[_PackEntry]:
    """Stale-state lookup for the delta fold: same key and DAO identity,
    fingerprint MOVED (the exact-match path already missed), and the
    entry carries a cursor to scan the delta from."""
    entry = _cache_lookup(stream, config, any_fingerprint=True)
    if entry is None or entry.cursor is None:
        return None
    return entry


def _cache_put(
    stream, config, wire, user_index, item_index,
    fingerprint=None, cursor=None,
) -> Optional[_PackEntry]:
    key = _cache_key(stream, config)
    if key is None:
        return None
    try:
        ref = weakref.ref(stream.cache_scope)
    except TypeError:  # unweakrefable DAO: no caching
        return None
    entry = _PackEntry(
        ref,
        stream.fingerprint if fingerprint is None else fingerprint,
        wire, user_index, item_index, cursor=cursor,
    )
    from predictionio_tpu.utils import device_ledger as _ledger

    entry.ledger = _ledger.get_ledger().register(
        component="pack-cache",
        nbytes=entry.resident_bytes(),
        device=_ledger.HOST_DEVICE,
        anchor=entry,
    )
    evicted = []
    with _PACK_CACHE_LOCK:
        displaced = _PACK_CACHE.pop(key, None)
        if displaced is not None:
            evicted.append(displaced)
        _PACK_CACHE[key] = entry
        while len(_PACK_CACHE) > PACK_CACHE_MAX_ENTRIES:
            evicted.append(_PACK_CACHE.popitem(last=False)[1])
    for old in evicted:
        _release_resident(old)
        if old.ledger is not None:
            old.ledger.close()
    return entry


# --- device-resident pack (round 17) ---
#
# ALX keeps factor and rating state resident on the accelerator between
# solve rounds and moves only what changed (PAPERS.md, arXiv:2112.02194).
# Here that means: after a full round ships the wire, the device copies
# of the COO planes, the CSR/segment-geometry offsets, and the trained
# factor slots PARK in HBM under a ResidentPack handle (registered in
# the device ledger's ``train-pack`` component, so retention is measured
# and leak-gated). The next delta round then computes its id resolution
# and scatter bookkeeping on host (delta-sized) and applies ONE on-device
# scatter into the resident planes — nothing store-sized crosses the
# host→device link, converting round cost from O(store) to O(delta).
#
# The device arm is an optimization of the host fold, never a semantic
# fork: any condition it cannot scatter through — segment-geometry
# buckets grew, a row crossed a segment boundary, unseen ids arrived,
# the value tier or id dtype would change, the device/mesh changed, or
# the cursor invalidated — demotes the pack (device_get restores the
# byte-identical host wire) and takes the existing host fold. Packs
# release on continuous-loop shutdown, on fallback, and on cache
# eviction; ``pio_resident_pack_bytes`` must read zero afterwards.

_RESIDENT_ENABLED = False


def resident_training_enabled() -> bool:
    return _RESIDENT_ENABLED


def set_resident_training(enabled: bool) -> bool:
    """Toggle the device-resident incremental-pack arm (default OFF —
    batch trains gain nothing from parking state in HBM; the continuous
    loop turns it on for its lifetime). Returns the previous setting."""
    global _RESIDENT_ENABLED
    with _PACK_CACHE_LOCK:
        prev = _RESIDENT_ENABLED
        _RESIDENT_ENABLED = bool(enabled)
    return prev


def _resident_bytes_gauge():
    from predictionio_tpu.utils import metrics as _metrics

    return _metrics.get_registry().gauge(
        "pio_resident_pack_bytes",
        "Bytes of training-pack state (COO planes, segment geometry, "
        "factor slots) parked device-resident between continuous rounds",
        labels=("device",),
    )


def _resident_rounds_counter():
    from predictionio_tpu.utils import metrics as _metrics

    return _metrics.get_registry().counter(
        "pio_resident_pack_rounds_total",
        "Streaming train rounds by resident-pack outcome: scatter "
        "(delta applied on device), fallback (pack demoted to the host "
        "fold), cold (no pack involved)",
        labels=("outcome",),
    )


def _delta_upload_gauge():
    from predictionio_tpu.utils import metrics as _metrics

    return _metrics.get_registry().gauge(
        "pio_train_delta_upload_bytes",
        "Host→device bytes the last streaming train round uploaded "
        "(resident scatter rounds: delta rows + touched regularizer "
        "entries only; full rounds: the whole wire + factor state)",
    )


def _refresh_resident_gauge(device_label: str) -> None:
    from predictionio_tpu.utils import device_ledger as _ledger

    _resident_bytes_gauge().labels(device=device_label).set(
        float(
            _ledger.get_ledger().total_bytes(
                component="train-pack", device=device_label
            )
        )
    )


@dataclasses.dataclass
class ResidentPack:
    """The device-resident arm of one :class:`_PackEntry`: the wire's
    COO planes, CSR/segment-geometry offsets, and the trained factor
    state, all as device arrays. The paired entry's ``wire`` is stripped
    to its metadata shell while a pack is live; ``_reconstruct_wire``
    restores the byte-identical host wire from these buffers."""

    # wire planes: item ids (uint16|int32) and value codes (int8 decoded
    # from nibbles, or float32), both length plane_len, user-sorted
    i_plane: object
    v_plane: object
    # aux CSR offsets / segment bases (aux_pad'd int32 device copies)
    su: object
    bu: object
    si: object
    bi: object
    # flat segment-geometry arrays (int32): the per-round device pack
    # consumes these instead of re-uploading geo.seg_rows/geo.rem
    seg_rows_u: object
    rem_u: object
    seg_rows_i: object
    rem_i: object
    # padded factor slots (the fused loop's donated X/Y round-trip back
    # here after every round) + the non-donated lam/obs vectors
    X: object
    Y: object
    user_lam: object
    item_lam: object
    user_obs: object
    item_obs: object
    # host-side metadata
    device: object  # jax device the buffers live on (identity-compared)
    device_label: str
    plane_len: int  # bucketed COO length of the planes
    n: int  # real (unpadded) observation count
    v_lo: int  # min/max of the REAL int8 value codes (nibble recompute)
    v_hi: int
    config_key: tuple  # _als.config_train_key(...) the factor state matches
    ledger: object = None  # train-pack LedgerHandle
    valid: bool = True

    _ARRAY_FIELDS = (
        "i_plane", "v_plane", "su", "bu", "si", "bi",
        "seg_rows_u", "rem_u", "seg_rows_i", "rem_i",
        "X", "Y", "user_lam", "item_lam", "user_obs", "item_obs",
    )

    def device_arrays(self) -> list:
        return [
            a
            for a in (getattr(self, f) for f in self._ARRAY_FIELDS)
            if a is not None
        ]

    def device_bytes(self) -> int:
        return int(sum(int(a.nbytes) for a in self.device_arrays()))

    def release(self) -> None:
        """Close the ledger entry and drop every device reference
        (idempotent; the buffers free by refcount once training's own
        references go)."""
        self.valid = False
        if self.ledger is not None and not self.ledger.closed:
            self.ledger.close()
        for f in self._ARRAY_FIELDS:
            setattr(self, f, None)
        _refresh_resident_gauge(self.device_label)


def _release_resident(entry: _PackEntry) -> None:
    """Release an entry's device pack WITHOUT restoring the host wire —
    only for entries being discarded (eviction, cache clear)."""
    pack = entry.resident
    if pack is None:
        return
    entry.resident = None
    pack.release()


def _reconstruct_wire(entry: _PackEntry) -> "_als.HostWire":
    """The full host wire of a resident entry, rebuilt byte-identically
    from the device planes (every device copy is an exact integer image
    of the host plane it replaced) and the retained geometry."""
    meta = entry.wire
    if not meta.stripped:
        return meta
    import jax

    pack = entry.resident
    i_host = np.asarray(jax.device_get(pack.i_plane))
    v_host = np.asarray(jax.device_get(pack.v_plane))
    vw = _als._pack_nibbles_host(v_host) if meta.nibble else v_host
    aux = {
        "su": _als.aux_pad(meta.geo_u.starts.astype(np.int32)),
        "bu": _als.aux_pad(meta.geo_u.seg_base.astype(np.int32)),
        "si": _als.aux_pad(meta.geo_i.starts.astype(np.int32)),
        "bi": _als.aux_pad(meta.geo_i.seg_base.astype(np.int32)),
    }
    return dataclasses.replace(
        meta, iw=i_host, vw=vw, aux=aux, stripped=False
    )


def _demote_resident(entry: _PackEntry) -> None:
    """Fallback-to-host: restore the entry's full host wire from the
    device planes, then release the pack (train-pack ledger → 0). The
    entry stays a valid host-fold checkpoint."""
    if entry.resident is None:
        return
    restored = _reconstruct_wire(entry)
    with _PACK_CACHE_LOCK:
        entry.wire = restored
    _release_resident(entry)
    if entry.ledger is not None and not entry.ledger.closed:
        entry.ledger.set(entry.resident_bytes())


def release_resident_packs() -> int:
    """Demote every cached entry's device-resident pack back to its
    host wire — continuous-loop shutdown and promotion handoff call
    this so the ``train-pack`` ledger reads zero afterwards. Returns
    the number of packs released."""
    with _PACK_CACHE_LOCK:
        entries = list(_PACK_CACHE.values())
    released = 0
    for entry in entries:
        if entry.resident is not None:
            _demote_resident(entry)
            released += 1
    return released


def _resident_usable(pack: Optional[ResidentPack]) -> bool:
    """A pack is only reusable on the device that owns its buffers —
    a backend/mesh change between rounds demotes instead."""
    if pack is None or not pack.valid or pack.i_plane is None:
        return False
    import jax

    return jax.devices()[0] is pack.device


def _resolve_existing(codes, names_arr, index: BiMap):
    """Resolve delta codes (the delta stream's shared code space) to
    the cached side's EXISTING dense ids. Returns None when any name is
    unseen — the resident scatter cannot grow a side's id space (a new
    id reshuffles the sorted-name relabel), so the caller falls back."""
    codes = np.asarray(codes, np.int64)
    if not len(codes):
        return codes
    uniq = np.unique(codes)
    lut = np.zeros(int(uniq[-1]) + 1, np.int64)
    names = np.asarray(names_arr)
    for c in uniq:
        dense = index.get(str(names[int(c)]))
        if dense is None:
            return None
        lut[int(c)] = dense
    return lut[codes]


def _establish_resident(
    entry: _PackEntry, wire, device_wire, factor_state, fs_out, config
) -> Optional[ResidentPack]:
    """Park a just-trained round's device state under a ResidentPack:
    the shipped planes/aux keep living in HBM, the geometry arrays the
    per-round device pack needs are placed once, and the fused loop's
    final X/Y slots (``fs_out``) carry the trained factors without ever
    re-crossing the link. The entry's host wire is then stripped to its
    metadata shell — the redundant host plane copy frees (satellite:
    the ``pack-cache`` host ledger entry shrinks accordingly)."""
    X, Y = fs_out.get("X"), fs_out.get("Y")
    if X is None or Y is None:
        return None
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.utils import device_ledger as _ledger

    i_dev, v_dev, aux_dev = device_wire
    if wire.nibble:
        codes = _als._unpack_nibbles_host(wire.vw)
        v_lo, v_hi = int(codes.min()), int(codes.max())
    elif wire.vw.dtype == np.int8:
        v_lo, v_hi = int(wire.vw.min()), int(wire.vw.max())
    else:
        v_lo = v_hi = 0
    # the long-lived device placements below are the reviewed resident
    # sites the device-residency lint allowlists (tests/test_lint.py):
    # every buffer registers in the train-pack ledger entry right after
    entry.resident = ResidentPack(
        i_plane=i_dev,
        v_plane=v_dev,
        su=jnp.asarray(aux_dev["su"]),
        bu=jnp.asarray(aux_dev["bu"]),
        si=jnp.asarray(aux_dev["si"]),
        bi=jnp.asarray(aux_dev["bi"]),
        seg_rows_u=jnp.asarray(wire.geo_u.seg_rows),
        rem_u=jnp.asarray(wire.geo_u.rem),
        seg_rows_i=jnp.asarray(wire.geo_i.seg_rows),
        rem_i=jnp.asarray(wire.geo_i.rem),
        X=X, Y=Y,
        user_lam=factor_state[2], item_lam=factor_state[3],
        user_obs=factor_state[4], item_obs=factor_state[5],
        device=jax.devices()[0],
        device_label=_ledger.device_label_of(i_dev),
        plane_len=int(i_dev.shape[0]),
        n=int(wire.counts_u.sum()),
        v_lo=v_lo, v_hi=v_hi,
        config_key=_als.config_train_key(config),
    )
    pack = entry.resident
    label, nbytes, members = _ledger.device_footprint(
        *pack.device_arrays()
    )
    pack.ledger = _ledger.get_ledger().register(
        component="train-pack",
        nbytes=nbytes,
        device=label,
        anchor=pack,
        members=members,
    )
    with _PACK_CACHE_LOCK:
        entry.wire = dataclasses.replace(
            wire, iw=wire.iw[:0], vw=wire.vw[:0], aux={}, stripped=True
        )
    if entry.ledger is not None and not entry.ledger.closed:
        entry.ledger.set(entry.resident_bytes())
    _refresh_resident_gauge(pack.device_label)
    return pack


# --- incremental pack state ---


class _SideCodes:
    """Dense per-side row ids over the stream's SHARED code space.

    The stream's batches carry codes from one table-global dictionary
    (users and items mixed); each solve side needs its own dense 0..n-1
    id space. Dense ids are assigned in first-appearance order as
    batches fold in, and the shared code of each dense id is kept so the
    stream's post-scan ``names`` array resolves dense ids to id strings.
    """

    def __init__(self):
        self._dense_of = np.full(1024, -1, np.int64)
        self._code_chunks = []
        self.n = 0

    def fold(self, codes: np.ndarray) -> np.ndarray:
        codes = np.asarray(codes)
        if not len(codes):
            return np.empty(0, np.int32)
        hi = int(codes.max()) + 1
        if hi > len(self._dense_of):
            grown = np.full(max(hi, 2 * len(self._dense_of)), -1, np.int64)
            grown[: len(self._dense_of)] = self._dense_of
            self._dense_of = grown
        dense = self._dense_of[codes]
        miss = dense < 0
        if miss.any():
            new_codes = codes[miss]
            uniq, first = np.unique(new_codes, return_index=True)
            uniq = uniq[np.argsort(first, kind="stable")]  # appearance order
            self._dense_of[uniq] = np.arange(
                self.n, self.n + len(uniq), dtype=np.int64
            )
            self._code_chunks.append(uniq)
            self.n += len(uniq)
            dense = self._dense_of[codes]
        return dense.astype(np.int32)

    def codes(self) -> np.ndarray:
        """Shared code of each dense id (dense-id order)."""
        if not self._code_chunks:
            return np.empty(0, np.int64)
        return np.concatenate(self._code_chunks)


def _grow_add(acc: np.ndarray, add: np.ndarray) -> np.ndarray:
    if len(add) > len(acc):
        grown = np.zeros(len(add), np.int64)
        grown[: len(acc)] = acc
        acc = grown
    acc[: len(add)] += add
    return acc


def _scatter_merge(
    batches, n, n_users, n_items, geo_u,
    remap_u=None, remap_i=None,
):
    """Counting-sort merge of user-presorted COO batches into the final
    sentinel-padded item/value planes. Each batch must be sorted by its
    user ids; ``remap_u``/``remap_i`` optionally relabel per-batch ids
    into the final dense spaces (the relabeling must be injective and,
    for the sort to survive it, monotone — both the provisional→sorted
    relabel of the full scan and the old→merged relabel of the delta
    fold are). Scattering batch b's run of user u right after the runs
    batches 0..b-1 wrote reproduces EXACTLY the stable global argsort of
    the monolithic packer: per user, batches in scan order, original
    order within."""
    pad = (_als._bucket_count(n) - n) if n else 1
    iw = np.full(n + pad, n_items, np.int32)  # padding -> sentinel id
    vw = np.zeros(n + pad, np.float32)
    heads = geo_u.starts[:-1].copy()  # [n_users] int64 write heads
    for u, i, v in batches:
        m = len(u)
        if not m:
            continue
        idx = np.arange(m, dtype=np.int64)
        newgrp = np.empty(m, bool)
        newgrp[0] = True
        np.not_equal(u[1:], u[:-1], out=newgrp[1:])
        first = np.maximum.accumulate(np.where(newgrp, idx, 0))
        u_f = remap_u[u] if remap_u is not None else u
        pos = heads[u_f] + (idx - first)
        iw[pos] = remap_i[i] if remap_i is not None else i
        vw[pos] = v
        heads += np.bincount(u_f, minlength=n_users)
    return iw, vw


def _scan_worker(stream, q: "_queue.Queue", box: dict) -> None:
    """Drive the store scan, pushing batches through the bounded queue.
    Runs the generator ON THIS THREAD (the sqlite backend reads through
    per-thread WAL snapshot connections, so the scan never contends with
    the consumer); resolves ``stream.names`` here too, since it is only
    valid after exhaustion."""
    busy = 0.0
    try:
        it = iter(stream)
        while True:
            t0 = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                break
            busy += time.perf_counter() - t0
            q.put(batch)
        t0 = time.perf_counter()
        box["names"] = stream.names
        box["cursor"] = getattr(stream, "cursor", None)
        busy += time.perf_counter() - t0
    except BaseException as e:
        box["error"] = e
    finally:
        box["scan_s"] = busy
        box["done_at"] = time.perf_counter()
        q.put(None)


def _scan_and_pack(stream, config, timings: dict, queue_batches: int):
    """Consume a ColumnarStream into a HostWire + id indexes, folding
    each batch while the scan of the next runs on the producer thread.

    Returns ``(wire, user_index, item_index, compile_wait, cursor)`` or
    None for an empty scan (callers fall back to the materialized path,
    whose sanity check owns the user-facing error)."""
    q: "_queue.Queue" = _queue.Queue(maxsize=max(1, queue_batches))
    box: dict = {}
    th = threading.Thread(
        target=_scan_worker, args=(stream, q, box),
        daemon=True, name="als-stream-scan",
    )
    th.start()

    uspace, ispace = _SideCodes(), _SideCodes()
    counts_u = np.zeros(0, np.int64)
    counts_i = np.zeros(0, np.int64)
    batches = []
    n = 0
    fold_busy = 0.0
    while True:
        batch = q.get()
        if batch is None:
            break
        e_codes, t_codes, values = batch
        t0 = time.perf_counter()
        u = uspace.fold(e_codes)
        i = ispace.fold(t_codes)
        # stable presort by user NOW, under the scan of the next batch;
        # the merge below then only scatters — no global argsort on the
        # exposed critical path
        order = np.argsort(u, kind="stable")
        u, i = u[order], i[order]
        v = np.asarray(values, np.float32)[order]
        counts_u = _grow_add(counts_u, np.bincount(u, minlength=uspace.n))
        counts_i = _grow_add(counts_i, np.bincount(i, minlength=ispace.n))
        batches.append((u, i, v))
        n += len(v)
        fold_busy += time.perf_counter() - t0
    th.join()
    if "error" in box:
        raise box["error"]
    timings["scan_s"] = box.get("scan_s", 0.0)
    timings["fold_s"] = fold_busy
    if n == 0:
        return None
    t_scan_done = box["done_at"]

    # Final dense ids relabel the provisional (first-appearance) ids
    # into SORTED-NAME order — the order every monolithic scan
    # (presence-bitmap page remap, np.unique concat, BiMap.string_int)
    # assigns — so the wire below is byte-identical to the monolithic
    # packer's and the trained factors match it exactly, not just up to
    # a row permutation. The relabeling is catalog-sized, not
    # event-sized.
    names = box["names"]
    u_names = names[uspace.codes()]
    i_names = names[ispace.codes()]
    n_users, n_items = uspace.n, ispace.n
    perm_u = np.argsort(u_names)
    perm_i = np.argsort(i_names)
    remap_u = np.empty(n_users, np.int32)
    remap_u[perm_u] = np.arange(n_users, dtype=np.int32)
    remap_i = np.empty(n_items, np.int32)
    remap_i[perm_i] = np.arange(n_items, dtype=np.int32)
    counts_u32 = np.zeros(n_users, np.int64)
    counts_u32[: len(counts_u)] = counts_u
    counts_u32 = counts_u32[perm_u].astype(np.int32)
    counts_i32 = np.zeros(n_items, np.int64)
    counts_i32[: len(counts_i)] = counts_i
    counts_i32 = counts_i32[perm_i].astype(np.int32)
    L_u = _als.auto_segment_length(
        None, n_users, config.segment_length, counts=counts_u32
    )
    L_i = _als.auto_segment_length(
        None, n_items, config.segment_length, counts=counts_i32
    )
    geo_u = _als._segment_geometry(
        counts_u32, n_users, L_u, 1, config.chunk_slots
    )
    geo_i = _als._segment_geometry(
        counts_i32, n_items, L_i, 1, config.chunk_slots
    )
    # geometry known: compile starts NOW, under merge+narrow+transfer
    compile_wait = _als.start_compile_async(
        n_users, n_items, geo_u, geo_i, L_u, L_i, config
    )

    # Counting-sort merge (shared helper). Each batch is presorted by
    # PROVISIONAL user id; the provisional→sorted relabel is injective,
    # so equal-user runs stay contiguous and the within-batch occurrence
    # rank computed from the provisional grouping is also the rank under
    # final ids.
    iw, vw = _scatter_merge(
        batches, n, n_users, n_items, geo_u,
        remap_u=remap_u, remap_i=remap_i,
    )
    batches.clear()

    wire = _als.finish_wire(
        iw, vw, n_users, n_items, L_u, L_i, geo_u, geo_i,
        counts_u32, counts_i32,
    )
    user_index = BiMap(
        {str(nm): j for j, nm in enumerate(u_names[perm_u])}
    )
    item_index = BiMap(
        {str(nm): j for j, nm in enumerate(i_names[perm_i])}
    )
    now = time.perf_counter()
    # exposed = the tail the scan could not hide: late folds + geometry
    # + merge + narrow/nibble + index build
    timings["pack_exposed_s"] = max(0.0, now - t_scan_done)
    timings["pack_s"] = fold_busy + timings["pack_exposed_s"]
    return wire, user_index, item_index, compile_wait, box.get("cursor")


# --- delta fold (round 9) ---
#
# Retrain cost proportional to the delta: the storage layer scans ONLY
# the rows committed after the cached entry's cursor
# (LEvents.stream_columns_delta); here the cached wire losslessly
# inverts back to the old user-major COO (als.wire_coo), the delta's ids
# merge into the old sorted-name spaces (a monotone relabel, so the old
# batch stays user-sorted), and ONE counting-sort scatter re-finishes
# the wire — O(total events) of vectorized host work, no store rescan,
# no per-batch argsorts. The result is byte-identical to a cold full
# scan of the grown store, because per user the folded sequence (old
# wire order, then delta in scan order) IS the cold scan's sequence —
# the storage layer's cursor validation guarantees nothing already
# folded was deleted, reordered, or resealed out from under us, and
# falls back to the full repack otherwise.


def _names_of(index: BiMap) -> np.ndarray:
    """A BiMap's keys as a sorted object-str array (BiMaps here are
    always built from sorted name arrays, so iteration order is sorted
    order)."""
    out = np.empty(len(index), object)
    out[:] = [str(k) for k in index]
    return out


def _merge_sorted_names(old_names: np.ndarray, add_names: np.ndarray):
    """Merge ``add_names`` (sorted, disjoint from ``old_names``) into
    the sorted ``old_names``. Returns ``(merged, old_to_new)`` where
    ``old_to_new`` is the (monotone) relabel of old dense ids."""
    if not len(add_names):
        return old_names, np.arange(len(old_names), dtype=np.int64)
    old_pos = (
        np.arange(len(old_names), dtype=np.int64)
        + np.searchsorted(add_names, old_names)
    )
    new_pos = (
        np.arange(len(add_names), dtype=np.int64)
        + np.searchsorted(old_names, add_names)
    )
    merged = np.empty(len(old_names) + len(add_names), object)
    merged[old_pos] = old_names
    merged[new_pos] = add_names
    return merged, old_pos


def _side_fold_codes(codes: np.ndarray, names_arr, old_names: np.ndarray):
    """Fold one side's delta codes (in the DELTA stream's shared code
    space) into the cached side's sorted-name space, extending it with
    unseen names. Delta-sized work only. Returns
    ``(merged_names, old_to_new, dense_codes)``."""
    if not len(codes):
        return (
            old_names,
            np.arange(len(old_names), dtype=np.int64),
            codes.astype(np.int64),
        )
    uniq = np.unique(codes)  # distinct delta codes, ascending
    uniq_names = np.empty(len(uniq), object)
    uniq_names[:] = [str(x) for x in np.asarray(names_arr)[uniq]]
    if len(old_names):
        pos = np.minimum(
            np.searchsorted(old_names, uniq_names), len(old_names) - 1
        )
        is_old = old_names[pos] == uniq_names
    else:
        is_old = np.zeros(len(uniq_names), bool)
    add = np.sort(uniq_names[~is_old])  # distinct by construction
    merged, old_to_new = _merge_sorted_names(old_names, add)
    lut = np.zeros(int(uniq[-1]) + 1, np.int64)
    lut[uniq] = np.searchsorted(merged, uniq_names)
    return merged, old_to_new, lut[np.asarray(codes, np.int64)]


def _scan_delta(dstream, timings: dict) -> Optional[dict]:
    """Consume a delta stream into flat code/value arrays (shared by
    the host fold and the resident scatter arm). Returns None when the
    stream cannot vouch for its own chain (no cursor) — the caller
    falls back to the full repack."""
    t0 = time.perf_counter()
    parts = []
    n_delta = 0
    for e, g, v in dstream:
        parts.append(
            (
                np.asarray(e, np.int64),
                np.asarray(g, np.int64),
                np.asarray(v, np.float32),
            )
        )
        n_delta += len(v)
    new_cursor = dstream.cursor
    if new_cursor is None:
        return None
    timings["delta_scan_s"] = time.perf_counter() - t0
    if parts:
        e_codes = np.concatenate([p[0] for p in parts])
        g_codes = np.concatenate([p[1] for p in parts])
        dv = np.concatenate([p[2] for p in parts])
        names_arr = dstream.names
    else:
        e_codes = g_codes = np.empty(0, np.int64)
        dv = np.empty(0, np.float32)
        names_arr = None
    return {
        "e_codes": e_codes,
        "g_codes": g_codes,
        "dv": dv,
        "names": names_arr,
        "cursor": new_cursor,
        "fingerprint": dstream.fingerprint,
        "n_delta": n_delta,
    }


def _fold_delta(entry: _PackEntry, dstream, config, timings: dict):
    """Fold a delta stream into a cached pack entry: re-finished wire,
    merged id indexes, warm-start factor seeds, and the chained cursor.
    Returns None when the delta stream cannot vouch for its own chain
    (no cursor) — the caller falls back to the full repack.

    With residency enabled and a device pack on the entry, the delta is
    first offered to the on-device scatter arm; any condition it cannot
    scatter through demotes the pack (restoring the byte-identical host
    wire) and the host fold runs unchanged."""
    scanned = _scan_delta(dstream, timings)
    if scanned is None:
        return None
    if _RESIDENT_ENABLED and entry.resident is not None:
        folded = _fold_delta_resident(entry, scanned, config, timings)
        if folded is not None:
            return folded
    if entry.resident is not None:
        _demote_resident(entry)
        timings["resident"] = "fallback"
    return _fold_delta_host(entry, scanned, config, timings)


def _fold_delta_host(
    entry: _PackEntry, scanned: dict, config, timings: dict
):
    """The host fold (round 9): invert the cached wire to COO, merge
    the delta in, re-finish. Needs the entry's FULL host wire — a
    resident entry is demoted before this runs."""
    n_delta = scanned["n_delta"]
    new_cursor = scanned["cursor"]
    t0 = time.perf_counter()
    old_u_names = _names_of(entry.user_index)
    old_i_names = _names_of(entry.item_index)
    e_codes = scanned["e_codes"]
    g_codes = scanned["g_codes"]
    dv = scanned["dv"]
    names_arr = scanned["names"]
    u_names, u_old2new, du = _side_fold_codes(
        e_codes, names_arr, old_u_names
    )
    i_names, i_old2new, di = _side_fold_codes(
        g_codes, names_arr, old_i_names
    )
    n_users, n_items = len(u_names), len(i_names)

    old_wire = entry.wire
    counts_u = np.zeros(n_users, np.int64)
    counts_u[u_old2new] = old_wire.counts_u
    counts_u += np.bincount(du, minlength=n_users)
    counts_i = np.zeros(n_items, np.int64)
    counts_i[i_old2new] = old_wire.counts_i
    counts_i += np.bincount(di, minlength=n_items)
    counts_u32 = counts_u.astype(np.int32)
    counts_i32 = counts_i.astype(np.int32)

    L_u = _als.auto_segment_length(
        None, n_users, config.segment_length, counts=counts_u32
    )
    L_i = _als.auto_segment_length(
        None, n_items, config.segment_length, counts=counts_i32
    )
    geo_u = _als._segment_geometry(
        counts_u32, n_users, L_u, 1, config.chunk_slots
    )
    geo_i = _als._segment_geometry(
        counts_i32, n_items, L_i, 1, config.chunk_slots
    )
    # geometry known: compile starts NOW, under the merge + transfer
    compile_wait = _als.start_compile_async(
        n_users, n_items, geo_u, geo_i, L_u, L_i, config
    )

    # old COO straight off the cached wire (user-major, original
    # per-user order — exactly the cold scan's prefix), relabeled by the
    # MONOTONE old→merged LUT so it stays user-sorted; the delta gets
    # its own stable presort, preserving scan order within each user
    ou, oi, ov = _als.wire_coo(old_wire)
    ou = u_old2new[ou].astype(np.int64)
    oi = i_old2new[oi]
    order = np.argsort(du, kind="stable")
    n = len(ov) + n_delta
    iw, vw = _scatter_merge(
        [(ou, oi, ov), (du[order], di[order], dv[order])],
        n, n_users, n_items, geo_u,
    )
    wire = _als.finish_wire(
        iw, vw, n_users, n_items, L_u, L_i, geo_u, geo_i,
        counts_u32, counts_i32,
    )
    user_index = BiMap({str(nm): j for j, nm in enumerate(u_names)})
    item_index = BiMap({str(nm): j for j, nm in enumerate(i_names)})

    warm = None
    k = config.rank
    if (
        entry.arrays is not None
        and entry.arrays.user_factors.shape == (old_wire.n_users, k)
        and entry.arrays.item_factors.shape == (old_wire.n_items, k)
    ):
        # previous factors carry over row-by-row; new users solve from
        # the item side on the first half-sweep, new items get the same
        # fresh nonnegative init a cold train would give them
        X0 = np.zeros((n_users, k), np.float32)
        X0[u_old2new] = entry.arrays.user_factors
        Y0 = np.ascontiguousarray(
            _als._factor_init_host(n_users, n_items, config, 1)[1][
                :n_items
            ]
        )
        Y0[i_old2new] = entry.arrays.item_factors
        warm = _als.ALSModelArrays(user_factors=X0, item_factors=Y0)

    timings["fold_exposed_s"] = time.perf_counter() - t0
    return {
        "wire": wire,
        "user_index": user_index,
        "item_index": item_index,
        "compile_wait": compile_wait,
        "cursor": new_cursor,
        "fingerprint": scanned["fingerprint"],
        "warm": warm,
        "delta_events": n_delta,
    }


def _fold_delta_resident(
    entry: _PackEntry, scanned: dict, config, timings: dict
) -> Optional[dict]:
    """The on-device scatter arm of the delta fold. Host work here is
    delta-sized (id resolution, sort, shift prefix-sums come from
    catalog-sized bincounts); the only host→device traffic is the delta
    rows themselves plus the touched regularizer entries. Returns None
    whenever the scatter cannot reproduce the cold wire byte-for-byte —
    the caller demotes the pack and takes the host fold.

    Fallback triggers, each checked against what a cold re-finish of
    the grown store would produce: an unseen user/item id (the
    sorted-name relabel would reshuffle old rows), a value outside the
    pack's int8 half-step tier, a changed auto segment length, a row
    crossing a segment boundary or the segment grid re-bucketing
    (seg_rows/chunk mismatch), an item-id plane dtype flip, a
    training-semantics change (any ``config_train_key`` component:
    rank/reg/reg_mode, an implicit flip, an alpha retune, a solver or
    block-size change — the parked factors were trained under different
    semantics and must not warm-start the new ones), and a device
    change (caught by ``_resident_usable`` upstream)."""
    pack = entry.resident
    if not _resident_usable(pack) or pack.X is None or pack.Y is None:
        return None
    if pack.config_key != _als.config_train_key(config):
        return None
    old = entry.wire
    names_arr = scanned["names"]
    du = _resolve_existing(scanned["e_codes"], names_arr, entry.user_index)
    if du is None:
        return None
    di = _resolve_existing(scanned["g_codes"], names_arr, entry.item_index)
    if di is None:
        return None
    t0 = time.perf_counter()
    d = int(scanned["n_delta"])
    dv = scanned["dv"]
    n_users, n_items = old.n_users, old.n_items

    # value-tier stability: the merged plane must stay on the pack's
    # tier or the cold wire's value dtype would differ
    if old.v_scale == 0.5:
        doubled = dv * 2.0
        codes = np.rint(doubled)
        if d and (
            np.abs(doubled - codes).max() != 0.0
            or np.abs(codes).max() > 127
        ):
            return None
        d_codes = codes.astype(np.int8)
    else:
        d_codes = dv.astype(np.float32)

    counts_u = old.counts_u.astype(np.int64) + np.bincount(
        du, minlength=n_users
    )
    counts_i = old.counts_i.astype(np.int64) + np.bincount(
        di, minlength=n_items
    )
    counts_u32 = counts_u.astype(np.int32)
    counts_i32 = counts_i.astype(np.int32)
    n_new = pack.n + d
    L_u = _als.auto_segment_length(
        None, n_users, config.segment_length, counts=counts_u32
    )
    L_i = _als.auto_segment_length(
        None, n_items, config.segment_length, counts=counts_i32
    )
    if L_u != old.L_u or L_i != old.L_i:
        return None
    geo_u = _als._segment_geometry(
        counts_u32, n_users, L_u, 1, config.chunk_slots
    )
    geo_i = _als._segment_geometry(
        counts_i32, n_items, L_i, 1, config.chunk_slots
    )
    for g2, g1 in ((geo_u, old.geo_u), (geo_i, old.geo_i)):
        if (
            g2.n_chunks != g1.n_chunks
            or g2.sc != g1.sc
            or g2.total != g1.total
            or not np.array_equal(g2.seg_rows, g1.seg_rows)
        ):
            return None
    P_old = pack.plane_len
    P_new = _als._bucket_count(n_new)
    i_dtype = old.iw.dtype  # stripped planes keep their dtype
    top_id = n_items if P_new > n_new else n_items - 1
    if np.dtype(np.uint16 if top_id < 65536 else np.int32) != i_dtype:
        return None
    if d_codes.dtype == np.int8:
        v_lo = min(pack.v_lo, int(d_codes.min()) if d else pack.v_lo)
        v_hi = max(pack.v_hi, int(d_codes.max()) if d else pack.v_hi)
        nibble = P_new % 2 == 0 and v_lo >= 0 and v_hi <= 15
    else:
        v_lo = v_hi = 0
        nibble = False

    compile_wait = _als.start_compile_async(
        n_users, n_items, geo_u, geo_i, L_u, L_i, config
    )

    import jax
    import jax.numpy as jnp

    upload = 0
    weighted = config.reg_mode == "weighted"
    i3, v3 = pack.i_plane, pack.v_plane
    su2, si2 = pack.su, pack.si
    rem_u2, rem_i2 = pack.rem_u, pack.rem_i
    user_lam2, item_lam2 = pack.user_lam, pack.item_lam
    if d:
        order = np.argsort(du, kind="stable")
        du_s = du[order].astype(np.int32)
        di_s = di[order].astype(i_dtype)
        dc_s = d_codes[order]
        du_dev = jax.device_put(du_s)
        di_dev = jax.device_put(di_s)
        dv_dev = jax.device_put(dc_s)
        upload += du_s.nbytes + di_s.nbytes + dc_s.nbytes

        # per-row delta counts and their prefix shifts, on device from
        # the uploaded ids alone (+1 slot so padding rows gather 0)
        dense_u = jnp.zeros((n_users + 1,), jnp.int32).at[du_dev].add(1)
        dense_i = (
            jnp.zeros((n_items + 1,), jnp.int32)
            .at[di_dev.astype(jnp.int32)]
            .add(1)
        )
        sh_u = jnp.concatenate(
            [
                jnp.zeros((1,), jnp.int32),
                jnp.cumsum(dense_u[:n_users], dtype=jnp.int32),
            ]
        )
        sh_i = jnp.concatenate(
            [
                jnp.zeros((1,), jnp.int32),
                jnp.cumsum(dense_i[:n_items], dtype=jnp.int32),
            ]
        )

        # old planes → shifted slots: rebuild each slot's user key from
        # the resident CSR offsets (the _device_pack_presorted trick),
        # shift by how many delta rows land before that user, and move.
        # new_pos is strictly increasing; old padding slots carry
        # sentinel/zero and either rewrite identical values or drop.
        marks = (
            jnp.zeros((P_old + 1,), jnp.int32)
            .at[pack.su[1:]]
            .add(1, mode="drop")
        )
        keys = jnp.cumsum(marks[:P_old], dtype=jnp.int32)
        new_pos = jnp.arange(P_old, dtype=jnp.int32) + sh_u[keys]
        opts = dict(
            unique_indices=True, indices_are_sorted=True, mode="drop"
        )
        init_id = n_items if P_new > n_new else 0
        i2 = (
            jnp.full((P_new,), init_id, dtype=pack.i_plane.dtype)
            .at[new_pos]
            .set(pack.i_plane, **opts)
        )
        v2 = (
            jnp.zeros((P_new,), pack.v_plane.dtype)
            .at[new_pos]
            .set(pack.v_plane, **opts)
        )

        # delta rows append after each user's old run: occurrence rank
        # within the (user-sorted) delta + the user's new end offset
        idx = jnp.arange(d, dtype=jnp.int32)
        newgrp = jnp.concatenate(
            [jnp.ones((1,), bool), du_dev[1:] != du_dev[:-1]]
        )
        first = jax.lax.cummax(jnp.where(newgrp, idx, 0))
        d_pos = pack.su[du_dev + 1] + sh_u[du_dev] + (idx - first)
        i3 = i2.at[d_pos].set(di_dev, **opts)
        v3 = v2.at[d_pos].set(dv_dev, **opts)

        # CSR offsets shift by the per-user/item prefix counts (edge
        # padding rides the clip to the final total); segment bases are
        # unchanged (seg_rows equality above), and only each row's LAST
        # segment gains the row's delta count
        su2 = pack.su + sh_u[
            jnp.clip(
                jnp.arange(pack.su.shape[0], dtype=jnp.int32), 0, n_users
            )
        ]
        si2 = pack.si + sh_i[
            jnp.clip(
                jnp.arange(pack.si.shape[0], dtype=jnp.int32), 0, n_items
            )
        ]
        seg_idx_u = jnp.arange(pack.seg_rows_u.shape[0], dtype=jnp.int32)
        is_last_u = (seg_idx_u + 1) == pack.bu[pack.seg_rows_u + 1]
        rem_u2 = pack.rem_u + jnp.where(
            is_last_u, dense_u[pack.seg_rows_u], 0
        )
        seg_idx_i = jnp.arange(pack.seg_rows_i.shape[0], dtype=jnp.int32)
        is_last_i = (seg_idx_i + 1) == pack.bi[pack.seg_rows_i + 1]
        rem_i2 = pack.rem_i + jnp.where(
            is_last_i, dense_i[pack.seg_rows_i], 0
        )

        if weighted:
            # weighted regularization tracks counts: upload the
            # host-computed values at the touched rows (guaranteed
            # bit-equal to a cold _lam_obs_host; obs never changes —
            # touched rows already had observations)
            lam_u_full, _ = _als._lam_obs_host(
                counts_u32, n_users, pack.user_lam.shape[0], config
            )
            uniq_u = np.unique(du_s).astype(np.int32)
            vals_u = np.ascontiguousarray(lam_u_full[uniq_u])
            user_lam2 = pack.user_lam.at[jax.device_put(uniq_u)].set(
                jax.device_put(vals_u),
                unique_indices=True, indices_are_sorted=True,
            )
            lam_i_full, _ = _als._lam_obs_host(
                counts_i32, n_items, pack.item_lam.shape[0], config
            )
            uniq_i = np.unique(di_s.astype(np.int64)).astype(np.int32)
            vals_i = np.ascontiguousarray(lam_i_full[uniq_i])
            item_lam2 = pack.item_lam.at[jax.device_put(uniq_i)].set(
                jax.device_put(vals_i),
                unique_indices=True, indices_are_sorted=True,
            )
            upload += (
                uniq_u.nbytes + vals_u.nbytes
                + uniq_i.nbytes + vals_i.nbytes
            )

    new_meta = dataclasses.replace(
        old,
        geo_u=geo_u, geo_i=geo_i,
        counts_u=counts_u32, counts_i=counts_i32,
        iw=np.empty(0, i_dtype),
        vw=np.empty(0, np.uint8 if nibble else d_codes.dtype),
        nibble=nibble, aux={}, stripped=True,
    )
    pack.i_plane, pack.v_plane = i3, v3
    pack.su, pack.si = su2, si2
    pack.rem_u, pack.rem_i = rem_u2, rem_i2
    pack.user_lam, pack.item_lam = user_lam2, item_lam2
    pack.plane_len = P_new
    pack.n = n_new
    pack.v_lo, pack.v_hi = v_lo, v_hi
    if pack.ledger is not None and not pack.ledger.closed:
        pack.ledger.set(pack.device_bytes())
    _refresh_resident_gauge(pack.device_label)
    with _PACK_CACHE_LOCK:
        entry.wire = new_meta
        entry.fingerprint = scanned["fingerprint"]
        entry.cursor = scanned["cursor"]
    if entry.ledger is not None and not entry.ledger.closed:
        entry.ledger.set(entry.resident_bytes())

    timings["fold_exposed_s"] = time.perf_counter() - t0
    timings["resident"] = "scatter"
    timings["delta_upload_bytes"] = int(upload)
    return {
        "wire": new_meta,
        "user_index": entry.user_index,
        "item_index": entry.item_index,
        "compile_wait": compile_wait,
        "cursor": scanned["cursor"],
        "fingerprint": scanned["fingerprint"],
        "warm": None,
        "delta_events": d,
        "resident_pack": pack,
        "device_wire": (
            i3, v3, {"su": su2, "bu": pack.bu, "si": si2, "bi": pack.bi}
        ),
        "geo_dev": (pack.seg_rows_u, rem_u2, pack.seg_rows_i, rem_i2),
        "factor_state": (
            pack.X, pack.Y, user_lam2, item_lam2,
            pack.user_obs, pack.item_obs,
        ),
        "upload_bytes": int(upload),
    }


# --- transfer ---


def _ship_wire(wire: "_als.HostWire", n_chunks: int = 2) -> tuple:
    """Double-buffered wire transfer: the COO planes split into chunks
    whose async ``device_put``s pipeline, and each value chunk's
    device-side nibble unpack dispatches as soon as its bytes are
    enqueued — so transfer of chunk k+1 overlaps unpack of chunk k.
    Returns the ``(i_dev, v_dev, aux_dev)`` pre-shipped wire
    ``als.device_pack_from_wire`` consumes."""
    import jax
    import jax.numpy as jnp

    def parts(a: np.ndarray):
        if n_chunks <= 1 or len(a) < 2 * n_chunks:
            return [a]
        step = -(-len(a) // n_chunks)
        step += step % 2  # even boundary: value pairs stay byte-aligned
        return [a[s : s + step] for s in range(0, len(a), step)]

    dev_i = [jax.device_put(p) for p in parts(wire.iw)]
    dev_v = []
    for p in parts(wire.vw):
        d = jax.device_put(p)
        dev_v.append(_als._unpack_nibbles(d) if wire.nibble else d)
    i_dev = dev_i[0] if len(dev_i) == 1 else jnp.concatenate(dev_i)
    v_dev = dev_v[0] if len(dev_v) == 1 else jnp.concatenate(dev_v)
    aux_dev = jax.device_put(wire.aux)  # enqueued last: fences the queue
    return i_dev, v_dev, aux_dev


# --- the pipeline entry ---


@dataclasses.dataclass
class StreamTrainResult:
    arrays: "_als.ALSModelArrays"
    user_index: BiMap
    item_index: BiMap
    timings: dict


def _attribute_phases(timer, timings: dict) -> None:
    """Record the pipeline's sub-phases on the workflow PhaseTimer,
    marking the ones that ran UNDER another phase as overlapped so the
    run summary's wall-clock accounting stays honest."""
    add = getattr(timer, "add", None)
    if add is None:
        return
    for name, key, overlapped in (
        ("stream:scan", "scan_s", True),
        ("stream:fold", "fold_s", True),
        ("stream:delta-scan", "delta_scan_s", False),
        ("stream:delta-fold", "fold_exposed_s", False),
        ("stream:pack-exposed", "pack_exposed_s", False),
        ("stream:device-put-exposed", "device_put_exposed_s", False),
        ("stream:compile", "compile_s", True),
        ("stream:compile-exposed", "compile_exposed_s", False),
        ("stream:device-loop", "device_loop_s", False),
    ):
        if timings.get(key):
            add(name, timings[key], overlapped=overlapped)
    note = getattr(timer, "note", None)
    if note is None:
        return
    # the pack cache is not silent: this round's outcome, the lifetime
    # hit/miss/fold counters, and the delta size land in the summary
    if timings.get("pack_cache"):
        note("pack_cache", timings["pack_cache"])
    stats = pack_cache_stats()
    note(
        "pack_cache_stats",
        f"hit={stats['hit']} miss={stats['miss']} fold={stats['fold']}",
    )
    if "delta_events" in timings:
        note("delta_events", timings["delta_events"])
    if timings.get("resident"):
        # device-resident pack outcome (round 17): scatter / fallback /
        # cold — the continuous loop's RoundReport picks this up
        note("resident", timings["resident"])
    # convergence telemetry from the fused loop (ops/als.py): the sweep
    # count and the final factor-delta RMS are the round's convergence
    # headline; the full curve stays in timings["sweep_telemetry"] and
    # the registry histograms
    tel = timings.get("sweep_telemetry")
    if tel:
        note("sweeps", len(tel))
        note(
            "final_factor_delta",
            f"user={tel[-1]['dx']:.2e} item={tel[-1]['dy']:.2e}",
        )
        # implicit mode only: the HKV objective at the final sweep
        # (ops/als.py telemetry) — the training-loss headline the
        # continuous round line and RoundReport surface
        if "objective" in tel[-1]:
            note("objective", f"{tel[-1]['objective']:.6g}")


def train_als_streaming(
    stream,
    config: "_als.ALSConfig",
    *,
    timings: Optional[dict] = None,
    timer=None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 5,
    profile_dir: Optional[str] = None,
    queue_batches: int = 4,
    ship_chunks: int = 2,
    cache: bool = True,
    delta: bool = True,
    warm_sweeps: int = 2,
) -> Optional[StreamTrainResult]:
    """Train ALS from a ``ColumnarStream`` through the overlapped
    pipeline (module docstring). Returns None when ``stream`` is None or
    the scan is empty — callers fall back to the materialized
    ``train_als`` path and its error reporting.

    With ``delta`` (and ``cache``) on, a store that GREW since the
    cached round skips the full rescan: the delta fold (module comment
    above) re-finishes the cached wire from only the new rows, and
    training warm-starts from the previous round's factors with a
    ``warm_sweeps`` iteration budget (0 disables the reduced budget) —
    retrain cost proportional to the delta, not the store. Any change
    the storage cursor cannot vouch for (deletes, tombstones, bulk
    imports, resealing) falls back to the full repack automatically.

    ``timings`` gains the pipeline's phase split: ``scan_s``/``fold_s``/
    ``compile_s`` (busy, overlapped), ``pack_exposed_s``/
    ``device_put_exposed_s``/``compile_exposed_s`` (critical-path wall),
    ``pack_cache`` ("hit"/"miss"/"fold"/"off") with ``delta_events``/
    ``delta_scan_s``/``fold_exposed_s`` on fold rounds, plus the usual
    ``device_loop_s``/``padded_slots``/``wire_mb`` from the shared
    training tail.
    """
    if stream is None:
        return None
    timings = {} if timings is None else timings
    t_start = time.perf_counter()

    warm_arrays = None
    train_config = config
    cache_entry: Optional[_PackEntry] = None
    resident_round = False  # wire planes already live in HBM
    resident_pack: Optional[ResidentPack] = None
    resident_geo = None
    resident_wire_dev = None
    pre_factor_state = None  # scatter rounds: device-resident factors
    demoted = False  # a resident pack fell back to host this round
    entry = _cache_get(stream, config) if cache else None
    if entry is not None:
        _stat_bump("hit")
        timings["pack_cache"] = "hit"
        timings["scan_s"] = timings["fold_s"] = 0.0
        timings["pack_exposed_s"] = 0.0
        cache_entry = entry
        if entry.resident is not None:
            if _RESIDENT_ENABLED and _resident_usable(entry.resident):
                # zero-upload hit: planes + geometry stay resident; the
                # factor state is rebuilt fresh below, so the trained
                # result is the plain hit path's, bit for bit
                resident_round = True
                resident_pack = entry.resident
            else:
                _demote_resident(entry)
                demoted = True
        wire = entry.wire
        user_index, item_index = entry.user_index, entry.item_index
        compile_wait = _als.start_compile_async(
            wire.n_users, wire.n_items, wire.geo_u, wire.geo_i,
            wire.L_u, wire.L_i, config,
        )
        logger.info(
            "streaming ALS: pack cache HIT (%d users, %d items, %.1f MB "
            "wire%s) — skipping scan+pack", wire.n_users, wire.n_items,
            wire.wire_mb, ", device-resident" if resident_round else "",
        )
    else:
        folded = None
        prior = (
            _cache_lookup(stream, config, any_fingerprint=True)
            if cache
            else None
        )
        if delta and prior is not None and prior.cursor is not None:
            dfactory = getattr(stream, "delta_factory", None)
            if dfactory is not None:
                dstream = dfactory(prior.cursor)
                if dstream is not None:
                    folded = _fold_delta(prior, dstream, config, timings)
        if timings.get("resident") == "fallback":
            demoted = True
        if folded is not None:
            _stat_bump("fold")
            timings["pack_cache"] = "fold"
            timings["delta_events"] = folded["delta_events"]
            timings["scan_s"] = timings["fold_s"] = 0.0
            timings["pack_exposed_s"] = 0.0
            wire = folded["wire"]
            user_index = folded["user_index"]
            item_index = folded["item_index"]
            compile_wait = folded["compile_wait"]
            warm_arrays = folded["warm"]
            if "resident_pack" in folded:
                # the device arm already scattered the delta into the
                # resident planes and updated the entry in place — no
                # _cache_put (that would displace the entry and release
                # the very pack this round trains from)
                resident_round = True
                resident_pack = folded["resident_pack"]
                resident_wire_dev = folded["device_wire"]
                resident_geo = folded["geo_dev"]
                pre_factor_state = folded["factor_state"]
                cache_entry = prior
            else:
                cache_entry = _cache_put(
                    stream, config, wire, user_index, item_index,
                    fingerprint=folded["fingerprint"],
                    cursor=folded["cursor"],
                )
            if (
                (warm_arrays is not None or pre_factor_state is not None)
                and 0 < warm_sweeps < config.iterations
            ):
                # warm-started factors recover full quality in a few
                # sweeps after a small delta (ALX / GPU-MF, PAPERS.md);
                # the iteration count is a dynamic scalar, so the warm
                # executable is the cold one — no recompile
                train_config = dataclasses.replace(
                    config, iterations=warm_sweeps
                )
                timings["warm_sweeps"] = warm_sweeps
            logger.info(
                "streaming ALS: delta %s of %d events into cached "
                "wire (%d users, %d items) — skipping full rescan",
                "SCATTER" if resident_round else "FOLD",
                folded["delta_events"], wire.n_users, wire.n_items,
            )
        else:
            if prior is not None and prior.resident is not None:
                # the full repack replaces the entry: restore the host
                # wire and release the pack, so the train-pack ledger
                # reads zero on this fallback round even if the rescan
                # comes up empty
                _demote_resident(prior)
                demoted = True
            _stat_bump("miss" if cache else "off")
            timings["pack_cache"] = "miss" if cache else "off"
            packed = _scan_and_pack(stream, config, timings, queue_batches)
            if packed is None:
                return None
            wire, user_index, item_index, compile_wait, cursor = packed
            if cache:
                cache_entry = _cache_put(
                    stream, config, wire, user_index, item_index,
                    cursor=cursor,
                )

    from predictionio_tpu.utils import device_ledger as _ledger

    fs_out: Optional[dict] = (
        {}
        if (_RESIDENT_ENABLED and cache_entry is not None and not demoted)
        else None
    )
    staging = None
    if resident_round:
        # nothing store-sized crosses the link: planes, aux, and
        # geometry are already device-resident under the train-pack
        # ledger — no staging entry, no transfer fence
        pack = resident_pack
        if pre_factor_state is not None:
            device_wire = resident_wire_dev
            factor_state = pre_factor_state
        else:
            device_wire = (
                pack.i_plane, pack.v_plane,
                {"su": pack.su, "bu": pack.bu,
                 "si": pack.si, "bi": pack.bi},
            )
            resident_geo = (
                pack.seg_rows_u, pack.rem_u, pack.seg_rows_i, pack.rem_i
            )
            factor_state = _als.init_factor_state_single(
                wire.counts_u, wire.counts_i, wire.n_users, wire.n_items,
                train_config,
            )
            timings["delta_upload_bytes"] = int(
                factor_state[1].nbytes
                + sum(int(a.nbytes) for a in factor_state[2:])
            )
        timings["device_put_exposed_s"] = 0.0
    else:
        # ship (async) first, then factor-state init: the RNG + small
        # factor/regularizer puts run while the wire chunks are in flight
        device_wire = _ship_wire(wire, n_chunks=ship_chunks)
        # HBM residency ledger: the staged wire is device-resident from
        # ship until the device pack consumes it; the Anchor backstops an
        # exception path, the explicit close below the normal one
        _staging_anchor = _ledger.Anchor()
        _st_label, _st_bytes, _st_members = _ledger.device_footprint(
            device_wire[0], device_wire[1], *device_wire[2].values()
        )
        staging = _ledger.get_ledger().register(
            component="stream-staging",
            nbytes=_st_bytes,
            device=_st_label,
            anchor=_staging_anchor,
            members=_st_members,
        )
        factor_state = _als.init_factor_state_single(
            wire.counts_u, wire.counts_i, wire.n_users, wire.n_items,
            train_config,
            warm=(
                None
                if warm_arrays is None
                else (warm_arrays.user_factors, warm_arrays.item_factors)
            ),
        )
        timings["delta_upload_bytes"] = int(
            wire.iw.nbytes + wire.vw.nbytes
            + sum(int(a.nbytes) for a in wire.aux.values())
            + factor_state[1].nbytes
            + (factor_state[0].nbytes if warm_arrays is not None else 0)
            + sum(int(a.nbytes) for a in factor_state[2:])
        )
        t0 = time.perf_counter()
        # aux was enqueued last: fetching it (small) fences the serialized
        # transfer queue behind the COO chunks; the 1-element fence then
        # waits out the concat/unpack tail
        _als._sync_fetch(device_wire[2])
        _als._fence((device_wire[0], device_wire[1]))
        timings["device_put_exposed_s"] = time.perf_counter() - t0

    try:
        arrays = _als.train_from_wire(
            wire, train_config,
            device_wire=device_wire,
            timings=timings,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            profile_dir=profile_dir,
            compile_wait=compile_wait,
            factor_state=factor_state,
            geo_dev=resident_geo,
            factor_slots_out=fs_out,
            _fp_material=(
                (
                    lambda: repr(
                        (cache_entry.fingerprint, cache_entry.cursor)
                    ).encode()
                )
                if resident_round
                else None
            ),
        )
    except BaseException:
        if resident_round and cache_entry is not None:
            # the donated X/Y slots may be consumed mid-loop; the
            # planes are not — restore the host wire and release the
            # pack so a failed round never strands train-pack bytes
            if resident_pack is not None:
                resident_pack.X = resident_pack.Y = None
            if cache_entry.resident is not None:
                _demote_resident(cache_entry)
            with _PACK_CACHE_LOCK:
                cache_entry.arrays = None
        raise
    finally:
        if staging is not None:
            staging.close()
    if cache_entry is not None:
        # the trained factors ride the entry so the NEXT delta round can
        # warm-start; plain attribute store under the cache lock (the
        # entry may already have been evicted — harmless)
        with _PACK_CACHE_LOCK:
            cache_entry.arrays = arrays
        if cache_entry.ledger is not None and not cache_entry.ledger.closed:
            cache_entry.ledger.set(cache_entry.resident_bytes())
    if fs_out is not None and cache_entry is not None:
        if (
            resident_round
            and resident_pack is not None
            and resident_pack.valid
        ):
            if fs_out.get("X") is None or fs_out.get("Y") is None:
                # defensive: without the final slots the pack has no
                # factors for the next scatter — demote instead of
                # keeping consumed references alive
                resident_pack.X = resident_pack.Y = None
                _demote_resident(cache_entry)
            else:
                # the fused loop's final device X/Y round-trip back
                # into the pack (donation consumed the previous slots);
                # lam/obs follow so the next scatter reuses them
                resident_pack.X = fs_out["X"]
                resident_pack.Y = fs_out["Y"]
                resident_pack.user_lam = factor_state[2]
                resident_pack.item_lam = factor_state[3]
                resident_pack.user_obs = factor_state[4]
                resident_pack.item_obs = factor_state[5]
                resident_pack.config_key = _als.config_train_key(config)
                if (
                    resident_pack.ledger is not None
                    and not resident_pack.ledger.closed
                ):
                    resident_pack.ledger.set(resident_pack.device_bytes())
                _refresh_resident_gauge(resident_pack.device_label)
        elif (
            not resident_round
            and cache_entry.resident is None
            and not wire.stripped
        ):
            _establish_resident(
                cache_entry, wire, device_wire, factor_state, fs_out,
                config,
            )
    if _RESIDENT_ENABLED:
        outcome = timings.get("resident") or (
            "scatter" if resident_round
            else ("fallback" if demoted else "cold")
        )
        timings["resident"] = outcome
        _resident_rounds_counter().labels(outcome=outcome).inc()
    if "delta_upload_bytes" in timings:
        _delta_upload_gauge().set(float(timings["delta_upload_bytes"]))
    timings["stream_wall_s"] = time.perf_counter() - t_start
    if timer is not None:
        _attribute_phases(timer, timings)
    return StreamTrainResult(
        arrays=arrays, user_index=user_index, item_index=item_index,
        timings=timings,
    )
