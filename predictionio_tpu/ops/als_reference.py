"""Numpy reference of MLlib 1.3 ALS semantics — the parity oracle.

An independent, deliberately-slow implementation of the algorithm the
reference's recommendation templates call
(examples/scala-parallel-recommendation/custom-query/src/main/scala/
ALSAlgorithm.scala:66-73 -> org.apache.spark.mllib.recommendation.ALS):

* **Explicit** (``ALS.train``): alternating ridge solves where MLlib<=1.3
  scales the regularizer by the per-row observation count (the ALS-WR
  "weighted-lambda" scheme): ``A = Ys^T Ys + lambda * n_i * I``.
* **Implicit** (``ALS.trainImplicit``): Hu-Koren-Volinsky — confidence
  ``c = alpha * |r|`` (non-negative), preference ``p = 1(r > 0)``,
  ``A = Y^T Y + Ys^T diag(c) Ys + lambda_row * I``,
  ``b = Ys^T (p * (1 + c))``.
* **Init / update order**: item factors drawn as |N(0,1)|/sqrt(k)
  (MLlib's nonnegative-gaussian init), user phase solved first each
  iteration — matching ops/als.py so factor-level comparison is possible
  when both start from identical init.

This module exists so tests/test_mllib_parity.py and bench.py can assert
RMSE parity of the fused TPU kernel (ops/als.py) against the reference
semantics without Spark. Pure numpy; no jax imports — an oracle must not
share code with the thing it checks.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import numpy as np


def init_item_factors(n_items: int, rank: int, seed: int) -> np.ndarray:
    """MLlib-style nonnegative scaled-gaussian init (matches ops/als.py)."""
    rng = np.random.default_rng(seed)
    return (
        np.abs(rng.standard_normal((n_items, rank))) / math.sqrt(rank)
    ).astype(np.float64)


def _solve_side(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    prev: np.ndarray,
    Y: np.ndarray,
    reg: float,
    alpha: float,
    implicit: bool,
    weighted_reg: bool,
) -> np.ndarray:
    k = Y.shape[1]
    # rows with no observations keep their previous value — matching both
    # MLlib and the TPU kernel, which only scatter solved rows (an unrated
    # item stays at its random init; zeroing it would also corrupt the
    # shared Gramian in implicit mode)
    X = np.array(prev, np.float64)
    G = Y.T @ Y if implicit else None
    order = np.argsort(rows, kind="stable")
    rows_s, cols_s, vals_s = rows[order], cols[order], vals[order]
    boundaries = np.flatnonzero(np.diff(rows_s)) + 1
    for grp_cols, grp_vals, rid in zip(
        np.split(cols_s, boundaries),
        np.split(vals_s, boundaries),
        rows_s[np.concatenate([[0], boundaries])] if len(rows_s) else [],
    ):
        Ys = Y[grp_cols]
        n_obs = len(grp_vals)
        lam = reg * n_obs if weighted_reg else reg
        if implicit:
            c = alpha * np.abs(grp_vals)
            A = G + (Ys * c[:, None]).T @ Ys + lam * np.eye(k)
            b = Ys.T @ ((grp_vals > 0) * (1.0 + c))
        else:
            A = Ys.T @ Ys + lam * np.eye(k)
            b = Ys.T @ grp_vals
        X[rid] = np.linalg.solve(A, b)
    return X


def train_als_reference(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    ratings: np.ndarray,
    n_users: int,
    n_items: int,
    rank: int = 10,
    iterations: int = 10,
    reg: float = 0.01,
    alpha: float = 1.0,
    implicit_prefs: bool = False,
    reg_mode: str = "weighted",
    seed: int = 0,
    item_init: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Run the MLlib-semantics alternating solves; returns (X, Y) float64.

    ``reg_mode="weighted"`` scales lambda by the per-row observation count
    (MLlib<=1.3's ALS-WR scheme); ``"plain"`` uses unscaled lambda —
    mirroring ALSConfig.reg_mode so the oracle and the TPU kernel can be
    run under identical semantics.
    """
    u = np.asarray(user_idx, np.int64)
    i = np.asarray(item_idx, np.int64)
    r = np.asarray(ratings, np.float64)
    Y = (
        np.array(item_init, np.float64)
        if item_init is not None
        else init_item_factors(n_items, rank, seed)
    )
    X = np.zeros((n_users, rank), np.float64)
    weighted = reg_mode == "weighted"
    for _ in range(iterations):
        X = _solve_side(
            u, i, r, X, Y, reg, alpha, implicit_prefs, weighted
        )
        Y = _solve_side(
            i, u, r, Y, X, reg, alpha, implicit_prefs, weighted
        )
    return X, Y


def rmse_reference(
    X: np.ndarray, Y: np.ndarray, u: np.ndarray, i: np.ndarray, r: np.ndarray
) -> float:
    pred = np.sum(X[np.asarray(u, np.int64)] * Y[np.asarray(i, np.int64)], -1)
    err = pred - np.asarray(r, np.float64)
    return float(np.sqrt(np.mean(err * err)))
