"""Device-mesh and sharding utilities — the distributed substrate.

The reference's distributed substrate is Spark (RDD partitions + shuffles,
SURVEY.md §2.11); here it is a `jax.sharding.Mesh` with XLA collectives over
ICI/DCN. This package centralizes mesh construction and sharding helpers so
algorithms declare *what* is sharded and XLA decides the collectives.
"""

from predictionio_tpu.parallel.distributed import (
    initialize_distributed,
    is_multi_host,
)
from predictionio_tpu.parallel.mesh import (
    default_mesh,
    device_count,
    make_mesh,
    shard_batch,
)

__all__ = [
    "default_mesh",
    "device_count",
    "initialize_distributed",
    "is_multi_host",
    "make_mesh",
    "shard_batch",
]
