"""Mesh construction and batch-sharding helpers.

Where the reference creates one SparkContext per workflow run
(core/.../workflow/WorkflowContext.scala:26-45) and lets Spark place RDD
partitions, the TPU build creates one `jax.sharding.Mesh` per workflow run
and places device arrays with `NamedSharding`. Axis conventions:

- ``data``  — batch/data parallelism (users, events, queries)
- ``model`` — tensor/model parallelism (factor columns, vocabulary shards)

Single-device runs use a trivial 1-device mesh so all algorithm code is
written once against shard_map/pjit and degrades gracefully.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def device_count() -> int:
    return len(jax.devices())


def make_mesh(axes: Dict[str, int], devices: Optional[Sequence] = None) -> Mesh:
    """Build a mesh with named axes, e.g. {"data": 4, "model": 2}.

    The product of axis sizes must equal the device count used. Axis order
    follows dict order; put the fastest-communication axis last so it maps
    to adjacent devices (ICI neighbors on a TPU slice).
    """
    devs = list(devices) if devices is not None else jax.devices()
    sizes = list(axes.values())
    total = math.prod(sizes)
    if total != len(devs):
        raise ValueError(
            f"mesh axes {axes} require {total} devices, have {len(devs)}"
        )
    dev_array = np.array(devs).reshape(sizes)
    return Mesh(dev_array, tuple(axes.keys()))


def default_mesh(axis_name: str = "data", devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over all (or the given) devices."""
    devs = list(devices) if devices is not None else jax.devices()
    return make_mesh({axis_name: len(devs)}, devs)


def pad_to_multiple(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def shard_batch(mesh: Mesh, array, axis: str = "data", batch_dim: int = 0):
    """Pad an array's batch dim to the mesh axis size and place it sharded.

    Returns (sharded_array, original_length). Padding keeps shapes static —
    a divisible batch is what lets XLA tile onto the MXU without dynamic
    shapes.
    """
    arr = np.asarray(array)
    n = arr.shape[batch_dim]
    size = mesh.shape[axis]
    padded = pad_to_multiple(max(n, 1), size)
    if padded != n:
        pad_width = [(0, 0)] * arr.ndim
        pad_width[batch_dim] = (0, padded - n)
        arr = np.pad(arr, pad_width)
    spec = [None] * arr.ndim
    spec[batch_dim] = axis
    sharding = NamedSharding(mesh, P(*spec))
    return jax.device_put(arr, sharding), n
