"""Multi-host (multi-slice) initialization.

The reference's "distributed backend" is the Spark cluster runtime —
driver↔executor control plus shuffle-based data exchange (SURVEY.md §2.11).
The TPU-native equivalent has two layers:

- **within a slice**: XLA collectives over ICI, produced by the sharding
  annotations in `ops/` and `parallel/mesh.py` — nothing to initialize;
- **across hosts/slices**: JAX's single-controller-per-host model wired by
  ``jax.distributed.initialize`` over DCN. Every host runs the same
  program; ``jax.devices()`` then spans all hosts and meshes built from it
  shard globally, with XLA routing inter-slice collective traffic over DCN.

This image exposes one TPU chip, so multi-host paths here are exercised in
process-count=1 form plus the virtual-device CPU mesh tests; the entry
point is the standard one and takes the standard environment
(coordinator_address, num_processes, process_id) or auto-detects on
managed TPU pods.
"""

from __future__ import annotations

import logging
from typing import Optional

logger = logging.getLogger(__name__)

_initialized = False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    strict: bool = True,
) -> None:
    """Wire this host into the multi-host JAX runtime (idempotent).

    With no arguments, relies on the TPU pod metadata autodetection. Call
    before any other JAX API on every host of the pod/slice set.

    ``strict=True`` (the default) re-raises an initialization failure: a
    mis-wired coordinator on a real pod must abort the job, not silently
    degrade it to single-process training. Pass ``strict=False`` only for
    best-effort contexts (e.g. a CLI that also runs single-host) — the
    failure is still logged loudly.
    """
    global _initialized
    if _initialized:
        logger.info("jax.distributed already initialized; skipping")
        return
    import jax

    if num_processes is not None and num_processes > 1:
        # Multi-process on the CPU backend (CI rigs, local rehearsal of a
        # pod launch) needs an explicit cross-process collectives transport:
        # on jax 0.9.0 the coordination handshake succeeds without one, but
        # the global device view never aggregates past the local device and
        # collectives hang/fail. Gloo is the bundled implementation. The
        # flag is consulted only by the CPU backend, so set it whenever CPU
        # is a candidate platform (explicitly listed, or unset = autoselect,
        # which falls back to CPU) — on TPU the ICI/DCN transport is native
        # and the flag is inert.
        platforms = [
            p.strip().lower()
            for p in (jax.config.jax_platforms or "").split(",")
        ]
        if "cpu" in platforms or platforms == [""]:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")

    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        logger.error(
            "jax.distributed.initialize failed (%s); the runtime would run "
            "with %d process(es). Call initialize_distributed before any "
            "other JAX usage on every host.",
            e,
            jax.process_count(),
        )
        if strict:
            raise
    _initialized = True
    logger.info(
        "distributed runtime up: process %d/%d, %d local / %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )


def is_multi_host() -> bool:
    import jax

    return jax.process_count() > 1
